"""Incremental range-sweep view builder — delta-applied snapshots.

The reference re-runs the full per-timestamp handshake for every hop of a
Range query (``Tasks/RangeTasks/RangeAnalysisTask.scala:18-35`` — fresh
``TimeCheck``/``Setup`` per timestamp) and our ``build_view`` likewise
re-folds the whole event log per hop. For an ascending sweep T0 < T1 < ...
over a pinned log that is wasteful: the fold state at T_{i+1} differs from
T_i only by the events with time in (T_i, T_{i+1}].

``SweepBuilder`` keeps the running fold state and applies each hop's delta:

* a fixed dense vertex dictionary is built once from the whole pinned log,
  so vertex fold state lives in flat dense arrays (O(delta) updates, no
  merging), and an edge (s, d) packs into ONE int64 key
  ``dense_s << 32 | dense_d`` — every edge-state merge is a single-key
  searchsorted, and the delta fold runs the native single-key kernel.
* cross-entity tombstones (vertex delete ⇒ incident-edge dead marks,
  ``Edge.killList`` semantics, ``Edge.scala:36-44``) are generated
  incrementally: delta deletes join against all pairs known so far (both
  src- and dst-sorted key arrays are maintained), and pairs first seen in
  this delta join against the full delete history — reproducing exactly the
  all-pairs × all-deletes join of ``build_view``.

Each ``view_at(T)`` emits a ``GraphView`` bit-identical to
``build_view(log, T)`` (tested in ``tests/test_sweep.py``).
"""

from __future__ import annotations

import numpy as np

from .events import EDGE_ADD, EDGE_DELETE, VERTEX_ADD, VERTEX_DELETE, EventLog
from .snapshot import (
    INT64_MIN,
    GraphView,
    _assemble_view,
    _expand_ranges,
    _fold_latest,
    build_view,
)

_ENC_SHIFT = 32
_ENC_MASK = (1 << _ENC_SHIFT) - 1

_VFOLD_POOL = None


def _vfold_pool():
    """Process-wide worker pool for the overlapped vertex folds — shared
    so long-lived servers don't pin one idle thread per SweepBuilder."""
    global _VFOLD_POOL
    if _VFOLD_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        _VFOLD_POOL = ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="sweep-vfold")
    return _VFOLD_POOL


_PREFETCH_POOL = None


def _prefetch_pool():
    """Process-wide single worker for hop-lookahead prefetchers
    (``engine/device_sweep.run_sweep``, ``engine/hopbatch._run_chunks``):
    hop *i+1*'s host fold + delta staging runs here while hop *i*'s
    compiled superstep runs on device — the fold → stage → ship → compute
    pipeline. SINGLE worker by design: a fold mutates shared SweepBuilder
    state, so at most one may be in flight. Deliberately separate from
    ``_vfold_pool`` — the fold task BLOCKS on its inner vertex fold, and
    sharing a pool would let it occupy the very worker that inner task
    needs (classic nested-submit deadlock)."""
    global _PREFETCH_POOL
    if _PREFETCH_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        _PREFETCH_POOL = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="sweep-prefetch")
    return _PREFETCH_POOL


def prefetch_map(fold_fns, body) -> None:
    """Drive ``fold_fns`` (zero-arg callables) through the prefetch worker
    with one-deep lookahead, calling ``body(payload, stall_seconds)`` for
    each fold's result while the NEXT fold already runs in the worker —
    the body (ship + device dispatch) overlaps the following fold.
    ``stall_seconds`` is how long the driver actually WAITED on the fold
    (0 = it hid entirely behind the previous body). If a fold or a body
    raises, the in-flight fold is drained SYNCHRONOUSLY before the
    exception propagates — folds mutate shared sweep state, and the
    caller's error handler must not reset that state under a
    still-running fold. The single concurrency-pattern copy both sweep
    engines pipeline through (a generator can't give this guarantee: its
    finally would only drain at finalisation, which the propagating
    traceback's frame references delay past the caller's handler)."""
    import time as _t

    fns = list(fold_fns)
    if not fns:
        return
    pool = _prefetch_pool()
    fut = pool.submit(fns[0])
    try:
        for i in range(len(fns)):
            t0 = _t.perf_counter()
            payload = fut.result()
            stall = _t.perf_counter() - t0
            fut = pool.submit(fns[i + 1]) if i + 1 < len(fns) else None
            body(payload, stall)
    except BaseException:
        if fut is not None:   # let the in-flight fold finish first
            fut.exception()
        raise

_EMPTY_DELTA = {
    "v_idx": np.empty(0, np.int64), "v_lat": np.empty(0, np.int64),
    "v_alive": np.empty(0, bool), "v_first": np.empty(0, np.int64),
    "e_enc": np.empty(0, np.int64), "e_lat": np.empty(0, np.int64),
    "e_alive": np.empty(0, bool), "e_first": np.empty(0, np.int64),
}


class SweepBuilder:
    """Build views at ascending timestamps over a pinned log, incrementally.

    For out-of-order `view_at` times, or once the dense dictionary would
    overflow the 32-bit pack, it falls back to full ``build_view`` per call.
    """

    def __init__(self, log: EventLog, *, include_occurrences: bool = False,
                 pad: str = "pow2", track_rows: bool = True,
                 preseed_pairs: bool = False):
        if include_occurrences and not track_rows:
            raise ValueError("occurrence views need the add-row lists")
        self.log = log.pin()
        self.include_occurrences = include_occurrences
        self.pad = pad
        self.track_rows = track_rows
        self._t = self.log.column("time")
        self._k = self.log.column("kind")
        self._s = self.log.column("src")
        self._d = self.log.column("dst")
        # dense dictionary over every vertex id the log ever mentions. dst is
        # only a vertex id on edge events — vertex events carry a -1 sentinel
        # there, and REAL ids can be negative (assign_id hashes to signed
        # int64), so select by kind, never by sign.
        is_e = (self._k == EDGE_ADD) | (self._k == EDGE_DELETE)
        d_real = self._d[is_e]
        self.uv = np.unique(np.concatenate([self._s, d_real])) \
            if len(self._s) else np.empty(0, np.int64)
        self._ok = len(self.uv) < (1 << 31)
        # per-row dense ids, computed ONCE: per-hop _advance slices these
        # instead of re-running searchsorted over the dictionary for every
        # delta (the dominant host cost of a columnar sweep). Skipped above
        # 2^23 events, where the 16B/event would hurt more than it helps.
        if self._ok and 0 < len(self._s) <= (1 << 23):
            self._sd_all = np.searchsorted(self.uv, self._s)
            self._dd_all = np.zeros(len(self._d), np.int64)
            self._dd_all[is_e] = np.searchsorted(self.uv, d_real)
        else:
            self._sd_all = self._dd_all = None
        nv = len(self.uv)
        # dense vertex fold state
        self.v_lat = np.full(nv, INT64_MIN, np.int64)
        self.v_alive = np.zeros(nv, bool)
        self.v_first = np.full(nv, INT64_MIN, np.int64)
        self.v_seen = np.zeros(nv, bool)
        # edge fold state keyed by packed (dense_s, dense_d); enc-sorted
        self.e_enc = np.empty(0, np.int64)
        self.e_lat = np.empty(0, np.int64)
        self.e_alive = np.empty(0, bool)
        self.e_first = np.empty(0, np.int64)
        # the same pair keys packed (dense_d, dense_s), kept sorted — the
        # dst-incidence index for tombstone joins
        self.e_enc_dst = np.empty(0, np.int64)
        # preseed: start the pair table with EVERY pair the log ever
        # mentions (alive=False, times at the sentinel). No pair is ever
        # "fresh" afterwards, so the per-hop sorted inserts and the
        # history-vs-new-pair joins vanish; the per-hop incident join over
        # all pairs generates exactly build_view's all-pairs × all-deletes
        # killList marks (a dead mark before a pair's first add loses to
        # the later add in the latest-wins fold — same outcome as the
        # historical join it replaces). The columnar engines opt in;
        # semantics stay bit-identical (tested against build_view).
        self.e_seen = np.empty(0, bool)   # pair has real marks (firsts set)
        self._preseeded = False
        if preseed_pairs and self._ok and is_e.any():
            sd_e = np.searchsorted(self.uv, self._s[is_e]) \
                if self._sd_all is None else self._sd_all[is_e]
            dd_e = np.searchsorted(self.uv, d_real) \
                if self._dd_all is None else self._dd_all[is_e]
            enc_all = np.unique(self._pack(sd_e, dd_e))
            self.e_enc = enc_all
            self.e_lat = np.full(len(enc_all), INT64_MIN, np.int64)
            self.e_alive = np.zeros(len(enc_all), bool)
            self.e_first = np.full(len(enc_all), INT64_MIN, np.int64)
            self.e_seen = np.zeros(len(enc_all), bool)
            self.e_enc_dst = np.sort(
                ((enc_all & _ENC_MASK) << _ENC_SHIFT)
                | (enc_all >> _ENC_SHIFT))
            self._preseeded = True
        # delete history: (dense vertex, time), sorted by vertex
        self.dh_v = np.empty(0, np.int64)
        self.dh_t = np.empty(0, np.int64)
        # in-time add-event row lists (property joins), ascending, grown
        # per delta — deltas are selected by event TIME, so their row
        # indices interleave with earlier hops' and need a sorted merge
        self._ea_rows = np.empty(0, np.int64)
        self._va_rows = np.empty(0, np.int64)
        self.t_prev: int | None = None
        # per-hop row selection: binary search when the log is time-sorted
        # (bulk loads, replayed dumps), O(N) boolean scan otherwise
        self._t_sorted = bool(
            len(self._t) == 0 or bool((self._t[:-1] <= self._t[1:]).all()))
        # last hop's touched-entity delta (dense vertex indices + packed edge
        # keys with their POST-update fold state) — consumed by the
        # device-resident sweep engine (engine/device_sweep.py), which ships
        # only these O(delta) rows to the chip instead of fresh O(m) arrays
        self.last_delta: dict | None = None

    # ---- helpers ----

    def _dense(self, ids: np.ndarray) -> np.ndarray:
        return np.searchsorted(self.uv, ids)

    def _pack(self, ds: np.ndarray, dd: np.ndarray) -> np.ndarray:
        return (ds << _ENC_SHIFT) | dd

    def _incident(self, enc_sorted: np.ndarray, dv: np.ndarray, dt: np.ndarray,
                  flip: bool):
        """Dead marks (enc, t) for pairs in `enc_sorted` whose FIRST packed
        component is in dv. flip=True means enc_sorted is (d, s)-packed and
        results are re-packed as (s, d)."""
        lo = np.searchsorted(enc_sorted, dv << _ENC_SHIFT, side="left")
        hi = np.searchsorted(enc_sorted, (dv + 1) << _ENC_SHIFT, side="left")
        rows, qidx = _expand_ranges(lo, hi)
        enc = enc_sorted[rows]
        if flip:
            enc = ((enc & _ENC_MASK) << _ENC_SHIFT) | (enc >> _ENC_SHIFT)
        return enc, dt[qidx]

    # ---- the sweep ----

    def view_at(self, time: int) -> GraphView:
        time = int(time)
        if not self._ok or (self.t_prev is not None and time < self.t_prev):
            return build_view(self.log, time,
                              include_occurrences=self.include_occurrences,
                              pad=self.pad)
        if self.t_prev is None or time > self.t_prev:
            self._advance(time)
        return self._emit(time)

    def _advance(self, time: int) -> None:
        t_prev = self.t_prev if self.t_prev is not None else np.iinfo(np.int64).min
        if self._t_sorted:
            lo = 0 if t_prev == np.iinfo(np.int64).min \
                else int(np.searchsorted(self._t, t_prev, side="right"))
            hi = int(np.searchsorted(self._t, time, side="right"))
            rows = np.arange(lo, hi)
        else:
            sel = (self._t <= time) if t_prev == np.iinfo(np.int64).min \
                else ((self._t > t_prev) & (self._t <= time))
            rows = np.flatnonzero(sel)
        self.t_prev = time
        if len(rows) == 0:
            self.last_delta = _EMPTY_DELTA
            return
        t = self._t[rows]
        k = self._k[rows]
        s = self._s[rows]
        d = self._d[rows]
        is_va = k == VERTEX_ADD
        is_vd = k == VERTEX_DELETE
        is_ea = k == EDGE_ADD
        is_ed = k == EDGE_DELETE
        uvd = uenc = None  # touched entities, recorded into last_delta below

        if self.track_rows:
            new_ea = rows[is_ea]
            new_va = rows[is_va]
            self._ea_rows = np.insert(
                self._ea_rows, np.searchsorted(self._ea_rows, new_ea), new_ea)
            self._va_rows = np.insert(
                self._va_rows, np.searchsorted(self._va_rows, new_va), new_va)

        if self._sd_all is not None:
            sd, dd = self._sd_all[rows], self._dd_all[rows]
            ds_ea, dd_ea = sd[is_ea], dd[is_ea]
            dv_del = sd[is_vd]
            dv_add = sd[is_va]
            ds_ed, dd_ed = sd[is_ed], dd[is_ed]
        else:
            ds_ea = self._dense(s[is_ea])
            dd_ea = self._dense(d[is_ea])
            dv_del = self._dense(s[is_vd])
            dv_add = self._dense(s[is_va])
            ds_ed = self._dense(s[is_ed])
            dd_ed = self._dense(d[is_ed])
        t_del = t[is_vd]

        # -- vertex delta fold: adds + edge-endpoint revivals vs deletes --
        # runs in a worker thread OVERLAPPED with the edge-side marks+fold
        # below (independent state; ctypes/numpy release the GIL): the two
        # folds are the per-hop host cost of a columnar sweep
        v_ids = np.concatenate([dv_add, ds_ea, dd_ea, dv_del])
        v_t = np.concatenate([t[is_va], t[is_ea], t[is_ea], t_del])
        v_al = np.zeros(len(v_ids), bool)
        v_al[: len(v_ids) - len(dv_del)] = True

        def _vertex_fold():
            if not len(v_ids):
                return None
            (uvd0,), dlat, dalive, dfirst = _fold_latest((v_ids,), v_t, v_al)
            # delta times are strictly later than any prior mark, so the
            # delta's latest wins outright and firsts only fill unseen slots
            self.v_lat[uvd0] = dlat
            self.v_alive[uvd0] = dalive
            self.v_first[uvd0] = np.where(self.v_seen[uvd0],
                                          self.v_first[uvd0], dfirst)
            self.v_seen[uvd0] = True
            return uvd0

        v_fut = _vfold_pool().submit(_vertex_fold)

        # -- edge delta marks: own add/delete events --
        enc_ea = self._pack(ds_ea, dd_ea)
        enc_ed = self._pack(ds_ed, dd_ed)
        marks_enc = [enc_ea, enc_ed]
        marks_t = [t[is_ea], t[is_ed]]
        marks_a = [np.ones(len(enc_ea), bool), np.zeros(len(enc_ed), bool)]

        delta_enc = np.unique(np.concatenate([enc_ea, enc_ed])) \
            if (len(enc_ea) or len(enc_ed)) else np.empty(0, np.int64)
        if self._preseeded:
            new_enc = delta_enc[:0]   # every pair is in the table already
        else:
            pos = np.searchsorted(self.e_enc, delta_enc)
            pos_c = np.clip(pos, 0, max(len(self.e_enc) - 1, 0))
            known = (self.e_enc[pos_c] == delta_enc) if len(self.e_enc) \
                else np.zeros(len(delta_enc), bool)
            new_enc = delta_enc[~known]

        if len(dv_del):
            # delta deletes × (pairs known before this hop ∪ NEW delta pairs)
            for enc_arr, flip in ((self.e_enc, False), (self.e_enc_dst, True)):
                enc_ts, t_ts = self._incident(enc_arr, dv_del, t_del, flip)
                marks_enc.append(enc_ts)
                marks_t.append(t_ts)
                marks_a.append(np.zeros(len(enc_ts), bool))
            new_by_dst = np.sort(
                ((new_enc & _ENC_MASK) << _ENC_SHIFT) | (new_enc >> _ENC_SHIFT))
            for enc_arr, flip in ((new_enc, False), (new_by_dst, True)):
                enc_ts, t_ts = self._incident(enc_arr, dv_del, t_del, flip)
                marks_enc.append(enc_ts)
                marks_t.append(t_ts)
                marks_a.append(np.zeros(len(enc_ts), bool))

        if len(new_enc) and len(self.dh_v):
            # historical deletes × pairs first seen in this delta
            ns = new_enc >> _ENC_SHIFT
            nd = new_enc & _ENC_MASK
            for comp in (ns, nd):
                lo = np.searchsorted(self.dh_v, comp, side="left")
                hi = np.searchsorted(self.dh_v, comp, side="right")
                hrows, qidx = _expand_ranges(lo, hi)
                marks_enc.append(new_enc[qidx])
                marks_t.append(self.dh_t[hrows])
                marks_a.append(np.zeros(len(hrows), bool))

        all_enc = np.concatenate(marks_enc)
        epos_known = None
        if len(all_enc):
            all_t = np.concatenate(marks_t)
            all_a = np.concatenate(marks_a)
            (uenc,), elat_d, ealive_d, efirst_d = _fold_latest((all_enc,), all_t, all_a)
            upos = np.searchsorted(self.e_enc, uenc)
            upos_c = np.clip(upos, 0, max(len(self.e_enc) - 1, 0))
            uknown = (self.e_enc[upos_c] == uenc) if len(self.e_enc) \
                else np.zeros(len(uenc), bool)
            # existing pairs: delta marks are strictly later — overwrite
            # (firsts only fill slots that never saw a real mark — preseeded
            # pairs exist in the table before their first event)
            kpos = upos_c[uknown]
            self.e_lat[kpos] = elat_d[uknown]
            self.e_alive[kpos] = ealive_d[uknown]
            self.e_first[kpos] = np.where(self.e_seen[kpos],
                                          self.e_first[kpos],
                                          efirst_d[uknown])
            self.e_seen[kpos] = True
            # new pairs: insert (fold already merged their full history,
            # including historical tombstones, so firsts are exact)
            fresh = ~uknown
            if not fresh.any():
                # positions are final (no inserts shifted them): last_delta
                # reuses them instead of re-searching the whole table
                epos_known = upos_c
            if fresh.any():
                at = upos[fresh]
                self.e_enc = np.insert(self.e_enc, at, uenc[fresh])
                self.e_lat = np.insert(self.e_lat, at, elat_d[fresh])
                self.e_alive = np.insert(self.e_alive, at, ealive_d[fresh])
                self.e_first = np.insert(self.e_first, at, efirst_d[fresh])
                self.e_seen = np.insert(self.e_seen, at,
                                        np.ones(fresh.sum(), bool))
                enc2 = (((uenc[fresh] & _ENC_MASK) << _ENC_SHIFT)
                        | (uenc[fresh] >> _ENC_SHIFT))
                enc2 = np.sort(enc2)
                self.e_enc_dst = np.insert(
                    self.e_enc_dst, np.searchsorted(self.e_enc_dst, enc2), enc2)

        if len(dv_del) and not self._preseeded:
            # the delete history only feeds the new-pair join, which a
            # preseeded table never takes (no pair is ever new)
            self.dh_v = np.concatenate([self.dh_v, dv_del])
            self.dh_t = np.concatenate([self.dh_t, t_del])
            order = np.argsort(self.dh_v, kind="stable")
            self.dh_v = self.dh_v[order]
            self.dh_t = self.dh_t[order]

        uvd = v_fut.result()   # join the overlapped vertex fold

        # Touched-entity delta with POST-update fold state, read back from the
        # running arrays so it is correct no matter which code path (known
        # pair overwrite / fresh insert / tombstone join) produced the value.
        tv = uvd if uvd is not None else np.empty(0, np.int64)
        te = uenc if uenc is not None else np.empty(0, np.int64)
        epos = epos_known if epos_known is not None \
            else np.searchsorted(self.e_enc, te)
        self.last_delta = {
            "v_idx": tv, "v_lat": self.v_lat[tv],
            "v_alive": self.v_alive[tv], "v_first": self.v_first[tv],
            "e_enc": te, "e_lat": self.e_lat[epos],
            "e_alive": self.e_alive[epos], "e_first": self.e_first[epos],
        }

    def _emit(self, time: int) -> GraphView:
        if not self.track_rows:
            raise RuntimeError(
                "this SweepBuilder was built with track_rows=False (fold "
                "state only — the columnar/device engines); use a default "
                "one to emit GraphViews")
        act_dense = np.flatnonzero(self.v_alive)
        act_vids = self.uv[act_dense]  # uv ascending ⇒ dense order = id order
        act_latest = self.v_lat[act_dense]
        act_first = self.v_first[act_dense]

        alive = self.e_alive
        enc = self.e_enc[alive]
        ae_s = self.uv[enc >> _ENC_SHIFT]
        ae_d = self.uv[enc & _ENC_MASK]
        ae_latest = self.e_lat[alive]
        ae_first = self.e_first[alive]
        # local endpoint indices via the dense→local LUT (enc order is
        # (src, dst)-major, so one argsort of the flipped packing gives the
        # (dst, src) order _assemble_view needs)
        lut = np.full(len(self.uv), -1, np.int32)
        lut[act_dense] = np.arange(len(act_dense), dtype=np.int32)
        src_loc = lut[enc >> _ENC_SHIFT]
        dst_loc = lut[enc & _ENC_MASK]
        eorder = np.argsort(
            (dst_loc.astype(np.int64) << _ENC_SHIFT) | src_loc, kind="stable")
        locs = (src_loc, dst_loc, eorder)

        eadd_rows = self._ea_rows
        vadd_rows = self._va_rows
        occ = None
        if self.include_occurrences:
            occ = (eadd_rows, self._t[eadd_rows],
                   self._s[eadd_rows], self._d[eadd_rows])
        return _assemble_view(
            self.log, time, act_vids, act_latest, act_first,
            ae_s, ae_d, ae_latest, ae_first, self.pad,
            eadd_rows, vadd_rows, occ, locs,
        )
