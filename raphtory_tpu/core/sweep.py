"""Incremental range-sweep view builder — delta-applied snapshots.

The reference re-runs the full per-timestamp handshake for every hop of a
Range query (``Tasks/RangeTasks/RangeAnalysisTask.scala:18-35`` — fresh
``TimeCheck``/``Setup`` per timestamp) and our ``build_view`` likewise
re-folds the whole event log per hop. For an ascending sweep T0 < T1 < ...
over a pinned log that is wasteful: the fold state at T_{i+1} differs from
T_i only by the events with time in (T_i, T_{i+1}].

``SweepBuilder`` keeps the running fold state and applies each hop's delta:

* a fixed dense vertex dictionary is built once from the whole pinned log,
  so vertex fold state lives in flat dense arrays (O(delta) updates, no
  merging), and an edge (s, d) packs into ONE int64 key
  ``dense_s << 32 | dense_d`` — every edge-state merge is a single-key
  searchsorted, and the delta fold runs the native single-key kernel.
* cross-entity tombstones (vertex delete ⇒ incident-edge dead marks,
  ``Edge.killList`` semantics, ``Edge.scala:36-44``) are generated
  incrementally: delta deletes join against all pairs known so far (both
  src- and dst-sorted key arrays are maintained), and pairs first seen in
  this delta join against the full delete history — reproducing exactly the
  all-pairs × all-deletes join of ``build_view``.

Each ``view_at(T)`` emits a ``GraphView`` bit-identical to
``build_view(log, T)`` (tested in ``tests/test_sweep.py``).
"""

from __future__ import annotations

import os
import threading

import numpy as np

from ..analysis.sanitizer import (note_shared as _san_note,
                                  track_shared as _san_track)
from .events import EDGE_ADD, EDGE_DELETE, VERTEX_ADD, VERTEX_DELETE, EventLog
from .snapshot import (
    INT64_MIN,
    GraphView,
    _assemble_view,
    _expand_ranges,
    _fold_latest,
    build_view,
)

_ENC_SHIFT = 32
_ENC_MASK = (1 << _ENC_SHIFT) - 1


def fold_workers() -> int:
    """Size of the chunk-fold worker pool (``RTPU_FOLD_WORKERS``). The
    default scales with the host — half the cores, capped at 8 — because
    fold workers compete with the XLA CPU backend for the same cores;
    ``1`` degrades every parallel-fold path to the serial pipeline."""
    v = os.environ.get("RTPU_FOLD_WORKERS")
    if v is not None:
        return max(1, int(v))
    return max(1, min(8, (os.cpu_count() or 2) // 2 + 1))


_VFOLD_POOLS: dict = {}
_VFOLD_POOLS_LOCK = threading.Lock()


def _vfold_pool():
    """Process-wide worker pool for the overlapped vertex folds — shared
    so long-lived servers don't pin one idle thread per SweepBuilder.
    Sized alongside the fold pool AND re-keyed when the knob changes
    (like ``fold_pool``): every concurrent chunk fold blocks on one inner
    vertex fold, so fewer workers than chunk folders would serialise the
    overlap the split exists for."""
    from concurrent.futures import ThreadPoolExecutor

    n = max(2, fold_workers())
    with _VFOLD_POOLS_LOCK:
        pool = _VFOLD_POOLS.get(n)
        if pool is None:
            pool = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="sweep-vfold")
            _VFOLD_POOLS[n] = pool
    return pool


_FOLD_POOLS: dict = {}
_FOLD_POOLS_LOCK = threading.Lock()


def fold_pool():
    """Process-wide sized pool for INDEPENDENT chunk folds (each task owns
    a forked ``SweepBuilder`` — nothing shared, unlike the single-worker
    prefetch lane). Keyed by the resolved ``RTPU_FOLD_WORKERS`` so tests
    (and operators) that change the knob get a correctly-sized pool
    instead of a stale cached one. Deliberately separate from
    ``_vfold_pool``: a chunk fold BLOCKS on its inner vertex fold, and
    sharing a pool would let it occupy the very worker that inner task
    needs (the nested-submit deadlock ``_prefetch_pool`` documents)."""
    from concurrent.futures import ThreadPoolExecutor

    n = fold_workers()
    with _FOLD_POOLS_LOCK:
        pool = _FOLD_POOLS.get(n)
        if pool is None:
            pool = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="sweep-fold")
            _FOLD_POOLS[n] = pool
    return pool


_PREFETCH_POOL = None
_PREFETCH_POOL_LOCK = threading.Lock()


def _prefetch_pool():
    """Process-wide single worker for hop-lookahead prefetchers
    (``engine/device_sweep.run_sweep``, ``engine/hopbatch._run_chunks``):
    hop *i+1*'s host fold + delta staging runs here while hop *i*'s
    compiled superstep runs on device — the fold → stage → ship → compute
    pipeline. SINGLE worker by design: a fold mutates shared SweepBuilder
    state, so at most one may be in flight. Deliberately separate from
    ``_vfold_pool`` — the fold task BLOCKS on its inner vertex fold, and
    sharing a pool would let it occupy the very worker that inner task
    needs (classic nested-submit deadlock)."""
    global _PREFETCH_POOL
    if _PREFETCH_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        # locked like the sibling pools: two sweeps racing the lazy init
        # would each get a pool and the single-worker invariant (at most
        # one fold in flight) would silently become two
        with _PREFETCH_POOL_LOCK:
            if _PREFETCH_POOL is None:
                _PREFETCH_POOL = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="sweep-prefetch")
    return _PREFETCH_POOL


def prefetch_depth() -> int:
    """Lookahead depth of ``prefetch_map`` (``RTPU_PREFETCH_DEPTH``,
    default 2): how many folds may be queued/in flight ahead of the fold
    the dispatch loop is consuming, so several folds hide behind one long
    device dispatch. On the single prefetch worker depth only QUEUES work
    (folds still run one at a time, in order — safe for folds that share
    a builder); on the sized ``fold_pool`` it is true concurrency."""
    return max(1, int(os.environ.get("RTPU_PREFETCH_DEPTH", 2)))


def prefetch_map(fold_fns, body, *, depth: int | None = None,
                 pool=None) -> None:
    """Drive ``fold_fns`` (zero-arg callables) through a fold worker pool
    with ``depth``-deep lookahead, calling ``body(payload, stall_seconds)``
    for each fold's result while the NEXT folds already run/queue in the
    pool — the body (ship + device dispatch) overlaps the following folds.
    ``stall_seconds`` is how long the driver actually WAITED on the fold
    (0 = it hid entirely behind the previous body). ``depth`` defaults to
    ``prefetch_depth()``; ``pool`` defaults to the SINGLE-worker prefetch
    lane, which serialises execution in submission order — the only safe
    pool for folds that mutate one shared SweepBuilder. Pass
    ``fold_pool()`` only for INDEPENDENT folds (forked builders). If a
    fold or a body raises, every in-flight fold is drained SYNCHRONOUSLY
    before the exception propagates — folds mutate sweep state, and the
    caller's error handler must not reset that state under a
    still-running fold. The single concurrency-pattern copy both sweep
    engines pipeline through (a generator can't give this guarantee: its
    finally would only drain at finalisation, which the propagating
    traceback's frame references delay past the caller's handler)."""
    import collections
    import time as _t

    fns = list(fold_fns)
    if not fns:
        return
    if depth is None:
        depth = prefetch_depth()
    depth = max(1, depth)
    if pool is None:
        pool = _prefetch_pool()
    # trace-context handoff: each fold task adopts the SUBMITTING
    # thread's context (the sweep span of the request being served), so
    # one request's spans stay one trace across the pool boundary even
    # when concurrent requests share these workers (obs/trace.py). A
    # no-op (fns unwrapped) when tracing is off or nothing is open.
    tr = _tracer()
    if tr is not None:
        fns = [tr.carry(fn) for fn in fns]
    inflight = collections.deque(
        pool.submit(fns[i]) for i in range(min(depth, len(fns))))
    nxt = len(inflight)
    try:
        for _ in range(len(fns)):
            fut = inflight.popleft()
            t0 = _t.perf_counter()
            payload = fut.result()
            stall = _t.perf_counter() - t0
            if nxt < len(fns):
                inflight.append(pool.submit(fns[nxt]))
                nxt += 1
            body(payload, stall)
    except BaseException:
        for fut in inflight:   # let every in-flight fold finish first
            fut.exception()
        raise

#: SweepBuilder attributes that are pure functions of the pinned log —
#: forks SHARE them (never mutated after __init__)
_LOG_DERIVED = ("log", "include_occurrences", "pad", "track_rows",
                "_t", "_k", "_s", "_d", "uv", "_ok", "_sd_all", "_dd_all",
                "_t_sorted", "_preseeded")
#: fold-state arrays mutated IN PLACE by _advance — checkpoint/fork copy
_STATE_COPIED = ("v_lat", "v_alive", "v_first", "v_seen",
                 "e_lat", "e_alive", "e_first", "e_seen")
#: fold-state arrays only ever REBOUND by _advance (np.insert/concatenate
#: build fresh arrays) — a checkpoint can hold the reference
_STATE_SHARED = ("e_enc", "e_enc_dst", "dh_v", "dh_t",
                 "_ea_rows", "_va_rows")


class FoldCheckpoint:
    """Immutable snapshot of a ``SweepBuilder``'s fold state at ``t_prev``
    — the seed of ``SweepBuilder.fork``. Checkpoints from ANY builder over
    the same pinned log content are interchangeable (the dense spaces are
    content-determined), which is what lets the fold cache hand them
    across requests; ``config`` guards against mixing builders with
    different emit/preseed settings."""

    __slots__ = ("t_prev", "state", "config", "nbytes")

    def __init__(self, t_prev, state: dict, config: tuple):
        self.t_prev = t_prev
        self.state = state
        self.config = config
        self.nbytes = int(sum(a.nbytes for a in state.values()))

_EMPTY_DELTA = {
    "v_idx": np.empty(0, np.int64), "v_lat": np.empty(0, np.int64),
    "v_alive": np.empty(0, bool), "v_first": np.empty(0, np.int64),
    "e_enc": np.empty(0, np.int64), "e_lat": np.empty(0, np.int64),
    "e_alive": np.empty(0, bool), "e_first": np.empty(0, np.int64),
}


class SweepBuilder:
    """Build views at ascending timestamps over a pinned log, incrementally.

    For out-of-order `view_at` times, or once the dense dictionary would
    overflow the 32-bit pack, it falls back to full ``build_view`` per call.
    """

    def __init__(self, log: EventLog, *, include_occurrences: bool = False,
                 pad: str = "pow2", track_rows: bool = True,
                 preseed_pairs: bool = False):
        if include_occurrences and not track_rows:
            raise ValueError("occurrence views need the add-row lists")
        self.log = log.pin()
        self.include_occurrences = include_occurrences
        self.pad = pad
        self.track_rows = track_rows
        self._t = self.log.column("time")
        self._k = self.log.column("kind")
        self._s = self.log.column("src")
        self._d = self.log.column("dst")
        # dense dictionary over every vertex id the log ever mentions. dst is
        # only a vertex id on edge events — vertex events carry a -1 sentinel
        # there, and REAL ids can be negative (assign_id hashes to signed
        # int64), so select by kind, never by sign.
        is_e = (self._k == EDGE_ADD) | (self._k == EDGE_DELETE)
        d_real = self._d[is_e]
        self.uv = np.unique(np.concatenate([self._s, d_real])) \
            if len(self._s) else np.empty(0, np.int64)
        self._ok = len(self.uv) < (1 << 31)
        # per-row dense ids, computed ONCE: per-hop _advance slices these
        # instead of re-running searchsorted over the dictionary for every
        # delta (the dominant host cost of a columnar sweep). Skipped above
        # 2^23 events, where the 16B/event would hurt more than it helps.
        if self._ok and 0 < len(self._s) <= (1 << 23):
            self._sd_all = np.searchsorted(self.uv, self._s)
            self._dd_all = np.zeros(len(self._d), np.int64)
            self._dd_all[is_e] = np.searchsorted(self.uv, d_real)
        else:
            self._sd_all = self._dd_all = None
        nv = len(self.uv)
        # dense vertex fold state
        self.v_lat = np.full(nv, INT64_MIN, np.int64)
        self.v_alive = np.zeros(nv, bool)
        self.v_first = np.full(nv, INT64_MIN, np.int64)
        self.v_seen = np.zeros(nv, bool)
        # edge fold state keyed by packed (dense_s, dense_d); enc-sorted
        self.e_enc = np.empty(0, np.int64)
        self.e_lat = np.empty(0, np.int64)
        self.e_alive = np.empty(0, bool)
        self.e_first = np.empty(0, np.int64)
        # the same pair keys packed (dense_d, dense_s), kept sorted — the
        # dst-incidence index for tombstone joins
        self.e_enc_dst = np.empty(0, np.int64)
        # preseed: start the pair table with EVERY pair the log ever
        # mentions (alive=False, times at the sentinel). No pair is ever
        # "fresh" afterwards, so the per-hop sorted inserts and the
        # history-vs-new-pair joins vanish; the per-hop incident join over
        # all pairs generates exactly build_view's all-pairs × all-deletes
        # killList marks (a dead mark before a pair's first add loses to
        # the later add in the latest-wins fold — same outcome as the
        # historical join it replaces). The columnar engines opt in;
        # semantics stay bit-identical (tested against build_view).
        self.e_seen = np.empty(0, bool)   # pair has real marks (firsts set)
        self._preseeded = False
        if preseed_pairs and self._ok and is_e.any():
            sd_e = np.searchsorted(self.uv, self._s[is_e]) \
                if self._sd_all is None else self._sd_all[is_e]
            dd_e = np.searchsorted(self.uv, d_real) \
                if self._dd_all is None else self._dd_all[is_e]
            enc_all = np.unique(self._pack(sd_e, dd_e))
            self.e_enc = enc_all
            self.e_lat = np.full(len(enc_all), INT64_MIN, np.int64)
            self.e_alive = np.zeros(len(enc_all), bool)
            self.e_first = np.full(len(enc_all), INT64_MIN, np.int64)
            self.e_seen = np.zeros(len(enc_all), bool)
            self.e_enc_dst = np.sort(
                ((enc_all & _ENC_MASK) << _ENC_SHIFT)
                | (enc_all >> _ENC_SHIFT))
            self._preseeded = True
        # delete history: (dense vertex, time), sorted by vertex
        self.dh_v = np.empty(0, np.int64)
        self.dh_t = np.empty(0, np.int64)
        # in-time add-event row lists (property joins), ascending, grown
        # per delta — deltas are selected by event TIME, so their row
        # indices interleave with earlier hops' and need a sorted merge
        self._ea_rows = np.empty(0, np.int64)
        self._va_rows = np.empty(0, np.int64)
        self.t_prev: int | None = None
        # per-hop row selection: binary search when the log is time-sorted
        # (bulk loads, replayed dumps), O(N) boolean scan otherwise
        self._t_sorted = bool(
            len(self._t) == 0 or bool((self._t[:-1] <= self._t[1:]).all()))
        # last hop's touched-entity delta (dense vertex indices + packed edge
        # keys with their POST-update fold state) — consumed by the
        # device-resident sweep engine (engine/device_sweep.py), which ships
        # only these O(delta) rows to the chip instead of fresh O(m) arrays
        self.last_delta: dict | None = None

    # ---- helpers ----

    def _dense(self, ids: np.ndarray) -> np.ndarray:
        return np.searchsorted(self.uv, ids)

    def _pack(self, ds: np.ndarray, dd: np.ndarray) -> np.ndarray:
        return (ds << _ENC_SHIFT) | dd

    def _incident(self, enc_sorted: np.ndarray, dv: np.ndarray, dt: np.ndarray,
                  flip: bool):
        """Dead marks (enc, t) for pairs in `enc_sorted` whose FIRST packed
        component is in dv. flip=True means enc_sorted is (d, s)-packed and
        results are re-packed as (s, d)."""
        lo = np.searchsorted(enc_sorted, dv << _ENC_SHIFT, side="left")
        hi = np.searchsorted(enc_sorted, (dv + 1) << _ENC_SHIFT, side="left")
        rows, qidx = _expand_ranges(lo, hi)
        enc = enc_sorted[rows]
        if flip:
            enc = ((enc & _ENC_MASK) << _ENC_SHIFT) | (enc >> _ENC_SHIFT)
        return enc, dt[qidx]

    # ---- checkpoint / fork ----

    def _config(self) -> tuple:
        return (self.include_occurrences, self.pad, self.track_rows,
                self._preseeded, len(self.uv), len(self._t))

    def checkpoint(self) -> FoldCheckpoint:
        """Snapshot the fold state at the current ``t_prev``. Arrays that
        ``_advance`` mutates in place are copied; arrays it only ever
        rebinds (the sorted pair/dst tables, delete history, row lists)
        are shared by reference — a later advance builds fresh ones and
        never touches the snapshot's."""
        state = {k: getattr(self, k).copy() for k in _STATE_COPIED}
        state.update({k: getattr(self, k) for k in _STATE_SHARED})
        return FoldCheckpoint(self.t_prev, state, self._config())

    def fork(self, cp: FoldCheckpoint | None = None) -> "SweepBuilder":
        """An INDEPENDENT builder over the same pinned log, seeded from
        ``cp`` (or this builder's current state): log-derived arrays are
        shared (immutable after __init__), fold state is copied — the
        fork and the original advance without observing each other. This
        is how a range sweep's chunks fold concurrently: each chunk forks
        from the nearest checkpoint and folds its own hop window.
        Equivalence holds because the fold state at T is a function of
        (log, T) alone, not of the hop sequence that reached it (the
        ``view_at ≡ build_view`` contract, tested per hop batching)."""
        if cp is not None and cp.config != self._config():
            raise ValueError(
                "checkpoint was taken from an incompatible SweepBuilder "
                f"(config {cp.config} != {self._config()}) — fold "
                "checkpoints only transfer between builders over the same "
                "pinned log content and emit settings")
        sw = SweepBuilder.__new__(SweepBuilder)
        for k in _LOG_DERIVED:
            setattr(sw, k, getattr(self, k))
        src = cp.state if cp is not None else None
        for k in _STATE_COPIED:
            setattr(sw, k, (src[k] if src is not None
                            else getattr(self, k)).copy())
        for k in _STATE_SHARED:
            # rebind-only arrays: the fork's first rebind leaves the
            # source (live builder or cached checkpoint) untouched
            setattr(sw, k, src[k] if src is not None else getattr(self, k))
        sw.t_prev = cp.t_prev if cp is not None else self.t_prev
        sw.last_delta = None
        return sw

    # ---- incremental re-pin (live epoch serving) ----

    def repin(self, live_log) -> str:
        """Adopt rows appended to the LIVE log since this builder's pin,
        without refolding history. Returns:

        * ``"noop"``     — nothing new; the pin already covers the log.
        * ``"extended"`` — the suffix was adopted in place: fold state,
          ``t_prev`` and the dense vertex/pair dictionaries all remain
          valid, and the next ``_advance`` folds exactly the new rows.
        * ``"rebuild"``  — the suffix cannot be adopted; the caller must
          construct a fresh builder (and refold from scratch).

        Extension is only sound when the pinned snapshot is still a
        PREFIX of the live log and the frozen dictionaries still cover
        it, so ``"rebuild"`` is returned when any of these hold:

        * the log was compacted (history rewritten — the pin is no
          longer a prefix; detected via ``EventLog.compactions``);
        * the suffix mentions a vertex id outside ``uv`` (the dense
          dictionary, and every per-row dense id derived from it, is
          frozen at pin time);
        * a preseeded builder sees a (src, dst) pair outside ``e_enc``
          (the preseed invariant is "every pair the log ever mentions");
        * a suffix event lands at or below ``t_prev`` — the watermark
          contract says events at or below the served fence never
          arrive late, so such a row means the fence was not honoured
          and already-folded state is stale.
        """
        new = live_log.pin()
        n_old = len(self._t)
        if (getattr(new, "compactions", 0)
                != getattr(self.log, "compactions", 0)):
            # checked BEFORE the row-count fast path: a compaction can
            # rewrite history to the SAME row count, and "same n" says
            # nothing about row identity across a rewrite
            return "rebuild"
        if new.n == n_old:
            return "noop"
        if new.n < n_old or not self._ok:
            return "rebuild"
        t_new = new.column("time")[n_old:]
        k_new = new.column("kind")[n_old:]
        s_new = new.column("src")[n_old:]
        d_new = new.column("dst")[n_old:]
        if self.t_prev is not None and len(t_new) \
                and int(t_new.min()) <= self.t_prev:
            return "rebuild"
        is_e = (k_new == EDGE_ADD) | (k_new == EDGE_DELETE)
        d_real = d_new[is_e]
        ids = np.concatenate([s_new, d_real])
        pos = np.searchsorted(self.uv, ids)
        pos_c = np.clip(pos, 0, max(len(self.uv) - 1, 0))
        if not len(self.uv) or not bool((self.uv[pos_c] == ids).all()):
            return "rebuild"   # new vertex id: dense dictionary is stale
        sd_new = pos[: len(s_new)]
        dd_new = np.zeros(len(d_new), np.int64)
        dd_new[is_e] = pos[len(s_new):]
        if self._preseeded and is_e.any():
            enc = self._pack(sd_new[is_e], dd_new[is_e])
            epos = np.clip(np.searchsorted(self.e_enc, enc), 0,
                           max(len(self.e_enc) - 1, 0))
            if not len(self.e_enc) \
                    or not bool((self.e_enc[epos] == enc).all()):
                return "rebuild"   # new pair: preseeded table is stale
        # adopt: rebind the log-derived views; everything else is valid
        self.log = new
        self._t = new.column("time")
        self._k = new.column("kind")
        self._s = new.column("src")
        self._d = new.column("dst")
        if self._sd_all is not None:
            self._sd_all = np.concatenate([self._sd_all, sd_new])
            self._dd_all = np.concatenate([self._dd_all, dd_new])
        self._t_sorted = bool(
            self._t_sorted
            and (not len(t_new) or bool((t_new[:-1] <= t_new[1:]).all()))
            and (n_old == 0 or int(t_new[0]) >= int(self._t[n_old - 1])))
        return "extended"

    # ---- the sweep ----

    def view_at(self, time: int) -> GraphView:
        time = int(time)
        if not self._ok or (self.t_prev is not None and time < self.t_prev):
            return build_view(self.log, time,
                              include_occurrences=self.include_occurrences,
                              pad=self.pad)
        if self.t_prev is None or time > self.t_prev:
            self._advance(time)
        return self._emit(time)

    def _advance(self, time: int) -> None:
        t_prev = self.t_prev if self.t_prev is not None else np.iinfo(np.int64).min
        if self._t_sorted:
            lo = 0 if t_prev == np.iinfo(np.int64).min \
                else int(np.searchsorted(self._t, t_prev, side="right"))
            hi = int(np.searchsorted(self._t, time, side="right"))
            rows = np.arange(lo, hi)
        else:
            sel = (self._t <= time) if t_prev == np.iinfo(np.int64).min \
                else ((self._t > t_prev) & (self._t <= time))
            rows = np.flatnonzero(sel)
        self.t_prev = time
        if len(rows) == 0:
            self.last_delta = _EMPTY_DELTA
            return
        t = self._t[rows]
        k = self._k[rows]
        s = self._s[rows]
        d = self._d[rows]
        is_va = k == VERTEX_ADD
        is_vd = k == VERTEX_DELETE
        is_ea = k == EDGE_ADD
        is_ed = k == EDGE_DELETE
        uvd = uenc = None  # touched entities, recorded into last_delta below

        if self.track_rows:
            new_ea = rows[is_ea]
            new_va = rows[is_va]
            self._ea_rows = np.insert(
                self._ea_rows, np.searchsorted(self._ea_rows, new_ea), new_ea)
            self._va_rows = np.insert(
                self._va_rows, np.searchsorted(self._va_rows, new_va), new_va)

        if self._sd_all is not None:
            sd, dd = self._sd_all[rows], self._dd_all[rows]
            ds_ea, dd_ea = sd[is_ea], dd[is_ea]
            dv_del = sd[is_vd]
            dv_add = sd[is_va]
            ds_ed, dd_ed = sd[is_ed], dd[is_ed]
        else:
            ds_ea = self._dense(s[is_ea])
            dd_ea = self._dense(d[is_ea])
            dv_del = self._dense(s[is_vd])
            dv_add = self._dense(s[is_va])
            ds_ed = self._dense(s[is_ed])
            dd_ed = self._dense(d[is_ed])
        t_del = t[is_vd]

        # -- vertex delta fold: adds + edge-endpoint revivals vs deletes --
        # runs in a worker thread OVERLAPPED with the edge-side marks+fold
        # below (independent state; ctypes/numpy release the GIL): the two
        # folds are the per-hop host cost of a columnar sweep
        v_ids = np.concatenate([dv_add, ds_ea, dd_ea, dv_del])
        v_t = np.concatenate([t[is_va], t[is_ea], t[is_ea], t_del])
        v_al = np.zeros(len(v_ids), bool)
        v_al[: len(v_ids) - len(dv_del)] = True

        def _vertex_fold():
            if not len(v_ids):
                return None
            (uvd0,), dlat, dalive, dfirst = _fold_latest((v_ids,), v_t, v_al)
            # delta times are strictly later than any prior mark, so the
            # delta's latest wins outright and firsts only fill unseen slots
            self.v_lat[uvd0] = dlat
            self.v_alive[uvd0] = dalive
            self.v_first[uvd0] = np.where(self.v_seen[uvd0],
                                          self.v_first[uvd0], dfirst)
            self.v_seen[uvd0] = True
            return uvd0

        # the inner vertex fold crosses to the vfold pool mid-advance:
        # carry the chunk fold's trace context with it (a no-op wrap
        # when tracing is off)
        tr = _tracer()
        v_fut = _vfold_pool().submit(
            tr.carry(_vertex_fold) if tr is not None else _vertex_fold)

        # -- edge delta marks: own add/delete events --
        enc_ea = self._pack(ds_ea, dd_ea)
        enc_ed = self._pack(ds_ed, dd_ed)
        marks_enc = [enc_ea, enc_ed]
        marks_t = [t[is_ea], t[is_ed]]
        marks_a = [np.ones(len(enc_ea), bool), np.zeros(len(enc_ed), bool)]

        delta_enc = np.unique(np.concatenate([enc_ea, enc_ed])) \
            if (len(enc_ea) or len(enc_ed)) else np.empty(0, np.int64)
        if self._preseeded:
            new_enc = delta_enc[:0]   # every pair is in the table already
        else:
            pos = np.searchsorted(self.e_enc, delta_enc)
            pos_c = np.clip(pos, 0, max(len(self.e_enc) - 1, 0))
            known = (self.e_enc[pos_c] == delta_enc) if len(self.e_enc) \
                else np.zeros(len(delta_enc), bool)
            new_enc = delta_enc[~known]

        if len(dv_del):
            # delta deletes × (pairs known before this hop ∪ NEW delta pairs)
            for enc_arr, flip in ((self.e_enc, False), (self.e_enc_dst, True)):
                enc_ts, t_ts = self._incident(enc_arr, dv_del, t_del, flip)
                marks_enc.append(enc_ts)
                marks_t.append(t_ts)
                marks_a.append(np.zeros(len(enc_ts), bool))
            new_by_dst = np.sort(
                ((new_enc & _ENC_MASK) << _ENC_SHIFT) | (new_enc >> _ENC_SHIFT))
            for enc_arr, flip in ((new_enc, False), (new_by_dst, True)):
                enc_ts, t_ts = self._incident(enc_arr, dv_del, t_del, flip)
                marks_enc.append(enc_ts)
                marks_t.append(t_ts)
                marks_a.append(np.zeros(len(enc_ts), bool))

        if len(new_enc) and len(self.dh_v):
            # historical deletes × pairs first seen in this delta
            ns = new_enc >> _ENC_SHIFT
            nd = new_enc & _ENC_MASK
            for comp in (ns, nd):
                lo = np.searchsorted(self.dh_v, comp, side="left")
                hi = np.searchsorted(self.dh_v, comp, side="right")
                hrows, qidx = _expand_ranges(lo, hi)
                marks_enc.append(new_enc[qidx])
                marks_t.append(self.dh_t[hrows])
                marks_a.append(np.zeros(len(hrows), bool))

        all_enc = np.concatenate(marks_enc)
        epos_known = None
        if len(all_enc):
            all_t = np.concatenate(marks_t)
            all_a = np.concatenate(marks_a)
            (uenc,), elat_d, ealive_d, efirst_d = _fold_latest((all_enc,), all_t, all_a)
            upos = np.searchsorted(self.e_enc, uenc)
            upos_c = np.clip(upos, 0, max(len(self.e_enc) - 1, 0))
            uknown = (self.e_enc[upos_c] == uenc) if len(self.e_enc) \
                else np.zeros(len(uenc), bool)
            # existing pairs: delta marks are strictly later — overwrite
            # (firsts only fill slots that never saw a real mark — preseeded
            # pairs exist in the table before their first event)
            kpos = upos_c[uknown]
            self.e_lat[kpos] = elat_d[uknown]
            self.e_alive[kpos] = ealive_d[uknown]
            self.e_first[kpos] = np.where(self.e_seen[kpos],
                                          self.e_first[kpos],
                                          efirst_d[uknown])
            self.e_seen[kpos] = True
            # new pairs: insert (fold already merged their full history,
            # including historical tombstones, so firsts are exact)
            fresh = ~uknown
            if not fresh.any():
                # positions are final (no inserts shifted them): last_delta
                # reuses them instead of re-searching the whole table
                epos_known = upos_c
            if fresh.any():
                at = upos[fresh]
                self.e_enc = np.insert(self.e_enc, at, uenc[fresh])
                self.e_lat = np.insert(self.e_lat, at, elat_d[fresh])
                self.e_alive = np.insert(self.e_alive, at, ealive_d[fresh])
                self.e_first = np.insert(self.e_first, at, efirst_d[fresh])
                self.e_seen = np.insert(self.e_seen, at,
                                        np.ones(fresh.sum(), bool))
                enc2 = (((uenc[fresh] & _ENC_MASK) << _ENC_SHIFT)
                        | (uenc[fresh] >> _ENC_SHIFT))
                enc2 = np.sort(enc2)
                self.e_enc_dst = np.insert(
                    self.e_enc_dst, np.searchsorted(self.e_enc_dst, enc2), enc2)

        if len(dv_del) and not self._preseeded:
            # the delete history only feeds the new-pair join, which a
            # preseeded table never takes (no pair is ever new)
            self.dh_v = np.concatenate([self.dh_v, dv_del])
            self.dh_t = np.concatenate([self.dh_t, t_del])
            order = np.argsort(self.dh_v, kind="stable")
            self.dh_v = self.dh_v[order]
            self.dh_t = self.dh_t[order]

        uvd = v_fut.result()   # join the overlapped vertex fold

        # Touched-entity delta with POST-update fold state, read back from the
        # running arrays so it is correct no matter which code path (known
        # pair overwrite / fresh insert / tombstone join) produced the value.
        tv = uvd if uvd is not None else np.empty(0, np.int64)
        te = uenc if uenc is not None else np.empty(0, np.int64)
        epos = epos_known if epos_known is not None \
            else np.searchsorted(self.e_enc, te)
        self.last_delta = {
            "v_idx": tv, "v_lat": self.v_lat[tv],
            "v_alive": self.v_alive[tv], "v_first": self.v_first[tv],
            "e_enc": te, "e_lat": self.e_lat[epos],
            "e_alive": self.e_alive[epos], "e_first": self.e_first[epos],
        }

    def _emit(self, time: int) -> GraphView:
        if not self.track_rows:
            raise RuntimeError(
                "this SweepBuilder was built with track_rows=False (fold "
                "state only — the columnar/device engines); use a default "
                "one to emit GraphViews")
        act_dense = np.flatnonzero(self.v_alive)
        act_vids = self.uv[act_dense]  # uv ascending ⇒ dense order = id order
        act_latest = self.v_lat[act_dense]
        act_first = self.v_first[act_dense]

        alive = self.e_alive
        enc = self.e_enc[alive]
        ae_s = self.uv[enc >> _ENC_SHIFT]
        ae_d = self.uv[enc & _ENC_MASK]
        ae_latest = self.e_lat[alive]
        ae_first = self.e_first[alive]
        # local endpoint indices via the dense→local LUT (enc order is
        # (src, dst)-major, so one argsort of the flipped packing gives the
        # (dst, src) order _assemble_view needs)
        lut = np.full(len(self.uv), -1, np.int32)
        lut[act_dense] = np.arange(len(act_dense), dtype=np.int32)
        src_loc = lut[enc >> _ENC_SHIFT]
        dst_loc = lut[enc & _ENC_MASK]
        eorder = np.argsort(
            (dst_loc.astype(np.int64) << _ENC_SHIFT) | src_loc, kind="stable")
        locs = (src_loc, dst_loc, eorder)

        eadd_rows = self._ea_rows
        vadd_rows = self._va_rows
        occ = None
        if self.include_occurrences:
            occ = (eadd_rows, self._t[eadd_rows],
                   self._s[eadd_rows], self._d[eadd_rows])
        return _assemble_view(
            self.log, time, act_vids, act_latest, act_first,
            ae_s, ae_d, ae_latest, ae_first, self.pad,
            eadd_rows, vadd_rows, occ, locs,
        )


# ------------------------------------------------------------- fold cache

_METRICS_SENTINEL = object()
_METRICS = _METRICS_SENTINEL


def _metrics():
    """obs.metrics bundle, or None when prometheus isn't importable —
    core must keep working in stripped environments."""
    global _METRICS
    if _METRICS is _METRICS_SENTINEL:
        try:
            from ..obs.metrics import METRICS

            _METRICS = METRICS
        except Exception:
            _METRICS = None
    return _METRICS


def _tracer():
    try:
        from ..obs.trace import TRACER

        return TRACER
    except Exception:
        return None


def log_fingerprint(log) -> tuple:
    """Content identity of a pinned log for fold-cache keys: row count +
    order-sensitive checksums over every column, plus the append version.
    Cached on the (frozen, immutable) pin — repeated REST requests pin
    the same live log and must land on the same key, and two logs that
    merely share a version counter must not collide."""
    fp = getattr(log, "_rtpu_fold_fp", None)
    if fp is not None:
        return fp
    t = log.column("time")
    idx = np.arange(len(t), dtype=np.uint64)
    gold = np.uint64(0x9E3779B97F4A7C15)

    def mix(a):
        if not len(a):
            return 0
        h = a.astype(np.int64, copy=False).view(np.uint64)
        return int(np.bitwise_xor.reduce((h + gold) * (idx * gold + gold)))

    # src and dst stay SEPARATE components: xor-combining them would be
    # symmetric per row, colliding a graph with its (partial) transpose
    fp = (int(len(t)), int(log.version), mix(t),
          mix(log.column("src")), mix(log.column("dst")),
          mix(log.column("kind").astype(np.int64)))
    try:
        log._rtpu_fold_fp = fp   # pins are frozen: content never changes
    except AttributeError:
        pass
    return fp


class FoldCache:
    """Bounded, memory-accounted, cross-request fold cache (LRU).

    Two kinds of entries share one byte budget:

    * **payloads** — a columnar engine's complete fold output for an
      exact (log fingerprint, hop grid) — a repeated REST range job skips
      folding entirely (``engine/hopbatch`` integration);
    * **checkpoints** — ``FoldCheckpoint`` states at chunk boundaries,
      so a later sweep over the same log seeds its chunk forks from the
      NEAREST checkpoint instead of re-folding the prefix.

    All mutation is under one lock; values must be treated as immutable
    by callers (payload arrays are never written after insertion — the
    engines copy-on-ship by construction)."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        from collections import OrderedDict

        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # (fp, config) -> ascending checkpoint times, for nearest lookup
        self._ckpt_times: dict[tuple, list] = {}
        # lockset-sanitizer registration (None unless RTPU_SANITIZE):
        # cache accesses report their held lockset, so a future unguarded
        # fast path shows up as a shared-state-race finding in tier-1
        self._san_tracker = _san_track("fold_cache")

    def _note_shared(self, write: bool) -> None:
        _san_note(self._san_tracker, write)

    # -- internals (callers hold self._lock) --

    def _evict_until(self, budget: int) -> None:
        while self._bytes > budget and self._entries:
            key, (value, nbytes) = self._entries.popitem(last=False)
            self._bytes -= nbytes
            self.evictions += 1
            if key[0] == "ckpt":
                times = self._ckpt_times.get(key[1:3])
                if times is not None:
                    try:
                        times.remove(key[3])
                    except ValueError:
                        pass
            m = _metrics()
            if m is not None:
                m.fold_cache_evictions.inc()
                m.fold_cache_bytes.set(self._bytes)

    def _note(self, hit: bool, key: tuple, nbytes: int = 0) -> None:
        m = _metrics()
        if m is not None:
            (m.fold_cache_hits if hit else m.fold_cache_misses).inc()
        tr = _tracer()
        if tr is not None:
            tr.instant("fold.cache", hit=hit, kind=str(key[0]),
                       bytes=int(nbytes), cached_bytes=self._bytes)

    # -- payload entries --

    def get(self, key: tuple):
        """Cached value for ``key`` (LRU-touch) or None — counts a hit or
        a miss either way."""
        with self._lock:
            self._note_shared(write=True)   # LRU touch mutates order
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                self._note(False, key)
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            self._note(True, key, ent[1])
            return ent[0]

    def put(self, key: tuple, value, nbytes: int) -> bool:
        """Insert (or refresh) ``key``; evicts LRU entries past the byte
        bound. Values larger than the whole bound are refused (False) —
        one oversized sweep must not flush every other tenant."""
        nbytes = int(nbytes)
        if nbytes > self.max_bytes:
            return False
        with self._lock:
            self._note_shared(write=True)
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            self._evict_until(self.max_bytes)
            m = _metrics()
            if m is not None:
                m.fold_cache_bytes.set(self._bytes)
        return True

    # -- checkpoint entries --

    def put_checkpoint(self, fp: tuple, cp: FoldCheckpoint) -> bool:
        if cp.t_prev is None or cp.nbytes > self.max_bytes:
            return False
        key = ("ckpt", fp, cp.config, int(cp.t_prev))
        with self._lock:
            self._note_shared(write=True)
            if key in self._entries:
                self._entries.move_to_end(key)
                return True
            self._entries[key] = (cp, cp.nbytes)
            self._bytes += cp.nbytes
            times = self._ckpt_times.setdefault((fp, cp.config), [])
            import bisect

            bisect.insort(times, int(cp.t_prev))
            self._evict_until(self.max_bytes)
            m = _metrics()
            if m is not None:
                m.fold_cache_bytes.set(self._bytes)
        return True

    def nearest_checkpoint(self, fp: tuple, config: tuple,
                           time: int) -> FoldCheckpoint | None:
        """Latest cached checkpoint at or before ``time`` for this log —
        the fork seed that minimises the prefix re-fold."""
        import bisect

        with self._lock:
            self._note_shared(write=True)   # hit path LRU-touches
            times = self._ckpt_times.get((fp, config))
            if not times:
                self.misses += 1
                self._note(False, ("ckpt", fp))
                return None
            i = bisect.bisect_right(times, int(time))
            if i == 0:
                self.misses += 1
                self._note(False, ("ckpt", fp))
                return None
            key = ("ckpt", fp, config, times[i - 1])
            ent = self._entries.get(key)
            if ent is None:   # index raced an eviction
                self.misses += 1
                self._note(False, key)
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            self._note(True, key, ent[1])
            return ent[0]

    def stats(self) -> dict:
        with self._lock:
            self._note_shared(write=False)
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "max_bytes": self.max_bytes, "hits": self.hits,
                    "misses": self.misses, "evictions": self.evictions}

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._ckpt_times.clear()
            self._bytes = 0


_FOLD_CACHE = None
_FOLD_CACHE_LOCK = threading.Lock()


def fold_cache() -> FoldCache | None:
    """Process-wide fold cache, sized by ``RTPU_FOLD_CACHE_MB`` (default
    256; ``0`` disables). The bound is re-read per call so tests and
    operators can resize/disable without a restart — a size change swaps
    in a fresh cache (the old one drains by GC)."""
    global _FOLD_CACHE
    mb = int(os.environ.get("RTPU_FOLD_CACHE_MB", 256))
    if mb <= 0:
        return None
    with _FOLD_CACHE_LOCK:
        if _FOLD_CACHE is None or _FOLD_CACHE.max_bytes != mb << 20:
            _FOLD_CACHE = FoldCache(mb << 20)
        return _FOLD_CACHE
