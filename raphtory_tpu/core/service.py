"""TemporalGraph — the user-facing handle tying log, ingestion and views.

The single-process equivalent of the whole reference deployment
(``SingleNodeSetup.scala``): storage + ingestion + analysis access behind one
object. The watermark fence reproduces the ``TimeCheck``/``TimeResponse``
gate (``AnalysisTask.scala:162-195``): a view at T is only served as *exact*
once every source's watermark has passed T; otherwise the caller opts into
waiting or a best-effort (live) view.
"""

from __future__ import annotations

import collections
import threading
import time as _time

from ..ingestion.watermark import WatermarkRegistry
from ..obs.metrics import METRICS
from ..obs.trace import TRACER
from .events import EventLog
from .snapshot import GraphView, build_view


class StaleViewError(RuntimeError):
    pass


class TemporalGraph:
    def __init__(self, log: EventLog | None = None,
                 watermarks: WatermarkRegistry | None = None,
                 cache_size: int = 8):
        self.log = log if log is not None else EventLog()
        self.watermarks = watermarks if watermarks is not None else WatermarkRegistry()
        self._cache: collections.OrderedDict = collections.OrderedDict()
        self._cache_size = cache_size
        self._cache_lock = threading.Lock()  # jobs share one graph
        # warm View engine: one resident DeviceSweep shared by View/Live
        # dispatches (engine/device_sweep keeps fold state ON device) —
        # a repeat view is a delta-advance + one dispatch, not a full
        # host fold + O(m) upload (ReaderWorker.scala:293-352 rebuilds a
        # lens per job; this is the thing that beats it)
        self._resident = None
        self._resident_lock = threading.Lock()
        self._resident_version = -1
        self._resident_n = 0            # rows scanned for post-pin events
        self._post_pin_min = 2**62      # min event time appended after pin
        self._resident_broken = False   # e.g. >2^31 vertices: stop retrying

    # ---- time bounds ----

    @property
    def earliest_time(self) -> int:
        return self.log.min_time

    @property
    def latest_time(self) -> int:
        return self.log.max_time

    def safe_time(self) -> int:
        """Largest timestamp no in-flight source can still mutate."""
        return min(self.watermarks.safe_time(), 2**62)

    # ---- views (the GraphLens surface) ----

    def view_at(self, time: int, *, exact: bool = True,
                wait_timeout: float = 0.0,
                include_occurrences: bool = False) -> GraphView:
        """Snapshot at `time`. exact=True enforces the watermark fence,
        optionally polling up to wait_timeout seconds (the reference re-checks
        every 10 s — AnalysisTask.scala:183-189); exact=False serves a
        best-effort live view."""
        if exact:
            if not self.watermarks.wait_for(time, timeout=wait_timeout):
                raise StaleViewError(
                    f"view at {time} not yet safe: watermark="
                    f"{self.safe_time()} ({self.watermarks.snapshot()})")
        version = self.log.version
        key = (version, int(time), include_occurrences)
        with self._cache_lock:
            hit = self._cache.get(key)
            if hit is not None:
                self._cache.move_to_end(key)
                return hit
        t0 = _time.perf_counter()
        with TRACER.span("snapshot.fold", time=int(time),
                         occurrences=bool(include_occurrences)):
            view = build_view(self.log, int(time),
                              include_occurrences=include_occurrences)
        METRICS.snapshot_build_seconds.observe(_time.perf_counter() - t0)
        self.cache_put(int(time), view, include_occurrences, version=version)
        return view

    def cache_put(self, time: int, view: GraphView,
                  include_occurrences: bool = False, *,
                  version: int | None = None) -> None:
        """Insert an externally built view (e.g. a SweepBuilder hop) into the
        shared cache so later view_at calls reuse it. `version` must be the
        log version the view was BUILT from (a sweep's pinned log), not the
        current one — a compaction between build and insert would otherwise
        file a pre-compaction view under the post-compaction key, undoing
        invalidate_cache()."""
        METRICS.view_vertices.set(view.n_active)
        METRICS.view_edges.set(view.m_active)
        if version is None:
            version = self.log.version
        key = (version, int(time), include_occurrences)
        with self._cache_lock:
            self._cache[key] = view
            while len(self._cache) > self._cache_size:
                self._cache.popitem(last=False)

    def resident_acquire(self, time: int):
        """Acquire the shared resident DeviceSweep for a warm View dispatch
        at ``time``; returns ``(sweep, held_lock)`` — the caller MUST
        release the lock — or None when the resident path cannot serve:

        * ``time`` behind the sweep's clock (DeviceSweep only ascends; the
          cold path's view cache handles out-of-order timestamps), or
        * the log's id space overflows the packed-key engine.

        A pin is replaced (not declined) when events appended after it
        land at or before ``time`` — exact, via an incremental min over
        the post-pin rows.

        The caller is responsible for the watermark fence (only ask for
        ``time`` ≤ ``safe_time()``)."""
        if self._resident_broken:
            return None
        self._resident_lock.acquire()
        try:
            sweep = self._resident
            if sweep is not None:
                if self.log.version != self._resident_version:
                    # the pinned fold can't see events appended after the
                    # pin — an incremental min over the new rows tells
                    # EXACTLY whether any lands at or before `time`
                    # (watermarks alone can't: direct log appends are
                    # legal and unfenced). pin() captures (n, version)
                    # atomically, so rows landing after this scan bump the
                    # live version past the one stored here.
                    pinned = self.log.pin()
                    if self._resident_n < pinned.n:
                        tcol = pinned.column("time")
                        self._post_pin_min = min(
                            self._post_pin_min,
                            int(tcol[self._resident_n:pinned.n].min()))
                        self._resident_n = pinned.n
                    self._resident_version = pinned.version
                # checked on EVERY acquire, not only when the version just
                # moved — an earlier small-time acquire may have recorded
                # the post-pin min and synced the version already
                if int(time) >= self._post_pin_min:
                    # post-pin events land at or before `time`: ADOPT the
                    # appended suffix in place (DeviceSweep.repin) so the
                    # next advance folds exactly the new rows — the
                    # incremental live-serving path. Only a genuine
                    # rebuild condition (compaction, new vertex/pair,
                    # out-of-order arrival, dtype overflow) re-pins from
                    # scratch.
                    if sweep.repin(self.log) == "extended":
                        # invariant restored: the sweep's (frozen) pin
                        # captured (n, version) atomically and now covers
                        # every scanned row
                        self._resident_n = sweep.sw.log.n
                        self._resident_version = sweep.sw.log.version
                        self._post_pin_min = 2**62
                    else:
                        sweep = None   # stale for this time: re-pin below
            if sweep is None:
                from ..engine.device_sweep import DeviceSweep

                pinned = self.log.pin()   # (n, version) atomic with rows
                sweep = DeviceSweep(pinned)
                self._resident = sweep
                self._resident_version = pinned.version
                self._resident_n = pinned.n
                self._post_pin_min = 2**62
            if sweep.t_now is not None and int(time) < sweep.t_now:
                self._resident_lock.release()
                return None
            return sweep, self._resident_lock
        except ValueError:
            self._resident_broken = True
            self._resident_lock.release()
            return None
        except BaseException:
            self._resident_lock.release()
            raise

    def resident_discard(self, log_replaced: bool = False) -> None:
        """Drop the resident sweep. Callers that hit device trouble
        mid-dispatch MUST call this while still holding the acquired lock:
        a partially applied delta leaves the device buffers inconsistent
        with the host fold, and the next acquire must re-pin.
        ``log_replaced`` also clears the broken latch — overflow is a
        property of the log, not of the graph object."""
        self._resident = None
        self._resident_version = -1
        self._resident_n = 0
        self._post_pin_min = 2**62
        if log_replaced:
            self._resident_broken = False

    # ---- maintenance ----

    def swap_log(self, new_log: EventLog) -> None:
        """Replace the log object; invalidates the view cache. NOTE: any
        ingestion pipeline holding the old log keeps writing there — prefer
        ``EventLog.compact_to`` (in-place) for live graphs."""
        self.log = new_log
        self.invalidate_cache()

    def invalidate_cache(self) -> None:
        with self._cache_lock:
            self._cache.clear()
        with self._resident_lock:
            # a swapped log may reuse version ids, and a previously
            # oversized log's broken latch must not outlive it
            self.resident_discard(log_replaced=True)

    def checkpoint(self, path: str) -> None:
        from ..persist.checkpoint import save_log

        save_log(self.log, path)

    @classmethod
    def restore(cls, path: str, **kw) -> "TemporalGraph":
        from ..persist.checkpoint import load_log

        return cls(log=load_log(path), **kw)

    def live_view(self, include_occurrences: bool = False) -> GraphView:
        """View at the current safe watermark (LiveAnalysisTask semantics:
        timestamp = min over workers' watermarks, LiveAnalysisTask.scala:55-105)."""
        t = min(self.safe_time(), self.latest_time)
        return self.view_at(t, exact=False,
                            include_occurrences=include_occurrences)
