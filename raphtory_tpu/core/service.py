"""TemporalGraph — the user-facing handle tying log, ingestion and views.

The single-process equivalent of the whole reference deployment
(``SingleNodeSetup.scala``): storage + ingestion + analysis access behind one
object. The watermark fence reproduces the ``TimeCheck``/``TimeResponse``
gate (``AnalysisTask.scala:162-195``): a view at T is only served as *exact*
once every source's watermark has passed T; otherwise the caller opts into
waiting or a best-effort (live) view.
"""

from __future__ import annotations

import collections
import threading
import time as _time

from ..ingestion.watermark import WatermarkRegistry
from ..obs.metrics import METRICS
from .events import EventLog
from .snapshot import GraphView, build_view


class StaleViewError(RuntimeError):
    pass


class TemporalGraph:
    def __init__(self, log: EventLog | None = None,
                 watermarks: WatermarkRegistry | None = None,
                 cache_size: int = 8):
        self.log = log if log is not None else EventLog()
        self.watermarks = watermarks if watermarks is not None else WatermarkRegistry()
        self._cache: collections.OrderedDict = collections.OrderedDict()
        self._cache_size = cache_size
        self._cache_lock = threading.Lock()  # jobs share one graph

    # ---- time bounds ----

    @property
    def earliest_time(self) -> int:
        return self.log.min_time

    @property
    def latest_time(self) -> int:
        return self.log.max_time

    def safe_time(self) -> int:
        """Largest timestamp no in-flight source can still mutate."""
        return min(self.watermarks.safe_time(), 2**62)

    # ---- views (the GraphLens surface) ----

    def view_at(self, time: int, *, exact: bool = True,
                wait_timeout: float = 0.0,
                include_occurrences: bool = False) -> GraphView:
        """Snapshot at `time`. exact=True enforces the watermark fence,
        optionally polling up to wait_timeout seconds (the reference re-checks
        every 10 s — AnalysisTask.scala:183-189); exact=False serves a
        best-effort live view."""
        if exact:
            if not self.watermarks.wait_for(time, timeout=wait_timeout):
                raise StaleViewError(
                    f"view at {time} not yet safe: watermark="
                    f"{self.safe_time()} ({self.watermarks.snapshot()})")
        version = self.log.version
        key = (version, int(time), include_occurrences)
        with self._cache_lock:
            hit = self._cache.get(key)
            if hit is not None:
                self._cache.move_to_end(key)
                return hit
        t0 = _time.perf_counter()
        view = build_view(self.log, int(time),
                          include_occurrences=include_occurrences)
        METRICS.snapshot_build_seconds.observe(_time.perf_counter() - t0)
        self.cache_put(int(time), view, include_occurrences, version=version)
        return view

    def cache_put(self, time: int, view: GraphView,
                  include_occurrences: bool = False, *,
                  version: int | None = None) -> None:
        """Insert an externally built view (e.g. a SweepBuilder hop) into the
        shared cache so later view_at calls reuse it. `version` must be the
        log version the view was BUILT from (a sweep's pinned log), not the
        current one — a compaction between build and insert would otherwise
        file a pre-compaction view under the post-compaction key, undoing
        invalidate_cache()."""
        METRICS.view_vertices.set(view.n_active)
        METRICS.view_edges.set(view.m_active)
        if version is None:
            version = self.log.version
        key = (version, int(time), include_occurrences)
        with self._cache_lock:
            self._cache[key] = view
            while len(self._cache) > self._cache_size:
                self._cache.popitem(last=False)

    # ---- maintenance ----

    def swap_log(self, new_log: EventLog) -> None:
        """Replace the log object; invalidates the view cache. NOTE: any
        ingestion pipeline holding the old log keeps writing there — prefer
        ``EventLog.compact_to`` (in-place) for live graphs."""
        self.log = new_log
        self.invalidate_cache()

    def invalidate_cache(self) -> None:
        with self._cache_lock:
            self._cache.clear()

    def checkpoint(self, path: str) -> None:
        from ..persist.checkpoint import save_log

        save_log(self.log, path)

    @classmethod
    def restore(cls, path: str, **kw) -> "TemporalGraph":
        from ..persist.checkpoint import load_log

        return cls(log=load_log(path), **kw)

    def live_view(self, include_occurrences: bool = False) -> GraphView:
        """View at the current safe watermark (LiveAnalysisTask semantics:
        timestamp = min over workers' watermarks, LiveAnalysisTask.scala:55-105)."""
        t = min(self.safe_time(), self.latest_time)
        return self.view_at(t, exact=False,
                            include_occurrences=include_occurrences)
