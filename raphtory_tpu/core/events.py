"""Append-only temporal event log — the heart of the store.

TPU-native re-design of the reference's bitemporal entity model
(``core/model/graphentities/Entity.scala:25-57`` — per-entity
``TreeMap[Long, Boolean]`` histories with tombstone deletes). Instead of
pointer-chasing per-entity maps, the whole graph history is ONE
structure-of-arrays event log on the host (numpy). Views/windows are computed
as vectorised folds over the sorted log (see ``snapshot.py``) and shipped to
the device as immutable CSR arrays.

Semantics (deterministic fold over the event *multiset* — order of arrival
never matters, mirroring the commutativity invariant of the reference,
``README.md:6``):

* A vertex is alive at T iff the latest vertex-relevant event at time <= T is
  an "alive" mark. Alive marks are: explicit vertex adds AND any edge add
  touching the vertex (the reference's ``EntityStorage.edgeAdd`` calls
  ``vertexAdd`` for both endpoints, ``EntityStorage.scala:241-263``). Dead
  marks are vertex deletes.
* An edge (src, dst) is alive at T iff the latest event at time <= T in its
  *merged* stream is an edge add. The merged stream is: its own add/delete
  events plus a dead mark at the time of every delete of either endpoint
  (the reference's ``killList`` propagation, ``Edge.scala:36-44``,
  ``EntityStorage.scala:148-232`` — here a pure fold, no ack protocol).
* Tie-break at equal timestamps: delete wins (tombstone preference). The
  reference's last-writer-wins TreeMap insert is order-dependent; we pick the
  deterministic, conservative resolution so the permutation invariant holds
  exactly.
"""

from __future__ import annotations

import threading

import numpy as np

# Event kinds (u8)
VERTEX_ADD = np.uint8(0)
VERTEX_DELETE = np.uint8(1)
EDGE_ADD = np.uint8(2)
EDGE_DELETE = np.uint8(3)

KIND_NAMES = {0: "vertex_add", 1: "vertex_delete", 2: "edge_add", 3: "edge_delete"}

_GROW = 1.6
_INIT_CAP = 1024


class _Columns:
    """Growable structure-of-arrays block."""

    def __init__(self, spec: dict[str, np.dtype], cap: int = _INIT_CAP):
        self.spec = spec
        self.n = 0
        self.cap = cap
        self.cols = {k: np.empty(cap, dtype=dt) for k, dt in spec.items()}

    def _ensure(self, extra: int) -> None:
        need = self.n + extra
        if need <= self.cap:
            return
        new_cap = max(need, int(self.cap * _GROW) + 1)
        for k in self.cols:
            new = np.empty(new_cap, dtype=self.spec[k])
            new[: self.n] = self.cols[k][: self.n]
            self.cols[k] = new
        self.cap = new_cap

    def append_row(self, **vals) -> int:
        self._ensure(1)
        i = self.n
        for k, v in vals.items():
            self.cols[k][i] = v
        self.n = i + 1
        return i

    def append_batch(self, **arrays) -> tuple[int, int]:
        lens = {len(a) for a in arrays.values()}
        assert len(lens) == 1, f"ragged batch: {lens}"
        m = lens.pop()
        self._ensure(m)
        i = self.n
        for k, a in arrays.items():
            self.cols[k][i : i + m] = a
        self.n = i + m
        return i, i + m

    def view(self, name: str) -> np.ndarray:
        return self.cols[name][: self.n]


class PropertyLog:
    """Timeline of property updates attached to events.

    Mirrors ``MutableProperty.previousState: TreeMap[Long, Any]``
    (``MutableProperty.scala:19``) / ``ImmutableProperty``
    (``ImmutableProperty.scala:9-11``) as flat arrays: each row says
    "event #e set key k to value v". Numeric values live in a float64 column
    (device-capable); strings in a host-side list referenced by index.
    """

    STR_TAG = np.int8(1)
    NUM_TAG = np.int8(0)

    def __init__(self) -> None:
        self._key_ids: dict[str, int] = {}
        self._key_names: list[str] = []
        self._immutable: set[int] = set()
        self._rows = _Columns(
            {
                "event": np.dtype(np.int64),
                "key": np.dtype(np.int32),
                "tag": np.dtype(np.int8),
                "num": np.dtype(np.float64),
                "sref": np.dtype(np.int64),
            }
        )
        self._strings: list[str] = []

    def key_id(self, name: str, immutable: bool = False) -> int:
        kid = self._key_ids.get(name)
        if kid is None:
            kid = len(self._key_names)
            self._key_ids[name] = kid
            self._key_names.append(name)
        if immutable:
            self._immutable.add(kid)
        return kid

    def key_name(self, kid: int) -> str:
        return self._key_names[kid]

    def is_immutable(self, kid: int) -> bool:
        return kid in self._immutable

    @property
    def keys(self) -> list[str]:
        return list(self._key_names)

    def append(self, event_row: int, props: dict[str, object] | None) -> None:
        if not props:
            return
        for name, value in props.items():
            immutable = False
            if name.startswith("!"):  # "!name" marks immutable, like Type props
                immutable, name = True, name[1:]
            kid = self.key_id(name, immutable=immutable)
            if isinstance(value, str):
                self._rows.append_row(
                    event=event_row,
                    key=kid,
                    tag=self.STR_TAG,
                    num=np.nan,
                    sref=len(self._strings),
                )
                self._strings.append(value)
            else:
                self._rows.append_row(
                    event=event_row,
                    key=kid,
                    tag=self.NUM_TAG,
                    num=float(value),
                    sref=-1,
                )

    @property
    def n(self) -> int:
        return self._rows.n

    def column(self, name: str) -> np.ndarray:
        return self._rows.view(name)

    def string(self, sref: int) -> str:
        return self._strings[sref]


class EventLog:
    """The append-only log. Thread-safe appends (ingestion workers share it).

    Columns: ``time`` (event time, i64), ``kind`` (u8), ``src`` (vertex id or
    edge source, i64), ``dst`` (edge destination, -1 for vertex events).
    Row index doubles as the event id referenced by ``PropertyLog``.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._rows = _Columns(
            {
                "time": np.dtype(np.int64),
                "kind": np.dtype(np.uint8),
                "src": np.dtype(np.int64),
                "dst": np.dtype(np.int64),
            }
        )
        self.props = PropertyLog()
        # Monotone high-water marks maintained on append (cheap, lock-held).
        self.min_time: int = np.iinfo(np.int64).max
        self.max_time: int = np.iinfo(np.int64).min
        self._version = 0  # bumped per append; snapshot cache invalidation key
        # bumped per compact_to only: `version` moves on both appends and
        # compactions, so version alone cannot tell pure growth (a pinned
        # prefix is still a prefix of the live log) from a history rewrite
        # (it is not). Incremental re-pinning (SweepBuilder.repin) needs
        # exactly that distinction.
        self._compactions = 0
        self._frozen = False

    # -- single-event API (the reference's EntityStorage verbs,
    #    EntityStorage.scala:73 vertexAdd / :237 edgeAdd / :148 vertexRemoval /
    #    :327 edgeRemoval) --

    def add_vertex(self, time: int, vid: int, props: dict | None = None) -> None:
        with self._lock:
            row = self._rows.append_row(time=time, kind=VERTEX_ADD, src=vid, dst=-1)
            self.props.append(row, props)
            self._touch(time)

    def delete_vertex(self, time: int, vid: int) -> None:
        with self._lock:
            self._rows.append_row(time=time, kind=VERTEX_DELETE, src=vid, dst=-1)
            self._touch(time)

    def add_edge(self, time: int, src: int, dst: int, props: dict | None = None) -> None:
        with self._lock:
            row = self._rows.append_row(time=time, kind=EDGE_ADD, src=src, dst=dst)
            self.props.append(row, props)
            self._touch(time)

    def delete_edge(self, time: int, src: int, dst: int) -> None:
        with self._lock:
            self._rows.append_row(time=time, kind=EDGE_DELETE, src=src, dst=dst)
            self._touch(time)

    # -- bulk API (hot ingestion path) --

    def append_batch(
        self,
        time: np.ndarray,
        kind: np.ndarray,
        src: np.ndarray,
        dst: np.ndarray,
        props: list | None = None,
    ) -> tuple[int, int]:
        """Append a batch of events; returns the [start, end) row range.

        ``props`` is a list of ``(batch_offset, dict)`` property payloads,
        appended under the SAME lock acquisition as the event rows — a
        freeze() concurrent with ingestion must never observe events whose
        properties are still pending (compact_to would drop them)."""
        with self._lock:
            rng = self._rows.append_batch(
                time=np.asarray(time, np.int64),
                kind=np.asarray(kind, np.uint8),
                src=np.asarray(src, np.int64),
                dst=np.asarray(dst, np.int64),
            )
            if props:
                start = rng[0]
                for off, p in props:
                    self.props.append(start + off, p)
            if len(time):
                t = np.asarray(time)
                self.min_time = min(self.min_time, int(t.min()))
                self.max_time = max(self.max_time, int(t.max()))
            self._version += 1
            return rng

    def _touch(self, time: int) -> None:
        self.min_time = min(self.min_time, int(time))
        self.max_time = max(self.max_time, int(time))
        self._version += 1

    # -- read access (snapshot builder) --

    @property
    def n(self) -> int:
        return self._rows.n

    @property
    def version(self) -> int:
        return self._version

    @property
    def compactions(self) -> int:
        return self._compactions

    def column(self, name: str) -> np.ndarray:
        """Zero-copy view of a column. Stable under concurrent appends
        (appends only extend past ``n``; rows < n are immutable)."""
        return self._rows.view(name)

    def arrays(self) -> dict[str, np.ndarray]:
        return {k: self._rows.view(k) for k in ("time", "kind", "src", "dst")}

    def __len__(self) -> int:
        return self._rows.n

    # ---- consistent snapshots & in-place compaction ----

    def freeze(self) -> "EventLog":
        """A consistent, immutable prefix snapshot taken under the lock.

        Rows already written never mutate, so the snapshot just pins matching
        (event-count, prop-count) lengths — O(1), no copying. Use for
        checkpointing / compaction concurrent with live appends."""
        with self._lock:
            n = self._rows.n
            p_n = self.props._rows.n
            rows = self._rows
            props = self.props
            # bounds/version read under the same lock that appends hold, so
            # they describe exactly the pinned n rows
            min_t, max_t, ver = self.min_time, self.max_time, self._version
            compactions = self._compactions
        out = EventLog.__new__(EventLog)
        out._lock = threading.Lock()
        out._frozen = True
        out._rows = _FrozenColumns(rows, n)
        out.props = _FrozenProps(props, p_n)
        out.min_time = min_t
        out.max_time = max_t
        out._version = ver
        out._compactions = compactions
        return out

    def pin(self) -> "EventLog":
        """Consistent read snapshot for view building — O(1). Views built
        over a pin keep serving their history even if the underlying log is
        compacted (``compact_to``) mid-job."""
        return self if self._frozen else self.freeze()

    def compact_to(self, new_log: "EventLog", since_row: int) -> None:
        """Atomically replace this log's contents with `new_log` + any events
        appended here at or after `since_row` (the live-ingestion tail). All
        holders of this EventLog object observe the compacted history."""
        with self._lock:
            n = self._rows.n
            if n > since_row:
                base = new_log.n
                new_log._rows.append_batch(**{
                    c: self._rows.view(c)[since_row:n].copy()
                    for c in ("time", "kind", "src", "dst")})
                pe = self.props.column("event")
                for r in np.flatnonzero(pe >= since_row):
                    tag = int(self.props.column("tag")[r])
                    if tag == self.props.STR_TAG:
                        sref = len(new_log.props._strings)
                        new_log.props._strings.append(
                            self.props.string(int(self.props.column("sref")[r])))
                    else:
                        sref = -1
                    new_log.props.key_id(
                        self.props.key_name(int(self.props.column("key")[r])))
                    new_log.props._rows.append_row(
                        event=base + int(pe[r]) - since_row,
                        key=int(self.props.column("key")[r]),
                        tag=tag,
                        num=float(self.props.column("num")[r]),
                        sref=sref)
                new_log.props._immutable |= self.props._immutable
            if n > since_row:
                tail_t = self._rows.view("time")[since_row:n]
                tail_min, tail_max = int(tail_t.min()), int(tail_t.max())
            else:
                tail_min = np.iinfo(np.int64).max
                tail_max = np.iinfo(np.int64).min
            self._rows = new_log._rows
            self.props = new_log.props
            if new_log.n:
                self.min_time = min(new_log.min_time, tail_min)
                self.max_time = max(new_log.max_time, tail_max)
            else:
                self.min_time, self.max_time = tail_min, tail_max
            self._version += 1
            self._compactions += 1

    def __repr__(self) -> str:  # pragma: no cover
        return f"EventLog(n={self.n}, time=[{self.min_time},{self.max_time}])"


class _FrozenColumns:
    """Read-only fixed-length view over a _Columns block."""

    def __init__(self, inner: _Columns, n: int):
        self._cols = {k: inner.cols[k][:n] for k in inner.cols}
        self.n = n

    def view(self, name: str) -> np.ndarray:
        return self._cols[name]

    def append_row(self, **kw):  # pragma: no cover
        raise RuntimeError("frozen log is read-only")

    append_batch = append_row


class _FrozenProps:
    """Read-only fixed-length view over a PropertyLog."""

    STR_TAG = PropertyLog.STR_TAG
    NUM_TAG = PropertyLog.NUM_TAG

    def __init__(self, inner: PropertyLog, n: int):
        self._inner = inner
        self.n = n
        self._key_ids = inner._key_ids
        self._immutable = inner._immutable

    @property
    def keys(self):
        return self._inner.keys

    def key_name(self, kid: int) -> str:
        return self._inner.key_name(kid)

    def is_immutable(self, kid: int) -> bool:
        return self._inner.is_immutable(kid)

    def column(self, name: str) -> np.ndarray:
        return self._inner._rows.cols[name][: self.n]

    def string(self, sref: int) -> str:
        return self._inner.string(sref)

    @property
    def _strings(self):
        return self._inner._strings
