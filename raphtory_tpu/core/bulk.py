"""Bulk static loading — add-only edge streams at 100M-event scale.

The general ingest path (EventLog → SweepBuilder fold) supports deletes,
revivals, properties and out-of-order arrival; its comparison sorts cost
minutes at 10^8 events on one host core. Bulk imports of APPEND-ONLY edge
streams (the Twitter-2010 / warehouse-export shape) need none of that
generality, and collapse to radix passes:

* one stable radix argsort of the packed (src, dst) keys builds the global
  pair table (stability keeps each pair's events time-ascending);
* per-hop fold state comes from DELTA SLICES of the time-sorted stream —
  hop j re-sorts only the events in (T_{j-1}, T_j], so a sweep's fold cost
  is one radix of the first slice plus near-nothing per later hop (the
  same incremental idea as ``core/sweep.SweepBuilder``, specialised until
  it is just sorts);
* "latest event <= T" per pair/vertex is the last row of each run.

The native radix kernel (``rtpu_radix_argsort_u64``) carries the hot
sorts here; the native batched searchsorted serves the general engines'
pair lookups (``GlobalTables.eng_pos``). Numpy fallbacks keep every path
correct without the library.

Output plugs straight into the hop-batched columnar engine
(``engine/hopbatch.run_columns``): the scale benchmark's whole load+fold
is seconds of radix passes instead of the general fold's minutes.
"""

from __future__ import annotations

import numpy as np

from ..engine.device_sweep import _pad_large
from ..native import lib as _native


class BulkGraph:
    """GlobalTables-shaped static tables over a bulk-loaded pair set."""

    def __init__(self, n_vertices: int, uniq_packed: np.ndarray,
                 tdtype) -> None:
        self.n = int(n_vertices)
        self.m = len(uniq_packed)
        self.n_pad = _pad_large(self.n)
        self.m_pad = _pad_large(self.m)
        self.tdtype = tdtype
        self.tmin = np.iinfo(tdtype).min
        self.uv = np.arange(self.n, dtype=np.int64)

        src_r = (uniq_packed >> np.uint64(32)).astype(np.int64)
        dst_r = (uniq_packed & np.uint64(0xFFFFFFFF)).astype(np.int64)
        flip = (dst_r.astype(np.uint64) << np.uint64(32)) \
            | src_r.astype(np.uint64)
        order = _native.radix_argsort_u64(flip)       # engine (dst, src) sort
        self.eng_of_rank = np.empty(self.m, np.int64)
        self.eng_of_rank[order] = np.arange(self.m)
        self.e_src = np.full(self.m_pad, self.n_pad - 1, np.int32)
        self.e_dst = np.full(self.m_pad, self.n_pad - 1, np.int32)
        self.e_src[: self.m] = src_r[order]
        self.e_dst[: self.m] = dst_r[order]


def _run_last(sorted_keys: np.ndarray):
    """Indices of the LAST row of each equal-key run (keys sorted)."""
    if len(sorted_keys) == 0:
        return np.empty(0, np.int64)
    change = np.flatnonzero(sorted_keys[1:] != sorted_keys[:-1])
    return np.concatenate([change, [len(sorted_keys) - 1]])


def bulk_hop_columns(src, dst, times, hop_times, n_vertices: int | None = None):
    """Load an ADD-ONLY edge stream and fold it at each hop time.

    ``src``/``dst``: dense non-negative int vertex ids (< 2^31);
    ``times``: non-decreasing event times (sort the stream first if not);
    ``hop_times``: ascending fold timestamps.

    Returns ``(bulk, e_lat, e_alive, v_lat, v_alive)`` with the column
    arrays shaped hop-major ``[H, m_pad]`` / ``[H, n_pad]`` in the bulk
    graph's engine order — exactly what ``engine.hopbatch.run_columns``
    consumes (row ``j`` = fold state at ``hop_times[j]``).
    """
    src = np.ascontiguousarray(src, np.int64)
    dst = np.ascontiguousarray(dst, np.int64)
    times = np.ascontiguousarray(times, np.int64)
    hop_times = [int(x) for x in hop_times]
    if sorted(hop_times) != hop_times:
        raise ValueError("hop_times must ascend")
    if len(times):
        # one comparison pass (no int64 diff temp at 100M scale); endpoints
        # then bound the whole sorted array in O(1)
        if not np.all(times[:-1] <= times[1:]):
            raise ValueError("bulk loader needs a time-sorted stream — "
                             "argsort by time first (radix_argsort_u64)")
        if times[0] < 0 or times[-1] >= 2**31:
            raise ValueError("bulk loader needs times in [0, 2^31) — use "
                             "the general EventLog path for wider clocks")
    id_max = max(int(src.max()), int(dst.max())) if len(src) else -1
    n_v = int(n_vertices) if n_vertices is not None else id_max + 1
    if len(src) and (src.min() < 0 or dst.min() < 0 or id_max >= 2**31):
        raise ValueError("bulk loader needs dense ids in [0, 2^31)")
    if id_max >= n_v:
        # an out-of-range id would silently mark PADDING vertices alive and
        # skew every column's rank mass — refuse instead
        raise ValueError(
            f"vertex id {id_max} >= n_vertices ({n_v})")

    tdtype = np.int32
    packed = (src.astype(np.uint64) << np.uint64(32)) | dst.astype(np.uint64)
    order_all = _native.radix_argsort_u64(packed)
    sp = packed[order_all]
    uniq = sp[_run_last(sp)]          # last-of-run == unique, sorted
    bulk = BulkGraph(n_v, uniq, tdtype)
    # pair rank per EVENT, recovered from the one full-stream sort — the
    # per-slice folds below then never binary-search the pair table
    starts = np.ones(len(sp), bool)
    starts[1:] = sp[1:] != sp[:-1]
    rank_sorted = np.cumsum(starts) - 1
    rank_of_event = np.empty(len(sp), np.int64)
    rank_of_event[order_all] = rank_sorted

    H = len(hop_times)
    e_lat = np.full((H, bulk.m_pad), bulk.tmin, tdtype)
    e_alive = np.zeros((H, bulk.m_pad), bool)
    v_lat = np.full((H, bulk.n_pad), bulk.tmin, tdtype)
    v_alive = np.zeros((H, bulk.n_pad), bool)

    lat_e = np.full(bulk.m_pad, bulk.tmin, tdtype)   # running engine-order
    al_e = np.zeros(bulk.m_pad, bool)
    lat_v = np.full(bulk.n_pad, bulk.tmin, tdtype)
    al_v = np.zeros(bulk.n_pad, bool)

    prev = 0
    for j, T in enumerate(hop_times):
        hi = int(np.searchsorted(times, T, side="right"))
        if hi > prev:
            ps = rank_of_event[prev:hi].astype(np.uint64)
            ts = times[prev:hi]
            o = _native.radix_argsort_u64(ps)        # stable: time-asc in run
            pss, tss = ps[o], ts[o]
            last = _run_last(pss)
            pos = bulk.eng_of_rank[pss[last].astype(np.int64)]
            lat_e[pos] = tss[last].astype(tdtype)
            al_e[pos] = True
            # vertex fold: interleave endpoints so the concatenated stream
            # stays time-ascending (both endpoints of an event adjacent)
            vk = np.empty(2 * (hi - prev), np.uint64)
            vk[0::2] = src[prev:hi].astype(np.uint64)
            vk[1::2] = dst[prev:hi].astype(np.uint64)
            vt = np.repeat(ts, 2)
            ov = _native.radix_argsort_u64(vk)
            vks, vts = vk[ov], vt[ov]
            lastv = _run_last(vks)
            vid = vks[lastv].astype(np.int64)
            lat_v[vid] = vts[lastv].astype(tdtype)
            al_v[vid] = True
            prev = hi
        e_lat[j] = lat_e          # contiguous row memcpy in this layout
        e_alive[j] = al_e
        v_lat[j] = lat_v
        v_alive[j] = al_v

    return bulk, e_lat, e_alive, v_lat, v_alive
