"""Bulk static loading — add-only edge streams at 100M-event scale.

The general ingest path (EventLog → SweepBuilder fold) supports deletes,
revivals, properties and out-of-order arrival; its comparison sorts cost
minutes at 10^8 events on one host core. Bulk imports of APPEND-ONLY edge
streams (the Twitter-2010 / warehouse-export shape) need none of that
generality, and collapse to radix passes:

* one stable radix argsort of the packed (src, dst) keys builds the global
  pair table (stability keeps each pair's events time-ascending);
* per-hop fold state comes from DELTA SLICES of the time-sorted stream —
  hop j re-sorts only the events in (T_{j-1}, T_j], so a sweep's fold cost
  is one radix of the first slice plus near-nothing per later hop (the
  same incremental idea as ``core/sweep.SweepBuilder``, specialised until
  it is just sorts);
* "latest event <= T" per pair/vertex is the last row of each run.

The native radix kernel (``rtpu_radix_argsort_u64``) carries the hot
sorts here; the native batched searchsorted serves the general engines'
pair lookups (``GlobalTables.eng_pos``). Numpy fallbacks keep every path
correct without the library.

Output plugs straight into the hop-batched columnar engine
(``engine/hopbatch.run_columns``): the scale benchmark's whole load+fold
is seconds of radix passes instead of the general fold's minutes.
"""

from __future__ import annotations

import numpy as np

from ..engine.device_sweep import _pad_large
from ..native import lib as _native


class BulkGraph:
    """GlobalTables-shaped static tables over a bulk-loaded pair set."""

    def __init__(self, n_vertices: int, uniq_packed: np.ndarray,
                 tdtype) -> None:
        self.n = int(n_vertices)
        self.m = len(uniq_packed)
        self.n_pad = _pad_large(self.n)
        self.m_pad = _pad_large(self.m)
        self.tdtype = tdtype
        self.tmin = np.iinfo(tdtype).min
        self.uv = np.arange(self.n, dtype=np.int64)

        src_r = (uniq_packed >> np.uint64(32)).astype(np.int64)
        dst_r = (uniq_packed & np.uint64(0xFFFFFFFF)).astype(np.int64)
        flip = (dst_r.astype(np.uint64) << np.uint64(32)) \
            | src_r.astype(np.uint64)
        order = _native.radix_argsort_u64(flip)       # engine (dst, src) sort
        self.eng_of_rank = np.empty(self.m, np.int64)
        self.eng_of_rank[order] = np.arange(self.m)
        self.e_src = np.full(self.m_pad, self.n_pad - 1, np.int32)
        self.e_dst = np.full(self.m_pad, self.n_pad - 1, np.int32)
        self.e_src[: self.m] = src_r[order]
        self.e_dst[: self.m] = dst_r[order]


def _run_last(sorted_keys: np.ndarray):
    """Indices of the LAST row of each equal-key run (keys sorted)."""
    if len(sorted_keys) == 0:
        return np.empty(0, np.int64)
    change = np.flatnonzero(sorted_keys[1:] != sorted_keys[:-1])
    return np.concatenate([change, [len(sorted_keys) - 1]])


def _bulk_load(src, dst, times, hop_times, n_vertices):
    """Shared bulk-loader head: validation + ONE global pair radix.

    Returns ``(bulk, src, dst, times, hop_times, pos_of_event)`` where
    ``pos_of_event[i]`` is event i's ENGINE position — recovered from the
    single full-stream sort, so per-hop folds never binary-search the pair
    table again."""
    src = np.ascontiguousarray(src, np.int64)
    dst = np.ascontiguousarray(dst, np.int64)
    times = np.ascontiguousarray(times, np.int64)
    hop_times = [int(x) for x in hop_times]
    if sorted(hop_times) != hop_times:
        raise ValueError("hop_times must ascend")
    if len(times):
        # one comparison pass (no int64 diff temp at 100M scale); endpoints
        # then bound the whole sorted array in O(1)
        if not np.all(times[:-1] <= times[1:]):
            raise ValueError("bulk loader needs a time-sorted stream — "
                             "argsort by time first (radix_argsort_u64)")
        if times[0] < 0 or times[-1] >= 2**31:
            raise ValueError("bulk loader needs times in [0, 2^31) — use "
                             "the general EventLog path for wider clocks")
    id_max = max(int(src.max()), int(dst.max())) if len(src) else -1
    n_v = int(n_vertices) if n_vertices is not None else id_max + 1
    if len(src) and (src.min() < 0 or dst.min() < 0 or id_max >= 2**31):
        raise ValueError("bulk loader needs dense ids in [0, 2^31)")
    if id_max >= n_v:
        # an out-of-range id would silently mark PADDING vertices alive and
        # skew every column's rank mass — refuse instead
        raise ValueError(
            f"vertex id {id_max} >= n_vertices ({n_v})")

    packed = (src.astype(np.uint64) << np.uint64(32)) | dst.astype(np.uint64)
    order_all = _native.radix_argsort_u64(packed)
    sp = packed[order_all]
    uniq = sp[_run_last(sp)]          # last-of-run == unique, sorted
    bulk = BulkGraph(n_v, uniq, np.int32)
    starts = np.ones(len(sp), bool)
    starts[1:] = sp[1:] != sp[:-1]
    rank_sorted = np.cumsum(starts) - 1
    pos_of_event = np.empty(len(sp), np.int64)
    pos_of_event[order_all] = bulk.eng_of_rank[rank_sorted]
    return bulk, src, dst, times, hop_times, pos_of_event


def _slice_fold(lat_e, lat_v, src, dst, times, pos_of_event, prev, hi,
                tdtype, al_e=None, al_v=None):
    """Fold the time-ascending event slice [prev, hi) into running
    engine-order rows by DIRECT fancy assignment: numpy integer-array
    assignment keeps the last value for duplicate indices, so "latest
    event <= T" is just "write in stream order" — no per-slice sort.
    Endpoints interleave so the flattened vertex write order stays
    time-ascending. Returns the slice's raw (pos, ts, vk, vts) updates for
    callers that ship them as deltas instead of folding on host
    (``lat_e``/``lat_v`` may be None to skip the writes entirely)."""
    pos = pos_of_event[prev:hi]
    ts = times[prev:hi].astype(tdtype)
    vk = np.empty(2 * (hi - prev), np.int64)
    vk[0::2] = src[prev:hi]
    vk[1::2] = dst[prev:hi]
    vts = np.repeat(ts, 2)
    if lat_e is not None:
        lat_e[pos] = ts
        lat_v[vk] = vts
    if al_e is not None:
        al_e[pos] = True
        al_v[vk] = True
    return pos, ts, vk, vts


def bulk_hop_columns(src, dst, times, hop_times, n_vertices: int | None = None):
    """Load an ADD-ONLY edge stream and fold it at each hop time.

    ``src``/``dst``: dense non-negative int vertex ids (< 2^31);
    ``times``: non-decreasing event times (sort the stream first if not);
    ``hop_times``: ascending fold timestamps.

    Returns ``(bulk, e_lat, e_alive, v_lat, v_alive)`` with the column
    arrays shaped hop-major ``[H, m_pad]`` / ``[H, n_pad]`` in the bulk
    graph's engine order — exactly what ``engine.hopbatch.run_columns``
    consumes (row ``j`` = fold state at ``hop_times[j]``).

    Per-slice folds are DIRECT fancy assignments: the stream is
    time-ascending and numpy integer-array assignment keeps the last value
    for duplicate indices, so "latest event <= T" is just "write in stream
    order" — no per-slice sort at all.
    """
    bulk, src, dst, times, hop_times, pos_of_event = _bulk_load(
        src, dst, times, hop_times, n_vertices)
    tdtype = bulk.tdtype

    H = len(hop_times)
    e_lat = np.full((H, bulk.m_pad), bulk.tmin, tdtype)
    e_alive = np.zeros((H, bulk.m_pad), bool)
    v_lat = np.full((H, bulk.n_pad), bulk.tmin, tdtype)
    v_alive = np.zeros((H, bulk.n_pad), bool)

    lat_e = np.full(bulk.m_pad, bulk.tmin, tdtype)   # running engine-order
    al_e = np.zeros(bulk.m_pad, bool)
    lat_v = np.full(bulk.n_pad, bulk.tmin, tdtype)
    al_v = np.zeros(bulk.n_pad, bool)

    prev = 0
    for j, T in enumerate(hop_times):
        hi = int(np.searchsorted(times, T, side="right"))
        if hi > prev:
            _slice_fold(lat_e, lat_v, src, dst, times, pos_of_event,
                        prev, hi, tdtype, al_e=al_e, al_v=al_v)
            prev = hi
        e_lat[j] = lat_e          # contiguous row memcpy in this layout
        e_alive[j] = al_e
        v_lat[j] = lat_v
        v_alive[j] = al_v

    return bulk, e_lat, e_alive, v_lat, v_alive


def bulk_hop_deltas(src, dst, times, hop_times, n_vertices: int | None = None):
    """Like ``bulk_hop_columns`` but O(base + deltas) output for
    DEVICE-SIDE column reconstruction (``engine.hopbatch.run_scale_columns``)
    — at 10^8-edge scale the materialised ``[H, m_pad]`` columns cannot
    cross the host link, so hop 0's full fold state ships once and each
    later hop ships only its raw update pairs (the device scatter-max
    dedupes; times ascend so max == latest).

    Returns ``(bulk, base_e_lat, base_v_lat, deltas_e, deltas_v)`` where
    ``base_*`` are the engine-order fold rows at ``hop_times[0]`` (int32,
    INT32_MIN = never seen — add-only, so alive == lat >= 0) and
    ``deltas_*[j]`` is hop j's ``(positions, times)`` pair (empty for
    j = 0, the base)."""
    bulk, src, dst, times, hop_times, pos_of_event = _bulk_load(
        src, dst, times, hop_times, n_vertices)
    tdtype = bulk.tdtype

    base_e = np.full(bulk.m_pad, bulk.tmin, tdtype)
    base_v = np.full(bulk.n_pad, bulk.tmin, tdtype)
    empty = (np.empty(0, np.int32), np.empty(0, tdtype))
    deltas_e, deltas_v = [empty], [empty]

    hi0 = int(np.searchsorted(times, hop_times[0], side="right"))
    _slice_fold(base_e, base_v, src, dst, times, pos_of_event, 0, hi0,
                tdtype)

    # later hops: raw update pairs only — the folds happen on device
    prev = hi0
    for T in hop_times[1:]:
        hi = int(np.searchsorted(times, T, side="right"))
        pos, ts, vk, vts = _slice_fold(
            None, None, src, dst, times, pos_of_event, prev, hi, tdtype)
        deltas_e.append((pos.astype(np.int32), ts))
        deltas_v.append((vk.astype(np.int32), vts))
        prev = hi
    return bulk, base_e, base_v, deltas_e, deltas_v
