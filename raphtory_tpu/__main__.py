"""``python -m raphtory_tpu`` — the single-node server entrypoint.

The reference deploys as a JVM binary whose role and wiring come from env
vars (``Server.scala:28-62`` reading SPOUTCLASS/ROUTERCLASS etc.); the
TPU-native equivalent boots a ``NodeRuntime`` (ingestion + storage +
analysis + REST + metrics + archivist) from the same env-var ergonomics
(``RAPHTORY_TPU_*`` — utils/config.Settings) plus a couple of CLI flags:

    python -m raphtory_tpu serve --csv edges.csv
    python -m raphtory_tpu serve --random 100000
    python -m raphtory_tpu bench            # delegates to bench.py configs

``serve`` starts the REST job API (:8081) and Prometheus metrics (:11600),
ingests the given sources, and then keeps serving queries until SIGINT.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading


def _serve(args) -> int:
    if args.platform:
        # must precede any backend use; this image's sitecustomize
        # force-registers the TPU tunnel, and env vars alone cannot
        # override it once jax is imported
        import jax

        jax.config.update("jax_platforms", args.platform)
    from .cluster.runtime import NodeRuntime
    from .ingestion.parser import (CsvEdgeListParser, IntCsvEdgeListParser,
                                   JsonUpdateParser)
    from .ingestion.source import FileSource, RandomSource
    from .utils.config import Settings

    settings = Settings.from_env()
    rt = NodeRuntime(settings=settings)
    parsers = {
        "int-csv": IntCsvEdgeListParser,
        "csv": CsvEdgeListParser,
        "json": JsonUpdateParser,
    }
    for path in args.csv or []:
        rt.add_source(FileSource(path, skip_header=args.skip_header),
                      parsers[args.format]())
    if args.random:
        rt.add_source(RandomSource(args.random, seed=args.seed))
    rt.start(rest=True, metrics=True)
    print(f"raphtory_tpu node up: REST :{settings.rest_port} "
          f"metrics :{settings.metrics_port}", flush=True)

    def _ingest_summary(aborted=lambda: False):
        # the event-TIME range is the operator's cheapest sanity check: a
        # CSV parsed with the wrong column order (e.g. time,src,dst fed to
        # the src,dst,time parser) ingests "successfully" with vertex ids
        # as timestamps, and latest_time gives it away at a glance.
        # earliest/latest are O(1) maintained marks, not column scans
        n = sum(rt.pipeline.counts.values())
        rng = (f"event times [{rt.graph.earliest_time}, "
               f"{rt.graph.latest_time}], " if len(rt.graph.log)
               else "empty log, ")
        word = "aborted" if aborted() else "done"
        print(f"ingest {word}: {n} updates, {rng}"
              f"safe_time={rt.graph.safe_time()}", flush=True)

    rt.ingest(wait=False)
    if args.ingest_only:
        # default signal behaviour stays in place: Ctrl-C / SIGTERM abort
        # the blocking join instead of being swallowed by a no-op handler
        rt.pipeline.join()
        _ingest_summary()
    else:
        stop = threading.Event()
        threading.Thread(
            target=lambda: (rt.pipeline.join(),
                            _ingest_summary(aborted=stop.is_set)),
            name="ingest-summary", daemon=True).start()
        signal.signal(signal.SIGINT, lambda *a: stop.set())
        signal.signal(signal.SIGTERM, lambda *a: stop.set())
        stop.wait()
    rt.stop()
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="raphtory_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sv = sub.add_parser("serve", help="run a single-node analysis server")
    sv.add_argument("--csv", action="append",
                    help="ingest a CSV edge-list file (repeatable)")
    sv.add_argument("--format", choices=["int-csv", "csv", "json"],
                    default="int-csv")
    sv.add_argument("--skip-header", action="store_true")
    sv.add_argument("--random", type=int, default=0,
                    help="also ingest N synthetic updates (RandomSource)")
    sv.add_argument("--seed", type=int, default=0)
    sv.add_argument("--ingest-only", action="store_true",
                    help="exit after sources drain (batch import mode)")
    sv.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu) before backend init")
    # add_help=False so `bench -h` forwards to bench.py's own parser
    sub.add_parser("bench", add_help=False,
                   help="run the benchmark suite; extra arguments are "
                        "forwarded to bench.py "
                        "(e.g. --config headline --device cpu)")
    # bench flags (--config, --suite, ...) pass through untouched —
    # argparse's REMAINDER is unreliable for option-like tokens after a
    # subcommand, so unknowns are collected instead
    args, extra = ap.parse_known_args(argv)
    if extra and args.cmd != "bench":
        ap.error(f"unrecognized arguments: {' '.join(extra)}")

    if args.cmd == "bench":
        import pathlib
        import runpy

        sys.argv = ["bench.py"] + extra
        runpy.run_path(str(pathlib.Path(__file__).resolve().parent.parent
                           / "bench.py"), run_name="__main__")
        return 0
    return _serve(args)


if __name__ == "__main__":
    sys.exit(main())
