"""Resilience plane — deterministic fault injection, unified retry
policy, circuit breakers, degraded-result bookkeeping.

The package is stdlib-only (no jax import) so the failpoint checks can
live in every layer — ingestion, transfer, device dispatch, scheduler,
REST — without dragging runtime deps into lint-time imports. Telemetry
(flight-recorder instants, metrics) is reached lazily and never raises:
the resilience plane must not be a new way to fail.

* :mod:`.faults` — named failpoints armed via ``RTPU_FAULTS``
  (``site=error|hang|slow:prob[:count][:seed]``); seeded, so chaos runs
  replay exactly; a disarmed check is one global-bool load.
* :mod:`.policy` — the one :class:`RetryPolicy` (failure classification,
  capped exponential backoff with full jitter, deadline-aware budgets)
  that every retry loop in the repo derives from.
* :mod:`.breaker` — per-peer closed→open→half-open circuit breakers so a
  dead peer costs one probe per window, not one socket timeout per
  federation pass.
* :mod:`.degrade` — bounded ledger of degraded (partial) results served,
  graded into ``/healthz``.

Operator surface: ``/faultz`` (jobs/rest.py) renders :func:`faultz`;
``RTPU_FAULT_DUMP`` writes the same document at interpreter exit (the CI
failure artifact). Full story: docs/RESILIENCE.md.
"""

from __future__ import annotations

from .breaker import BREAKERS, CircuitBreaker
from .degrade import DEGRADED
from .faults import FaultError, faultz, fire
from .policy import RetryPolicy, is_transient_message

__all__ = [
    "BREAKERS",
    "CircuitBreaker",
    "DEGRADED",
    "FaultError",
    "RetryPolicy",
    "faultz",
    "fire",
    "is_transient_message",
]
