"""Per-peer circuit breakers — closed → open → half-open.

A dead ``/clusterz`` peer used to cost one full socket timeout per
federation pass (every snapshot, every advisor tick). Behind a breaker
it costs ``threshold`` timeouts ONCE, then one half-open probe per
``window_s`` until it answers again; every skipped pass renders
``reachable: false`` with the breaker as evidence instead of paying the
wire.

Transitions emit ``breaker.state`` flight-recorder instants and set the
``raphtory_breaker_state{peer}`` gauge (0 closed, 1 half-open, 2 open)
— both OUTSIDE the breaker lock, repo rule. The clock is injectable so
tests drive window expiry without sleeping.

Knobs: ``RTPU_BREAKER_THRESHOLD`` consecutive failures open the breaker
(default 3); ``RTPU_BREAKER_WINDOW_S`` seconds open before the next
half-open probe (default 10).
"""

from __future__ import annotations

import os
import threading
import time

_STATE_CODE = {"closed": 0, "half-open": 1, "open": 2}


def breaker_threshold() -> int:
    """``RTPU_BREAKER_THRESHOLD`` — consecutive failures that open."""
    try:
        return max(1, int(
            os.environ.get("RTPU_BREAKER_THRESHOLD", "") or 3))
    except ValueError:
        return 3


def breaker_window_s() -> float:
    """``RTPU_BREAKER_WINDOW_S`` — open dwell before a half-open probe."""
    try:
        return float(os.environ.get("RTPU_BREAKER_WINDOW_S", "") or 10.0)
    except ValueError:
        return 10.0


def _note_state(name: str, state: str, failures: int) -> None:
    try:
        from ..obs.metrics import METRICS

        METRICS.breaker_state.labels(name).set(_STATE_CODE[state])
    except Exception:
        pass
    try:
        from ..obs.trace import TRACER

        TRACER.instant("breaker.state", peer=name, state=state,
                       failures=failures)
    except Exception:
        pass
    try:
        from ..obs import journal

        if journal.enabled():
            journal.emit("breaker", {"peer": name, "state": state,
                                     "failures": failures})
    except Exception:
        pass


class CircuitBreaker:
    def __init__(self, name: str, threshold: int | None = None,
                 window_s: float | None = None, clock=time.monotonic):
        self.name = name
        self.threshold = threshold or breaker_threshold()
        self.window_s = (window_s if window_s is not None
                         else breaker_window_s())
        self._clock = clock
        self._mu = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        self._last_ok: float | None = None
        self._last_error = ""

    # ---- the two calls every guarded site makes ----

    def allow(self) -> bool:
        """May this call go to the wire? Open breakers say no until the
        window elapses, then exactly ONE caller gets the half-open
        probe; the rest keep fast-failing until it resolves."""
        transition = None
        with self._mu:
            if self._state == "closed":
                allowed = True
            elif self._state == "open":
                if self._clock() - self._opened_at < self.window_s:
                    allowed = False
                else:
                    self._state = "half-open"
                    self._probing = True
                    transition = ("half-open", self._failures)
                    allowed = True
            elif self._probing:     # half-open, probe already in flight
                allowed = False
            else:                   # half-open, probe slot free
                self._probing = True
                allowed = True
        if transition is not None:
            _note_state(self.name, *transition)
        return allowed

    def record(self, ok: bool, error: str = "") -> None:
        """Report the call's outcome (every allowed call must)."""
        transition = None
        with self._mu:
            if ok:
                self._last_ok = self._clock()
                self._last_error = ""
                if self._state != "closed":
                    transition = ("closed", self._failures)
                self._state = "closed"
                self._failures = 0
                self._probing = False
            else:
                self._failures += 1
                self._last_error = error[:200]
                if self._state == "half-open":
                    self._probing = False
                    self._state = "open"
                    self._opened_at = self._clock()
                    transition = ("open", self._failures)
                elif (self._state == "closed"
                        and self._failures >= self.threshold):
                    self._state = "open"
                    self._opened_at = self._clock()
                    transition = ("open", self._failures)
            failures = self._failures
        if transition is not None:
            _note_state(self.name, transition[0], failures)

    # ---- observability ----

    def state(self) -> str:
        with self._mu:
            return self._state

    def snapshot(self) -> dict:
        with self._mu:
            now = self._clock()
            out = {
                "state": self._state,
                "failures": self._failures,
                "threshold": self.threshold,
                "window_s": self.window_s,
            }
            if self._state == "open":
                out["retry_in_s"] = round(
                    max(0.0, self.window_s - (now - self._opened_at)), 3)
            if self._last_ok is not None:
                out["seconds_since_last_ok"] = round(now - self._last_ok, 3)
            if self._last_error:
                out["last_error"] = self._last_error
            return out


class BreakerRegistry:
    """Bounded name → breaker map (cap 256: peer sets are small; a
    runaway name source must not grow this without bound — RT011)."""

    def __init__(self, cap: int = 256):
        self._mu = threading.Lock()
        self._cap = cap
        self._breakers: dict[str, CircuitBreaker] = {}

    def get(self, name: str, **kw) -> CircuitBreaker:
        with self._mu:
            br = self._breakers.get(name)
            if br is None:
                if len(self._breakers) >= self._cap:
                    # evict the oldest-inserted entry (dict order)
                    self._breakers.pop(next(iter(self._breakers)))
                br = self._breakers[name] = CircuitBreaker(name, **kw)
            return br

    def snapshot(self) -> dict:
        with self._mu:
            brs = list(self._breakers.values())
        return {br.name: br.snapshot() for br in brs}

    def reset(self) -> None:
        with self._mu:
            self._breakers.clear()


BREAKERS = BreakerRegistry()
