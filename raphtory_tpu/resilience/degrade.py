"""Degraded-result ledger — who got partial answers, and how recently.

The degraded-serving contract (docs/RESILIENCE.md): a query whose retry
budget or deadline expires MID-sweep returns the hops it finished,
marked ``degraded: true`` with a ``coveredTime`` watermark, instead of
hanging or 500ing. This module is the bounded process-wide record of
those serves: ``/healthz`` grades ``degraded`` while any landed inside
the fast budget window, and ``/faultz`` renders the tally.

Everything is O(ring); a chaos storm serving thousands of partial
results cannot grow this without bound (RT011).
"""

from __future__ import annotations

import threading
import time
from collections import deque


class DegradedLedger:
    def __init__(self, ring: int = 256, clock=time.monotonic):
        self._mu = threading.Lock()
        self._clock = clock
        self._total = 0
        self._recent: deque[tuple[float, str, str]] = deque(maxlen=ring)

    def note(self, job_id: str, reason: str,
             covered_time: int | None = None) -> None:
        with self._mu:
            self._total += 1
            self._recent.append((self._clock(), str(job_id), reason))
            total = self._total
        try:
            from ..obs.metrics import METRICS

            METRICS.degraded_results.labels(reason).inc()
        except Exception:
            pass
        try:
            from ..obs.trace import TRACER

            TRACER.instant("degrade.serve", job_id=str(job_id),
                           reason=reason, covered_time=covered_time,
                           total=total)
        except Exception:
            pass
        try:
            from ..obs import journal

            if journal.enabled():
                journal.emit("degrade", {
                    "job_id": str(job_id), "reason": reason,
                    "covered_time": covered_time, "total": total})
        except Exception:
            pass

    def recent(self, window_s: float) -> int:
        """Degraded results served inside the trailing window."""
        now = self._clock()
        with self._mu:
            return sum(1 for t, _, _ in self._recent
                       if now - t <= window_s)

    def total(self) -> int:
        with self._mu:
            return self._total

    def snapshot(self) -> dict:
        now = self._clock()
        with self._mu:
            last = [{"job_id": j, "reason": r,
                     "seconds_ago": round(now - t, 3)}
                    for t, j, r in list(self._recent)[-8:]]
            return {"total": self._total, "last": last}

    def reset(self) -> None:
        with self._mu:
            self._total = 0
            self._recent.clear()


DEGRADED = DegradedLedger()
