"""The one retry policy — classification, capped backoff with full
jitter, deadline-aware budgets.

Every retry loop in the repo derives from here (the transfer engine's
wire loop, peer scrapes/forwards). The shape all of them share:

* **Classify first.** Programming errors (bad shapes, real OOM,
  INVALID_ARGUMENT) re-raise immediately — burning a backoff schedule
  on a bug hides it for ~minutes (rtpulint RT002 exists because of
  this). Transient transport wobbles retry.
* **Capped exponential backoff with FULL jitter.** The classic
  ``base * 2**attempt`` makes every failed caller wake in lockstep and
  re-stampede whatever just fell over; drawing uniformly from
  ``[0, min(cap, base * 2**(attempt-1))]`` (AWS-style full jitter)
  decorrelates the herd. ``RTPU_RETRY_CAP_S`` bounds the ceiling.
* **Deadline-aware budgets.** A caller holding a scheduler
  ``deadline_ms`` passes the absolute deadline; the policy refuses to
  start a sleep that would overrun it and re-raises the last error
  instead — the jobs layer then degrades honestly rather than blowing
  the deadline inside a sleep.

Telemetry per decision (never on the zero-failure hot path):
``retry.attempt`` flight-recorder instants and
``raphtory_retry_attempts_total{site,outcome}`` with outcome one of
``retry`` / ``fatal`` / ``exhausted`` / ``deadline``.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass, field
from typing import Callable

# Shared failure-classification markers (the transfer engine's
# classifier reuses these; tests/test_transfer_pipeline.py pins them).
TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "CANCELLED",
    "Connection reset",
    "connection reset",
    "Socket closed",
    "socket closed",
)

PROGRAMMING_MARKERS = (
    "INVALID_ARGUMENT",
    "RESOURCE_EXHAUSTED",
    "UNIMPLEMENTED",
    "NOT_FOUND",
    "FAILED_PRECONDITION",
)


def is_transient_message(msg: str) -> bool | None:
    """Classify an error MESSAGE: True (transient marker), False
    (programming marker), None (no marker — caller decides by type)."""
    if any(m in msg for m in PROGRAMMING_MARKERS):
        return False
    if any(m in msg for m in TRANSIENT_MARKERS):
        return True
    return None


def default_classify(e: BaseException) -> bool:
    """Generic transient test for non-device sites: injected faults and
    marked/transport errors retry, everything else is a bug."""
    from .faults import FaultError

    if isinstance(e, FaultError):
        return True
    verdict = is_transient_message(str(e))
    if verdict is not None:
        return verdict
    return isinstance(e, (TimeoutError, ConnectionError, OSError))


def retry_cap_s() -> float:
    """``RTPU_RETRY_CAP_S`` — backoff ceiling shared by every policy."""
    try:
        return float(os.environ.get("RTPU_RETRY_CAP_S", "") or 60.0)
    except ValueError:
        return 60.0


_METRICS_SENTINEL = object()
_METRICS = _METRICS_SENTINEL


def _metrics():
    global _METRICS
    if _METRICS is _METRICS_SENTINEL:
        try:
            from ..obs.metrics import METRICS as _M

            _METRICS = _M
        except Exception:
            _METRICS = None
    return _METRICS


def note_attempt(site: str, outcome: str, attempt: int,
                 wait: float) -> None:
    """Record one retry decision (metric + instant, never raises) —
    public so loops that keep their own structure (the transfer
    engine's pipelined slice retry) report through the same channel."""
    m = _metrics()
    if m is not None:
        try:
            m.retry_attempts.labels(site, outcome).inc()
        except Exception:
            pass
    try:
        from ..obs.trace import TRACER

        TRACER.instant("retry.attempt", site=site, outcome=outcome,
                       attempt=attempt, wait_s=round(wait, 4))
    except Exception:
        pass


@dataclass
class RetryPolicy:
    """``attempts`` total tries (1 = no retries); ``base_s`` doubles per
    attempt, capped at ``cap_s`` (None = the ``RTPU_RETRY_CAP_S`` knob);
    ``classify(e)`` True means retryable; ``rng`` is injectable so tests
    replay jitter deterministically."""

    attempts: int = 4
    base_s: float = 1.0
    cap_s: float | None = None
    classify: Callable[[BaseException], bool] = field(
        default=default_classify)
    rng: random.Random = field(default_factory=lambda: random)  # type: ignore[assignment]

    def backoff_s(self, attempt: int) -> float:
        """Full-jitter wait before re-attempt ``attempt`` (1-based):
        uniform over [0, min(cap, base * 2**(attempt-1))]."""
        cap = self.cap_s if self.cap_s is not None else retry_cap_s()
        ceiling = min(cap, self.base_s * (2.0 ** (attempt - 1)))
        if ceiling <= 0.0:
            return 0.0
        return self.rng.uniform(0.0, ceiling)

    def run(self, fn, *, site: str = "generic",
            deadline: float | None = None,
            clock: Callable[[], float] = time.monotonic,
            on_retry: Callable[[int, BaseException, float], None]
            | None = None):
        """Call ``fn()`` under the policy. ``deadline`` is an absolute
        ``clock()`` timestamp: a backoff that would overrun it re-raises
        the last transient error instead of sleeping through it."""
        err: BaseException | None = None
        for attempt in range(1, max(1, self.attempts) + 1):
            try:
                return fn()
            except Exception as e:
                if not self.classify(e):
                    note_attempt(site, "fatal", attempt, 0.0)
                    raise
                err = e
                if attempt >= self.attempts:
                    note_attempt(site, "exhausted", attempt, 0.0)
                    raise
                wait = self.backoff_s(attempt)
                if deadline is not None and clock() + wait > deadline:
                    note_attempt(site, "deadline", attempt, wait)
                    raise
                note_attempt(site, "retry", attempt, wait)
                if on_retry is not None:
                    on_retry(attempt, e, wait)
                if wait > 0.0:
                    time.sleep(wait)
        raise err if err is not None else RuntimeError("unreachable")
