"""Deterministic failpoints, armed via ``RTPU_FAULTS``.

Spec grammar (comma-separated entries)::

    site=mode:prob[:count][:seed]

    RTPU_FAULTS="transfer.wire=error:0.1:3:42,peer.scrape=hang:1.0"

* ``site`` — one of :data:`SITES` (unknown names log a warning and are
  skipped: an operator typo is data, never a crash).
* ``mode`` — ``error`` raises :class:`FaultError` (classified transient
  by every retry loop: the message carries ``UNAVAILABLE``), ``hang``
  sleeps ``RTPU_FAULT_HANG_S`` (bounded — a CI chaos run must never
  wedge forever), ``slow`` sleeps ``RTPU_FAULT_SLOW_S``.
* ``prob`` — per-pass injection probability in [0, 1].
* ``count`` — max injections (empty/omitted = unlimited).
* ``seed`` — RNG seed; omitted derives a stable one from the site name,
  so the SAME spec replays the SAME injection sequence, run after run.

The disarmed fast path is one module-global bool load — production with
``RTPU_FAULTS`` unset pays ~ns per check. ``RTPU_RESIL=0`` is the kill
switch: the plane stays disarmed even with a spec set (the bench's A/B
off arm). Armed state is parsed once at import; tests and the chaos
bench re-arm explicitly via :func:`arm` / :func:`disarm`.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
import zlib

_log = logging.getLogger("raphtory_tpu.resilience")

SITES = (
    "transfer.wire",      # utils/transfer.py — the device_put wire
    "device.dispatch",    # engine/device_sweep.py — compiled-program run
    "peer.scrape",        # obs/cluster.py — /clusterz federation fetch
    "ingest.sink",        # ingestion/router.py — shard delivery
    "watermark.advance",  # ingestion/watermark.py — fence advance
    "sched.dispatch",     # jobs/scheduler.py — coalesced batch dispatch
    "rest.handler",       # jobs/rest.py — request handler entry
)

MODES = ("error", "hang", "slow")


class FaultError(RuntimeError):
    """An injected failure. The message carries ``UNAVAILABLE`` so every
    classifier in the repo (transfer's ``_is_transient``, the shared
    :class:`~raphtory_tpu.resilience.policy.RetryPolicy`) files it
    transient — injected faults exercise the retry path, they don't
    masquerade as programming errors."""


def hang_s() -> float:
    """``RTPU_FAULT_HANG_S`` — bounded sleep for ``hang`` injections."""
    try:
        return float(os.environ.get("RTPU_FAULT_HANG_S", "") or 30.0)
    except ValueError:
        return 30.0


def slow_s() -> float:
    """``RTPU_FAULT_SLOW_S`` — sleep for ``slow`` injections."""
    try:
        return float(os.environ.get("RTPU_FAULT_SLOW_S", "") or 0.1)
    except ValueError:
        return 0.1


class _Failpoint:
    __slots__ = ("site", "mode", "prob", "count", "seed", "rng",
                 "injected", "passes")

    def __init__(self, site: str, mode: str, prob: float,
                 count: int | None, seed: int):
        self.site = site
        self.mode = mode
        self.prob = prob
        self.count = count          # None = unlimited
        self.seed = seed
        self.rng = random.Random(seed)
        self.injected = 0
        self.passes = 0

    def snapshot(self) -> dict:
        return {"mode": self.mode, "prob": self.prob, "count": self.count,
                "seed": self.seed, "passes": self.passes,
                "injected": self.injected,
                "exhausted": (self.count is not None
                              and self.injected >= self.count)}


_MU = threading.Lock()
_ARMED: dict[str, _Failpoint] = {}
_SPEC = ""
_ACTIVE = False     # the disarmed fast path reads ONLY this


def _derived_seed(site: str) -> int:
    # stable across processes and runs — hash() is salted, crc32 is not
    return zlib.crc32(site.encode())


def _parse(spec: str) -> dict[str, _Failpoint]:
    armed: dict[str, _Failpoint] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        try:
            site, rest = entry.split("=", 1)
            site = site.strip()
            parts = rest.split(":")
            mode = parts[0].strip()
            prob = float(parts[1])
            count = int(parts[2]) if len(parts) > 2 and parts[2] else None
            seed = (int(parts[3]) if len(parts) > 3 and parts[3]
                    else _derived_seed(site))
        except (ValueError, IndexError) as e:
            _log.warning("RTPU_FAULTS: malformed entry %r skipped (%s)",
                         entry, e)
            continue
        if site not in SITES:
            _log.warning("RTPU_FAULTS: unknown site %r skipped; sites=%s",
                         site, ",".join(SITES))
            continue
        if mode not in MODES:
            _log.warning("RTPU_FAULTS: unknown mode %r for %s skipped; "
                         "modes=%s", mode, site, ",".join(MODES))
            continue
        if not 0.0 <= prob <= 1.0:
            _log.warning("RTPU_FAULTS: prob %r for %s outside [0,1], "
                         "skipped", prob, site)
            continue
        armed[site] = _Failpoint(site, mode, prob, count, seed)
    return armed


def _resil_enabled() -> bool:
    """``RTPU_RESIL`` — the plane-wide kill switch (``0`` keeps every
    failpoint disarmed even when ``RTPU_FAULTS`` is set)."""
    return os.environ.get("RTPU_RESIL", "1") != "0"


def arm(spec: str | None = None) -> dict:
    """(Re)arm from ``spec`` (default: the ``RTPU_FAULTS`` env var).
    Returns the armed-sites snapshot. Tests and the chaos bench call
    this directly; production arms once at import."""
    global _ARMED, _SPEC, _ACTIVE
    if spec is None:
        spec = os.environ.get("RTPU_FAULTS", "")
    with _MU:
        _SPEC = spec
        _ARMED = _parse(spec) if (spec and _resil_enabled()) else {}
        _ACTIVE = bool(_ARMED)
        return {s: fp.snapshot() for s, fp in _ARMED.items()}


def disarm() -> None:
    """Drop every armed failpoint (the disarmed fast path returns)."""
    global _ARMED, _SPEC, _ACTIVE
    with _MU:
        _ARMED = {}
        _SPEC = ""
        _ACTIVE = False


def _instant(name: str, **attrs) -> None:
    try:
        from ..obs.trace import TRACER

        TRACER.instant(name, **attrs)
    except Exception:   # telemetry must never become a second fault
        pass


def _journal_emit(kind: str, data: dict) -> None:
    try:
        from ..obs import journal

        if journal.enabled():
            journal.emit(kind, data)
    except Exception:   # durability must never become a second fault
        pass


def fire(site: str) -> None:
    """The failpoint check. Disarmed: one global load, returns. Armed:
    roll the site's seeded RNG; inject by raising / sleeping."""
    if not _ACTIVE:
        return
    with _MU:
        fp = _ARMED.get(site)
        if fp is None:
            return
        fp.passes += 1
        if fp.count is not None and fp.injected >= fp.count:
            return
        if fp.rng.random() >= fp.prob:
            return
        fp.injected += 1
        n, mode = fp.injected, fp.mode
    # the injection itself happens OUTSIDE the registry lock: a hang
    # must stall the caller, not every other failpoint in the process
    _instant("fault.inject", site=site, mode=mode, n=n)
    _journal_emit("fault", {"site": site, "mode": mode, "n": n})
    if mode == "error":
        raise FaultError(f"UNAVAILABLE: injected fault at {site} (#{n})")
    time.sleep(hang_s() if mode == "hang" else slow_s())


def faultz() -> dict:
    """The ``/faultz`` document: armed sites with injection counts,
    breaker states, degraded-results ledger."""
    with _MU:
        sites = {s: fp.snapshot() for s, fp in _ARMED.items()}
        doc = {"enabled": _ACTIVE, "spec": _SPEC, "sites": sites}
    try:
        from .breaker import BREAKERS

        doc["breakers"] = BREAKERS.snapshot()
    except Exception:
        doc["breakers"] = {}
    try:
        from .degrade import DEGRADED

        doc["degraded"] = DEGRADED.snapshot()
    except Exception:
        doc["degraded"] = {}
    return doc


arm()

_fault_dump = os.environ.get("RTPU_FAULT_DUMP")
if _fault_dump:
    import json as _json

    from ..obs import exitdump as _exitdump

    def _dump_faultz(path=_fault_dump):
        with open(path, "w") as f:
            _json.dump(faultz(), f, indent=1)

    _exitdump.register("fault", _dump_faultz)
