"""Resilient host→device transfers for flaky / slow links.

The single-chip rig reaches its TPU through a tunnel that has been
measured to (a) run at tens of MB/s and (b) drop mid-transfer with
``UNAVAILABLE: TPU backend setup/compile error`` when a multi-hundred-MB
``device_put`` is in flight (observed killing a whole scale benchmark 20
minutes in). A monolithic put makes that failure all-or-nothing;
uploading in bounded slices with per-slice retry turns a transient flap
into a pause instead.

This is transport plumbing, not semantics: results are bit-identical to
``jax.device_put``. The reference has no analogue (its graph lives in
the same JVM as the compute — SURVEY.md §1 L3); this is the TPU-native
cost of a disaggregated accelerator.
"""

from __future__ import annotations

import logging
import time

import numpy as np

_log = logging.getLogger(__name__)


def _put_retry(a, retries: int, backoff: float, device):
    import jax

    for attempt in range(retries):
        try:
            x = jax.device_put(a, device)
            x.block_until_ready()   # surface transport errors HERE
            return x
        except Exception as e:  # noqa: BLE001 — runtime transport errors
            if attempt + 1 == retries:
                raise   # no retry follows — don't sleep into the raise
            wait = backoff * (2 ** attempt)
            _log.warning("device_put of %.1f MB failed (%s); retry %d/%d "
                         "in %.0fs", a.nbytes / 2**20, e, attempt + 1,
                         retries, wait)
            time.sleep(wait)


def device_put_chunked(a, *, chunk_bytes: int = 32 << 20, retries: int = 4,
                       backoff: float = 10.0, device=None):
    """``jax.device_put`` in bounded slices with per-slice retry.

    Slices along axis 0 (row groups sized to ``chunk_bytes``), retries
    each slice with exponential backoff, concatenates on device. Arrays
    at or under ``chunk_bytes`` take the single-put path (still
    retried). 0-d and tiny arrays go straight through.
    """
    import jax.numpy as jnp

    a = np.asarray(a)
    if a.ndim == 0 or a.nbytes <= chunk_bytes:
        return _put_retry(a, retries, backoff, device)
    n = a.shape[0]
    per_row = max(1, a.nbytes // n)
    rows = max(1, int(chunk_bytes // per_row))
    parts = [
        _put_retry(np.ascontiguousarray(a[lo: lo + rows]), retries,
                   backoff, device)
        for lo in range(0, n, rows)
    ]
    if len(parts) == 1:
        return parts[0]
    return jnp.concatenate(parts, axis=0)
