"""Pipelined, resilient host→device transfers for flaky / slow links.

The single-chip rig reaches its TPU through a tunnel that has been
measured to (a) run at tens of MB/s and (b) drop mid-transfer with
``UNAVAILABLE: TPU backend setup/compile error`` when a multi-hundred-MB
``device_put`` is in flight (observed killing a whole scale benchmark 20
minutes in). A monolithic put makes that failure all-or-nothing;
uploading in bounded slices with per-slice retry turns a transient flap
into a pause instead.

Round 5's verdict made the next cost plain: the serial slice loop left
the host memcpy, the wire, and the device taking turns idling — each
slice blocked (``block_until_ready``) before the next ``ascontiguousarray``
staging copy even started. ``TransferEngine`` pipelines the stages in the
bulk-synchronous *pseudo-streaming* style (arXiv:1608.07200): a bounded
window (default 2) of in-flight ``device_put`` futures, so slice *i+1*'s
host-side staging overlaps slice *i*'s wire time. Completion (and
therefore per-slice retry) happens only when the window is full or at
drain; the staged host buffer stays alive until its slice completes, so a
transport flap re-ships exactly that slice and the upload resumes
mid-array.

This is transport plumbing, not semantics: results are bit-identical to
``jax.device_put`` (same concatenate-on-device shape/dtype/values). The
reference has no analogue (its graph lives in the same JVM as the compute
— SURVEY.md §1 L3); this is the TPU-native cost of a disaggregated
accelerator.

Knobs and telemetry
-------------------
* ``RTPU_TRANSFER_DEPTH`` — in-flight window depth (default 2; 1 is the
  old fully-serial behaviour, kept as the bench comparison point).
* ``TransferEngine.stats`` / ``shared_engine().stats`` — bytes shipped,
  slice count, retries, per-stage stall seconds (``stage`` = host copy,
  ``wire`` = blocked on an in-flight put), window high-water mark.
* Mirrored into Prometheus when ``obs.metrics`` is importable:
  ``raphtory_h2d_bytes_total``, ``raphtory_h2d_slices_total``,
  ``raphtory_h2d_retries_total``, ``raphtory_h2d_stall_seconds_total
  {stage}``, ``raphtory_h2d_inflight_depth``.
* Per-slice spans in the flight recorder when ``obs.trace`` is importable
  and tracing is on (``RTPU_TRACE``): ``ship.stage`` / ``ship.wire`` /
  ``ship.retry`` with byte counts — stalls as timeline children of the
  sweep, not just counters (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ..analysis.sanitizer import (note_shared as _san_note,
                                  track_shared as _san_track)
from ..resilience import faults as _faults
from ..resilience.policy import (PROGRAMMING_MARKERS as _PROGRAMMING_MARKERS,
                                 TRANSIENT_MARKERS as _TRANSIENT_MARKERS,
                                 RetryPolicy, note_attempt)

_log = logging.getLogger(__name__)

# The classification marker tuples live in resilience/policy.py now (the
# one retry policy every loop derives from); the local names survive for
# the tests that pin them.


def _is_transient(e: BaseException) -> bool:
    """True for transport-flavoured failures (retry), False for
    programming errors (re-raise immediately)."""
    msg = str(e)
    if any(m in msg for m in _TRANSIENT_MARKERS):
        return True
    # a bare XlaRuntimeError with an unrecognised status: the runtime died
    # under us (tunnel teardown often surfaces as INTERNAL) — retryable
    # unless the status says the CALL was wrong
    if type(e).__name__ == "XlaRuntimeError":
        return not any(m in msg for m in _PROGRAMMING_MARKERS)
    return False


def _default_depth() -> int:
    return max(1, int(os.environ.get("RTPU_TRANSFER_DEPTH", 2)))


_METRICS_SENTINEL = object()
_METRICS = _METRICS_SENTINEL


def _metrics():
    """obs.metrics bundle, or None when prometheus isn't importable —
    the transfer layer must work in stripped environments."""
    global _METRICS
    if _METRICS is _METRICS_SENTINEL:
        try:
            from ..obs.metrics import METRICS

            _METRICS = METRICS
        except Exception:
            _METRICS = None
    return _METRICS


_TRACER = None


def _tracer():
    """The process tracer (``obs.trace.TRACER``) — imported lazily so the
    transfer layer stays import-light. ``obs.trace`` is stdlib-only and
    ``obs/__init__`` guards its prometheus/jax imports, so this works in
    the same stripped environments ``_metrics()`` degrades in."""
    global _TRACER
    if _TRACER is None:
        from ..obs.trace import TRACER

        _TRACER = TRACER
    return _TRACER


@dataclass
class TransferStats:
    """Cumulative pipeline telemetry for one engine (or the shared one).

    Mutation goes through :meth:`bump` under the stats' own lock: the
    SHARED engine is driven by every concurrent job thread, and unguarded
    ``+=`` on these counters loses updates under load (the rtpulint v2
    lockset detector catches exactly this shape at runtime)."""

    bytes_shipped: int = 0
    slices: int = 0
    retries: int = 0
    stage_seconds: float = 0.0   # host-side ascontiguousarray staging
    wire_seconds: float = 0.0    # blocked on an in-flight put (window full
    #                              or drain) — the wire stall the pipeline
    #                              exists to hide
    depth_high_water: int = 0
    _mu: threading.Lock = field(default_factory=threading.Lock,
                                repr=False, compare=False)
    #: lockset-sanitizer handle — attached by shared_engine() ONLY (a
    #: tracker registration is permanent, and device_put_chunked builds
    #: a throwaway engine per call)
    _san_tracker: object = field(default=None, repr=False, compare=False)

    def bump(self, **deltas) -> None:
        """Atomically add ``deltas`` to counters; ``depth_high_water`` is
        a max, not a sum. Returns nothing — readers use ``as_dict``."""
        with self._mu:
            for k, v in deltas.items():
                if k == "depth_high_water":
                    if v > self.depth_high_water:
                        self.depth_high_water = v
                else:
                    setattr(self, k, getattr(self, k) + v)
            self._note_shared_write()

    def _note_shared_write(self) -> None:
        """Lockset-sanitizer hook (no-op unless RTPU_SANITIZE installed a
        tracker): every mutation reports under the stats lock, so a
        future unguarded write path shows up as a race finding."""
        _san_note(self._san_tracker, write=True)

    def as_dict(self) -> dict:
        with self._mu:
            return {
                "bytes_shipped": int(self.bytes_shipped),
                "slices": int(self.slices),
                "retries": int(self.retries),
                "stage_stall_seconds": round(self.stage_seconds, 4),
                "wire_stall_seconds": round(self.wire_seconds, 4),
                "inflight_depth_high_water": int(self.depth_high_water),
            }

    def totals(self) -> dict:
        """Cheap cumulative snapshot for periodic samplers (the /slz
        series ring diffs consecutive samples into per-interval rates):
        bytes shipped and combined stage+wire stall seconds."""
        with self._mu:
            return {
                "bytes_shipped": int(self.bytes_shipped),
                "stall_seconds": round(
                    self.stage_seconds + self.wire_seconds, 6),
            }

    def delta_since(self, prior: dict) -> dict:
        """Stats accumulated since a ``prior`` ``as_dict()`` snapshot —
        how benches attribute shared-engine traffic to one timed region.
        The high-water depth is a max, not a counter — reported absolute."""
        now = self.as_dict()
        out = {k: round(now[k] - prior.get(k, 0), 4)
               if isinstance(now[k], float) else now[k] - prior.get(k, 0)
               for k in now}
        out["inflight_depth_high_water"] = now["inflight_depth_high_water"]
        return out


class TransferEngine:
    """Bounded-depth pipelined chunked ``device_put``.

    ``put`` slices along axis 0 (row groups sized to ``chunk_bytes``),
    stages each slice into a contiguous host buffer, issues the put
    WITHOUT blocking, and only completes (blocks + retries) the oldest
    slice when the in-flight window is full — so staging slice *i+1*
    overlaps slice *i*'s wire time. ``depth=1`` reproduces the old serial
    stage→ship→block loop exactly.
    """

    def __init__(self, *, depth: int | None = None,
                 chunk_bytes: int = 32 << 20, retries: int = 4,
                 backoff: float = 10.0, device=None):
        self.depth = max(1, int(depth if depth is not None
                                else _default_depth()))
        self.chunk_bytes = int(chunk_bytes)
        self.retries = int(retries)
        self.backoff = float(backoff)
        self.device = device
        self.stats = TransferStats()
        # the shared policy supplies CAPPED, FULL-JITTER backoff waits:
        # N engines retrying the same dead tunnel no longer wake in
        # lockstep and re-stampede it (docs/RESILIENCE.md)
        self.policy = RetryPolicy(attempts=self.retries,
                                  base_s=self.backoff,
                                  classify=_is_transient)

    # ---- slice lifecycle ----

    def _record_depth(self, n: int) -> None:
        if n > self.stats.depth_high_water:   # racy fast-path read only —
            self.stats.bump(depth_high_water=n)  # bump re-checks locked
            m = _metrics()
            if m is not None:
                m.h2d_inflight_depth.set(n)

    def _stage(self, a):
        """Contiguous host copy of one slice (no-op view when already
        contiguous) — the pipeline's host-memcpy stage."""
        t0 = time.perf_counter()
        with _tracer().span("ship.stage", bytes=int(a.nbytes)):
            staged = np.ascontiguousarray(a)
        dt = time.perf_counter() - t0
        self.stats.bump(stage_seconds=dt)
        m = _metrics()
        if m is not None:
            m.h2d_stall_seconds.labels(stage="stage").inc(dt)
        return staged

    def _issue(self, staged):
        """Non-blocking ``device_put``; a transport error AT ISSUE falls
        back to the blocking retry loop for this slice only."""
        import jax

        self.stats.bump(slices=1, bytes_shipped=staged.nbytes)
        m = _metrics()
        if m is not None:
            m.h2d_bytes.inc(staged.nbytes)
            m.h2d_slices.inc()
        try:
            _faults.fire("transfer.wire")
            return jax.device_put(staged, self.device), staged
        except Exception as e:  # noqa: BLE001 — classified below
            if not _is_transient(e):
                raise
            return self._retry(staged, e), None   # completed synchronously

    def _retry(self, staged, first_err):
        """Blocking re-put of one staged slice under the shared policy's
        capped full-jitter backoff — attempt 1 (the pipelined issue)
        already failed."""
        import jax

        err = first_err
        for attempt in range(1, self.retries):
            wait = self.policy.backoff_s(attempt)
            _log.warning(
                "device_put of %.1f MB failed (%s); retry %d/%d in %.1fs",
                staged.nbytes / 2**20, err, attempt, self.retries - 1, wait)
            note_attempt("transfer.wire", "retry", attempt, wait)
            time.sleep(wait)
            self.stats.bump(retries=1)
            m = _metrics()
            if m is not None:
                m.h2d_retries.inc()
            try:
                with _tracer().span("ship.retry", attempt=attempt,
                                    bytes=int(staged.nbytes)):
                    _faults.fire("transfer.wire")
                    x = jax.device_put(staged, self.device)
                    x.block_until_ready()   # surface transport errors HERE
                return x
            except Exception as e:  # noqa: BLE001 — classified below
                if not _is_transient(e):
                    note_attempt("transfer.wire", "fatal", attempt, 0.0)
                    raise
                err = e
        note_attempt("transfer.wire", "exhausted", self.retries, 0.0)
        raise err

    def _complete(self, item):
        """Block on one in-flight slice; transport failure re-ships it
        from the still-live staged buffer (the upload resumes mid-array)."""
        x, staged = item
        t0 = time.perf_counter()
        if staged is not None:   # None: already completed at issue time
            with _tracer().span("ship.wire", bytes=int(staged.nbytes)):
                try:
                    _faults.fire("transfer.wire")
                    x.block_until_ready()
                except Exception as e:  # noqa: BLE001 — classified below
                    if not _is_transient(e):
                        raise
                    x = self._retry(staged, e)
        dt = time.perf_counter() - t0
        self.stats.bump(wire_seconds=dt)
        m = _metrics()
        if m is not None:
            m.h2d_stall_seconds.labels(stage="wire").inc(dt)
        return x

    # ---- public API ----

    def _slices_of(self, a) -> list:
        """Row-group slices of ``a`` sized to ``chunk_bytes`` (the whole
        array when it fits)."""
        if a.ndim == 0 or a.nbytes <= self.chunk_bytes:
            return [a]
        n = a.shape[0]
        per_row = max(1, a.nbytes // n)
        rows = max(1, int(self.chunk_bytes // per_row))
        return [a[lo: lo + rows] for lo in range(0, n, rows)]

    def put(self, a):
        """``jax.device_put(a)``, pipelined: bit-identical result, bounded
        in-flight window, per-slice retry. Device arrays pass through."""
        import jax
        import jax.numpy as jnp

        if isinstance(a, jax.Array):
            return a
        a = np.asarray(a)
        parts = self._pump([(0, s) for s in self._slices_of(a)])[0]
        if len(parts) == 1:
            return parts[0]
        return jnp.concatenate(parts, axis=0)

    def put_many(self, arrays):
        """Pipelined puts of a LIST of arrays — the in-flight window spans
        array boundaries, so array k+1's staging overlaps array k's wire
        time (the per-dispatch payload ship of the sweep engines). Device
        arrays pass through untouched; order is preserved."""
        import jax
        import jax.numpy as jnp

        plan, out = [], [None] * len(arrays)
        for k, a in enumerate(arrays):
            if isinstance(a, jax.Array):
                out[k] = a
                continue
            plan.extend((k, s) for s in self._slices_of(np.asarray(a)))
        parts = self._pump(plan)
        for k, ps in parts.items():
            out[k] = ps[0] if len(ps) == 1 else jnp.concatenate(ps, axis=0)
        return out

    def _pump(self, plan):
        """Drive the stage→issue→complete pipeline over ``plan`` (a list
        of (key, slice)); returns {key: [device parts in order]}."""
        inflight: deque = deque()
        parts: dict[int, list] = {}
        for key, sl in plan:
            parts.setdefault(key, [])
            while len(inflight) >= self.depth:
                k0, item = inflight.popleft()
                parts[k0].append(self._complete(item))
            staged = self._stage(sl)
            inflight.append((key, self._issue(staged)))
            self._record_depth(len(inflight))
        while inflight:
            k0, item = inflight.popleft()
            parts[k0].append(self._complete(item))
        return parts


_SHARED: TransferEngine | None = None
_SHARED_LOCK = threading.Lock()


def shared_engine() -> TransferEngine:
    """Process-wide engine (env-configured depth) used by the sweep
    engines' payload ships — one stats bundle for the whole process.
    Creation is locked: two REST threads racing the lazy init would
    otherwise each get an engine and split the process stats between
    them (rtpulint RT010)."""
    global _SHARED
    if _SHARED is None:
        with _SHARED_LOCK:
            if _SHARED is None:
                eng = TransferEngine()
                # lockset-sanitizer registration (None unless
                # RTPU_SANITIZE): the SHARED engine's stats are driven by
                # every job thread, so each mutation reports its held
                # lockset. Only here — a registration is permanent, and
                # device_put_chunked builds a throwaway engine per call.
                eng.stats._san_tracker = _san_track("transfer_stats")
                _SHARED = eng
    return _SHARED


def _put_retry(a, retries: int, backoff: float, device):
    """Serial staged put with retry — kept for callers that want one
    blocking slice; transport-error classification shared with the
    engine (programming errors re-raise immediately)."""
    eng = TransferEngine(depth=1, retries=retries, backoff=backoff,
                         device=device)
    staged = eng._stage(np.asarray(a))
    return eng._complete(eng._issue(staged))


def device_put_chunked(a, *, chunk_bytes: int = 32 << 20, retries: int = 4,
                       backoff: float = 10.0, device=None,
                       depth: int | None = None):
    """``jax.device_put`` in bounded slices with per-slice retry and a
    pipelined in-flight window.

    Slices along axis 0 (row groups sized to ``chunk_bytes``), keeps up to
    ``depth`` puts in flight (default ``RTPU_TRANSFER_DEPTH``, 2) so the
    next slice's host staging overlaps the current slice's wire time,
    retries each slice with exponential backoff on TRANSPORT errors only,
    concatenates on device. ``depth=1`` is the old serial loop. 0-d and
    tiny arrays go straight through (still retried)."""
    return TransferEngine(depth=depth, chunk_bytes=chunk_bytes,
                          retries=retries, backoff=backoff,
                          device=device).put(a)
