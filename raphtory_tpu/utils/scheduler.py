"""Named recurring/one-shot background tasks.

The reference wraps the Akka scheduler in ``SchedulerUtil.scala:13-50``
(named recurring + once tasks, cancellable by name) to drive keep-alives,
watermark folds, and archivist cycles. Same surface over threading timers.
"""

from __future__ import annotations

import threading


class Scheduler:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._tasks: dict[str, threading.Timer] = {}
        self._cancelled: set[str] = set()
        self._closed = False

    def recurring(self, name: str, interval_s: float, fn, *args) -> None:
        """Run ``fn`` every ``interval_s`` seconds until cancelled. A crash
        in one tick is recorded on the task and does not stop the next."""

        def tick():
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 — a failing tick must not
                pass           # kill the schedule (reference logs + ticks on)
            # cancel() during a long-running fn must stick: a cancelled
            # name never re-arms (the set is checked under _arm's lock too)
            with self._lock:
                if name in self._cancelled:
                    return
            self._arm(name, interval_s, tick)

        with self._lock:
            self._cancelled.discard(name)  # re-registering revives the name
        self._arm(name, interval_s, tick)

    def once(self, name: str, delay_s: float, fn, *args) -> None:
        def run():
            with self._lock:
                self._tasks.pop(name, None)
            fn(*args)

        self._arm(name, delay_s, run)

    def _arm(self, name: str, delay_s: float, fn) -> None:
        with self._lock:
            if self._closed:
                return
            old = self._tasks.pop(name, None)
            if old is not None:
                old.cancel()
            t = threading.Timer(delay_s, fn)
            t.daemon = True
            self._tasks[name] = t
            t.start()

    def cancel(self, name: str) -> bool:
        with self._lock:
            self._cancelled.add(name)
            t = self._tasks.pop(name, None)
            if t is not None:
                t.cancel()
                return True
            return False

    def shutdown(self) -> None:
        with self._lock:
            self._closed = True
            for t in self._tasks.values():
                t.cancel()
            self._tasks.clear()

    @property
    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._tasks)
