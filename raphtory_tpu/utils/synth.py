"""Synthetic temporal-graph workload generators.

The parity target is the reference's ``RandomSpout`` stress workload
(``examples/random/actors/RandomSpout.scala:27-59``: rate-controlled mix of
30% vertex adds / 70% edge adds over a bounded ID pool, the paper's §6.1
benchmark definition) plus a GAB-like social graph (preferential attachment →
heavy-tailed degrees, timestamped over a long span) standing in for the
README's demo dataset in zero-egress environments.
"""

from __future__ import annotations

import numpy as np

from ..core.events import EDGE_ADD, EDGE_DELETE, VERTEX_ADD, VERTEX_DELETE, EventLog


def random_update_stream(
    n_events: int,
    id_pool: int = 1_000_000,
    seed: int = 0,
    t_start: int = 0,
    t_end: int | None = None,
    mix=(0.3, 0.7, 0.0, 0.0),  # (vertex add, edge add, vertex del, edge del)
):
    """The paper's workload: add-only default mix 30/70; 'worst case' is
    (0.3, 0.4, 0.1, 0.2). Returns columnar arrays ready for
    ``EventLog.append_batch``."""
    rng = np.random.default_rng(seed)
    t_end = t_end if t_end is not None else n_events
    kinds_choice = rng.choice(4, size=n_events, p=list(mix))
    kind_map = np.array([VERTEX_ADD, EDGE_ADD, VERTEX_DELETE, EDGE_DELETE])
    kinds = kind_map[kinds_choice].astype(np.uint8)
    times = np.sort(rng.integers(t_start, t_end, n_events)).astype(np.int64)
    src = rng.integers(0, id_pool, n_events).astype(np.int64)
    dst = rng.integers(0, id_pool, n_events).astype(np.int64)
    dst[(kinds == VERTEX_ADD) | (kinds == VERTEX_DELETE)] = -1
    return times, kinds, src, dst


def bitcoin_like_log(
    n_addresses: int = 20_000,
    n_txs: int = 200_000,
    seed: int = 11,
    t_span: int = 2_600_000,
) -> EventLog:
    """Bitcoin-style transaction graph (``BitcoinRouter`` workload shape):
    address→address payment edges, heavy-tailed sender distribution
    (exchanges / mixers dominate), timestamps over ~a month so hour/day/week
    batched windows are all non-trivial."""
    rng = np.random.default_rng(seed)
    # heavy-tailed senders: Zipf-ish via pareto index into the address pool
    ranks = np.minimum(
        (rng.pareto(1.2, n_txs) * 50).astype(np.int64), n_addresses - 1)
    src = ranks
    dst = rng.integers(0, n_addresses, n_txs).astype(np.int64)
    times = np.sort(rng.integers(0, t_span, n_txs)).astype(np.int64)
    kinds = np.full(n_txs, EDGE_ADD, np.uint8)
    log = EventLog()
    log.append_batch(times, kinds, src, dst)
    return log


def ldbc_like_log(
    n_persons: int = 10_000,
    n_knows: int = 120_000,
    delete_frac: float = 0.1,
    seed: int = 13,
    t_span: int = 2_600_000,
    weighted: bool = False,
) -> EventLog:
    """LDBC-SNB person_knows_person workload shape (``LDBCRouter`` with
    deletion support, ``ldbc/routers/LDBCRouter.scala:291-319``): friendship
    edge adds over the span plus a ``delete_frac`` fraction of later edge
    deletions — windowed views exercise the tombstone path."""
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n_persons, n_knows).astype(np.int64)
    dst = rng.integers(0, n_persons, n_knows).astype(np.int64)
    times = np.sort(rng.integers(0, int(t_span * 0.9), n_knows)).astype(np.int64)
    kinds = np.full(n_knows, EDGE_ADD, np.uint8)
    # delete a sample of existing edges at a later time
    n_del = int(n_knows * delete_frac)
    rows = rng.choice(n_knows, n_del, replace=False)
    d_times = times[rows] + rng.integers(
        1, int(t_span * 0.1), n_del).astype(np.int64)
    d_kinds = np.full(n_del, EDGE_DELETE, np.uint8)
    t_all = np.concatenate([times, d_times])
    k_all = np.concatenate([kinds, d_kinds])
    s_all = np.concatenate([src, src[rows]])
    d_all = np.concatenate([dst, dst[rows]])
    order = np.argsort(t_all, kind="stable")
    props = None
    if weighted:
        # interaction weight on each knows-edge add (SSSP workloads)
        w = np.round(rng.uniform(0.5, 5.0, n_knows), 2)
        is_add = k_all[order] == EDGE_ADD
        props = [(int(off), {"weight": float(w[i])})
                 for i, off in enumerate(np.flatnonzero(is_add))]
    log = EventLog()
    log.append_batch(t_all[order], k_all[order], s_all[order], d_all[order],
                     props=props)
    return log


def gab_like_arrays(
    n_vertices: int = 30_000,
    n_edges: int = 300_000,
    seed: int = 7,
    t_span: int = 2_600_000,
):
    """(src, dst, times) arrays of the GAB-style preferential-attachment
    stream — the raw form the bulk loader (core/bulk.py) ingests without an
    EventLog round-trip."""
    rng = np.random.default_rng(seed)
    # preferential attachment via repeated-endpoint sampling trick: draw dst
    # from previously used endpoints with prob p, else uniform
    src = rng.integers(0, n_vertices, n_edges).astype(np.int64)
    dst = np.empty(n_edges, np.int64)
    pool = rng.integers(0, n_vertices, n_edges)  # fallback uniform draws
    reuse = rng.random(n_edges) < 0.6
    # vectorised approximation: reuse samples index into earlier positions
    earlier = (rng.random(n_edges) * np.maximum(np.arange(n_edges), 1)).astype(np.int64)
    dst[~reuse] = pool[~reuse]
    dst[reuse] = src[earlier[reuse]]
    times = np.sort(rng.integers(0, t_span, n_edges)).astype(np.int64)
    return src, dst, times


def gab_like_log(
    n_vertices: int = 30_000,
    n_edges: int = 300_000,
    seed: int = 7,
    t_span: int = 2_600_000,  # ~a month of seconds
) -> EventLog:
    """GAB-style social graph: preferential attachment (heavy-tailed in-degree,
    one giant component ~ the README demo's 22k-vertex biggest cluster),
    timestamps spread over the span so windowed views are non-trivial."""
    src, dst, times = gab_like_arrays(n_vertices, n_edges, seed, t_span)
    kinds = np.full(n_edges, EDGE_ADD, np.uint8)
    log = EventLog()
    log.append_batch(times, kinds, src, dst)
    return log


def twitter_like_log(
    n_vertices: int = 5_300_000,
    n_edges: int = 100_000_000,
    seed: int = 11,
    t_span: int = 2_600_000,
) -> EventLog:
    """Twitter-2010-class synthetic follow graph (the BASELINE.md scale
    config shape): tens of millions of preferential-attachment edges over a
    month of timestamps. Same generator as ``gab_like_log`` — heavy-tailed
    degrees, one giant component — at a scale where the vertex state stops
    fitting any host cache and the accelerator's memory system is the
    ceiling."""
    return gab_like_log(n_vertices=n_vertices, n_edges=n_edges, seed=seed,
                        t_span=t_span)
