"""Runtime configuration — env-var flags + a typed settings bundle.

The reference's behaviour flags are environment variables read at class-load
(``Utils.scala:22-26``: SAVING/COMPRESSING/ARCHIVING/WINDOWING/LOCAL/DEBUG;
``Server.scala:28-62``: SPOUTCLASS/ROUTERCLASS/PARTITION_MIN/ROUTER_MIN)
plus HOCON for cluster tuning. Here one dataclass carries every knob, with
``Settings.from_env()`` reading the ``RAPHTORY_TPU_*`` namespace so
deployments keep the env-var ergonomics.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, fields


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return default if v is None else int(v)


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return default if v is None else float(v)


def _env_str(name: str, default: str) -> str:
    return os.environ.get(name, default)


def process_index() -> int:
    """This process's index in a multi-process deployment.

    Resolution order: ``RTPU_PROCESS_INDEX`` (explicit — plain
    multi-process deployments that never call ``jax.distributed``), then
    ``jax.process_index()`` when jax is ALREADY imported (a serving
    process always has it; never imported from here, so stripped
    environments and pre-``jax.distributed.initialize`` code paths are
    untouched), else 0."""
    v = os.environ.get("RTPU_PROCESS_INDEX")
    if v:
        try:
            return max(0, int(v))
        except ValueError:
            pass
    import sys

    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:
            return 0
    return 0


def port_stride() -> int:
    """``RTPU_PORT_STRIDE`` (default 1): per-process listen-port offset
    multiplier. 0 disables striding (every process binds the configured
    port verbatim — the single-process behaviour)."""
    try:
        return max(0, int(os.environ.get("RTPU_PORT_STRIDE", "1") or 1))
    except ValueError:
        return 1


def strided_port(base: int, index: int | None = None) -> int:
    """Auto-offset a listen port by this process's index so an N-process
    localhost cluster never collides on the fixed REST/metrics ports:
    ``base + index * RTPU_PORT_STRIDE``. Port 0 (ephemeral, tests) is
    never offset, and process 0 always binds ``base`` — single-process
    deployments see no change."""
    base = int(base)
    if base == 0:
        return 0
    idx = process_index() if index is None else max(0, int(index))
    return base + idx * port_stride()


def configure_compile_cache() -> str | None:
    """Wire JAX's persistent compilation cache to ``RTPU_COMPILE_CACHE_DIR``.

    Short TPU tunnel windows re-pay every XLA compile on each fresh
    process; with a cache dir set, compiled programs persist across runs
    (and across the bench's config subprocesses). The thresholds drop to
    zero so even fast compiles persist — the sweep engines compile many
    small per-shape programs whose compile times sit under JAX's default
    1s floor. Returns the directory when wired, None when the knob is
    unset; called from package import (harmless before jax is first
    used), safe to call again."""
    path = os.environ.get("RTPU_COMPILE_CACHE_DIR", "")
    if not path:
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", path)
    for knob, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(knob, val)
        except (AttributeError, ValueError):   # older jax: keep defaults
            pass
    return path


@dataclass
class Settings:
    """All behaviour flags. Defaults match the reference's defaults where a
    counterpart exists (noted per field)."""

    # feature flags (Utils.scala:22-26)
    saving: bool = False          # SAVING: durable checkpoint after ingest
    compressing: bool = True      # COMPRESSING: run-length history dedup
    archiving: bool = True        # ARCHIVING: drop oldest history under pressure
    windowing: bool = True        # WINDOWING: window queries enabled
    local: bool = True            # LOCAL: single-process deployment
    debug: bool = False           # DEBUG: verbose logging

    # cluster-up gate (WatchDog.scala:66-83; PARTITION_MIN/ROUTER_MIN)
    min_shards: int = 1
    min_sources: int = 1

    # liveness (application.conf:101-152 failure detector + auto-down)
    heartbeat_interval_s: float = 10.0   # keep-alive cadence (refs: 10 s)
    stale_after_s: float = 30.0          # staleness log threshold (refs: 30 s)
    auto_down_after_s: float = 1200.0    # auto-down-unreachable (refs: 20 m)

    # memory governor (Archivist.scala:38-39,56-58)
    archivist_interval_s: float = 60.0
    max_events: int = 50_000_000
    archive_fraction: float = 0.1

    # service ports (AnalysisRestApi.scala:30; application.conf:208-213)
    rest_port: int = 8081
    metrics_port: int = 11600

    # checkpoint directory ("" disables; the Cassandra-saving analogue)
    checkpoint_dir: str = ""

    # result sink directory ("" disables; Utils.scala:107-126 writes rows
    # to an env-configured path — here one file per job under this dir)
    sink_dir: str = ""
    sink_format: str = "jsonl"   # default per-job format: jsonl | csv

    # staged ingestion: >0 bounds a parse→append queue (events) with a
    # backlog gauge — the writer-mailbox shape; 0 = direct appends
    ingest_queue_events: int = 0

    # build the resident View sweep right after ingest (background), so
    # the FIRST REST View is already warm instead of paying the pin
    prewarm: bool = False

    @classmethod
    def from_env(cls, prefix: str = "RAPHTORY_TPU_") -> "Settings":
        kw = {}
        for f in fields(cls):
            name = prefix + f.name.upper()
            if os.environ.get(name) is None:
                continue
            if f.type == "bool":
                kw[f.name] = _env_bool(name, f.default)
            elif f.type == "int":
                kw[f.name] = _env_int(name, f.default)
            elif f.type == "float":
                kw[f.name] = _env_float(name, f.default)
            else:
                kw[f.name] = _env_str(name, f.default)
        return cls(**kw)
