"""Device-resident range sweeps — ship O(delta) bytes per hop, not O(m).

The host-side range sweep (``core/sweep.py`` + ``bsp.run_async``) already
amortises the *fold*: hop T_{i+1} re-folds only the events in (T_i, T_{i+1}].
But it still re-assembles and re-uploads fresh O(m_pad) edge arrays every hop
— per-view local vertex indices change as vertices appear/die, so nothing on
the device can be reused. On a TPU behind a transfer tunnel that H2D traffic
dominates the whole sweep (~124 ms/view at GAB scale for ~40 MFLOP of
PageRank — measured in round 3).

This engine removes the per-hop re-indexing by construction:

* **Global dense index space.** Vertices are indexed by their rank in the
  sorted set of every id the pinned log ever mentions (``SweepBuilder.uv``);
  the edge table is every (src, dst) pair the log ever mentions, sorted once
  by (dst, src). Both are uploaded ONCE. Positions never change across the
  sweep — dead entities are simply masked.
* **Device-resident fold state.** Per-entity ``latest_time / alive /
  first_time`` live in donated device buffers. Each hop ships only the
  touched rows (``SweepBuilder.last_delta``) and scatters them in on device.
* **On-device window masks.** ``in-window(T, W) ⟺ alive ∧ latest ≥ T − W``
  (``Entity.aliveAtWithWindow``, ``Entity.scala:193-201``) is computed on
  device from the resident arrays — masks are never built, packed, or
  transferred by the host.

The reference re-runs its full actor handshake per range hop
(``RangeAnalysisTask.scala:18-35``); the host path amortises the fold; this
engine amortises the *device traffic* too, which is the term that actually
bounds a TPU sweep.

Supported programs: anything that doesn't need occurrence arrays or
edge/vertex properties (property materialisation is a host-side join today —
such programs fall back to the ``bsp`` path, see ``supported()``).
"""

from __future__ import annotations

import functools
import threading as _threading
import time as _time
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from ..core.events import EDGE_ADD, EDGE_DELETE, EventLog
from ..core.snapshot import INT64_MIN, _pad_bucket
from ..core.sweep import _ENC_MASK, _ENC_SHIFT, SweepBuilder
from ..native import lib as _native
from ..obs import ledger as _ledger
from ..obs.trace import TRACER
from ..resilience import faults as _faults
from ..utils.transfer import _metrics
from .bsp import make_mask_runner
from .program import VertexProgram


def sweep_phase_summary(sp, elapsed, fold_seconds, fold_stall_seconds,
                        ship_delta, ship_bytes, n_hops, fold_modes=None):
    """Per-sweep fold/stage/ship/compute phase breakdown, attached to the
    sweep span AND observed into ``raphtory_sweep_phase_seconds{phase}``
    — shared by both sweep engines. ``fold`` is host fold+staging time
    (worker-thread time under the lookahead prefetcher), ``stage``/
    ``ship`` are the transfer engine's staging-copy and wire-wait stalls
    accumulated during THIS sweep (``TransferStats.delta_since``), and
    ``compute`` is the dispatch-loop wall residual (device compute plus
    Python driving) — elapsed minus the fold stall and transfer stalls
    the loop actually waited on. Per-hop numbers are these divided by
    ``n_hops``. Returns the phase dict (engines keep it as
    ``last_phase_seconds``).

    Attribution caveat: the stage/ship deltas come from the PROCESS-WIDE
    shared transfer engine, so when several jobs sweep concurrently each
    summary includes the others' H2D stalls (and compute, the residual,
    shrinks correspondingly). Serial operation — the bench protocol and
    the common single-job server — attributes exactly; for contended
    timelines read the per-slice ``ship.*`` spans, which carry their own
    thread/track, instead of the summary."""
    stage = float(ship_delta.get("stage_stall_seconds", 0.0))
    wire = float(ship_delta.get("wire_stall_seconds", 0.0))
    phases = {
        "fold": float(fold_seconds),
        "stage": stage,
        "ship": wire,
        "compute": max(float(elapsed) - float(fold_stall_seconds)
                       - stage - wire, 0.0),
    }
    m = _metrics()
    if m is not None:
        for ph, sec in phases.items():
            m.sweep_phase_seconds.labels(ph).observe(sec)
    led = _ledger.current()
    if led is not None:
        # per-query cost attribution: the sweep ran on THIS (the job's)
        # thread, so the thread-local ledger is the owning query's
        led.add_sweep(phases, ship_delta, ship_bytes, n_hops,
                      fold_modes=fold_modes)
    sp.set(elapsed_seconds=round(float(elapsed), 6),
           fold_stall_seconds=round(float(fold_stall_seconds), 6),
           ship_bytes=int(ship_bytes), n_hops=int(n_hops),
           **{f"{ph}_seconds": round(sec, 6) for ph, sec in phases.items()})
    return phases


def supported(program: VertexProgram) -> bool:
    """True if `program` can run on the device-resident sweep engine."""
    return (not program.needs_occurrences
            and not program.edge_props
            and not program.vertex_props)


def _pad_large(n: int) -> int:
    """Power-of-two buckets up to 2^16 (compile reuse across small logs),
    then 2^16-multiples — pow2 padding would waste up to 2x of every
    per-edge gather at GAB scale and beyond."""
    if n <= (1 << 16):
        return _pad_bucket(n)
    step = 1 << 16
    return ((n + step - 1) // step) * step


#: per-log cache of the device-uploaded static (src, dst) engine tables —
#: a cold engine over an unchanged log reuses the resident arrays instead
#: of re-shipping 2 * m_pad int32 over the host↔device link per query
_DEVICE_EDGES = weakref.WeakKeyDictionary()


def _device_edges(log, tables):
    """Device (e_src, e_dst) for ``tables``, cached per log (the CALLER's
    log object, not the per-engine pin). The (m, n) key is exact: pairs
    and vertices are never removed from a log, so equal counts mean the
    identical deterministic table (same pair set, same dense ranks, same
    (dst, src) sort). Shared by the hop-batched engines and DeviceSweep."""
    ent = _DEVICE_EDGES.get(log)
    if ent is not None and ent[0] == tables.m and ent[1] == tables.n:
        return ent[2], ent[3]
    from ..utils.transfer import device_put_chunked

    # chunked + retried: at 10^8-pair scale these are the largest single
    # transfers in the system, and a monolithic put through the tunnel is
    # all-or-nothing (it has died mid-put and wedged the link)
    es = device_put_chunked(tables.e_src)
    ed = device_put_chunked(tables.e_dst)
    _DEVICE_EDGES[log] = (tables.m, tables.n, es, ed)
    # resident-buffer gauge (obs/device.py): the static edge tables are
    # the largest long-lived device allocation — weakref-keyed on the
    # SAME log object as the cache above, so the row dies with the entry
    from ..obs import device as _obs_device

    _obs_device.RESIDENT.track(
        log, "edge_tables",
        _obs_device.nbytes_tree((es, ed)), m=tables.m)
    return es, ed


class GlobalTables:
    """Static global-dense-space graph tables over a pinned log: every
    vertex id the log ever mentions (rank in ``uv`` = dense index) and every
    (src, dst) pair, (dst, src)-sorted. Positions never change across a
    sweep — shared by the single-chip ``DeviceSweep`` and the mesh
    ``parallel.sweep.ShardedSweep``."""

    def __init__(self, sw: SweepBuilder):
        if not sw._ok:
            raise ValueError("log has >= 2^31 distinct vertices — the packed "
                             "pair key space is exhausted; use build_view")
        self.uv = sw.uv
        if sw._preseeded:
            # a preseeded sweep's pair table IS the all-pairs table (and
            # never grows) — no second unique over the edge events
            self.all_enc = sw.e_enc
        else:
            is_e = (sw._k == EDGE_ADD) | (sw._k == EDGE_DELETE)
            if is_e.any():
                enc = ((sw._dense(sw._s[is_e]) << _ENC_SHIFT)
                       | sw._dense(sw._d[is_e]))
                self.all_enc = np.unique(enc)
            else:
                self.all_enc = np.empty(0, np.int64)

        self.n = len(self.uv)
        self.m = len(self.all_enc)
        self.n_pad = _pad_large(self.n)
        self.m_pad = _pad_large(self.m)
        # times narrow to i32 when the whole log fits — halves both the
        # resident fold state and the delta bytes, and skips the TPU's
        # emulated 64-bit compares in the per-hop window masks
        tcol = sw._t
        self.tdtype = (
            np.int32 if len(tcol) == 0
            or (tcol.min() > np.iinfo(np.int32).min // 2
                and tcol.max() < np.iinfo(np.int32).max // 2)
            else np.int64)
        self.tmin = np.iinfo(self.tdtype).min

        # engine edge order: (dst, src) — combine-at-destination segment ops
        # run with indices_are_sorted=True (snapshot.py uses the same order)
        flip = ((self.all_enc & _ENC_MASK) << _ENC_SHIFT) \
            | (self.all_enc >> _ENC_SHIFT)
        order = np.argsort(flip)              # engine pos i ← enc rank
        self.eng_of_rank = np.empty(self.m, np.int64)
        self.eng_of_rank[order] = np.arange(self.m)

        self.e_src = np.full(self.m_pad, self.n_pad - 1, np.int32)
        self.e_dst = np.full(self.m_pad, self.n_pad - 1, np.int32)
        eng_enc = self.all_enc[order]
        self.e_src[: self.m] = (eng_enc >> _ENC_SHIFT).astype(np.int32)
        self.e_dst[: self.m] = (eng_enc & _ENC_MASK).astype(np.int32)
        self.vids = np.full(self.n_pad, -1, np.int64)
        self.vids[: self.n] = self.uv

    def eng_pos(self, enc: np.ndarray) -> np.ndarray:
        """Engine positions of packed pair keys (must exist in the log).
        Packed keys are non-negative (dense<<32|dense), so the sorted i64
        table reinterprets as u64 zero-copy for the native parallel
        searchsorted — the hot per-hop lookup at 10^8-pair scale."""
        if len(enc) > (1 << 16) and _native.available():
            idx = _native.searchsorted_u64(
                self.all_enc.view(np.uint64),
                np.ascontiguousarray(enc).view(np.uint64))
            return self.eng_of_rank[idx]
        return self.eng_of_rank[np.searchsorted(self.all_enc, enc)]

    def cast_times(self, a: np.ndarray) -> np.ndarray:
        """i64 fold times → the narrow resident dtype (INT64_MIN pad maps to
        the narrow dtype's min) — shared by every engine over these tables."""
        if self.tdtype == np.int64:
            return a
        return np.where(a == INT64_MIN, self.tmin, a).astype(self.tdtype)


def normalize_windows(windows) -> list[int]:
    """window list → int list with -1 for 'no window' (engine convention)."""
    return [(-1 if w is None else int(w)) for w in windows]


@functools.lru_cache(maxsize=32)
def _compiled_apply(cap_v: int, cap_e: int, tdt: str):
    """Scatter one (padded) delta chunk into the six fold-state buffers.
    Chunk capacities are fixed per sweep, so this compiles exactly once;
    pad rows carry index -1 and are dropped by the scatter."""

    def apply(v_lat, v_alive, v_first, e_lat, e_alive, e_first,
              v_idx, vd_lat, vd_alive, vd_first,
              e_idx, ed_lat, ed_alive, ed_first):
        v_lat = v_lat.at[v_idx].set(vd_lat, mode="drop")
        v_alive = v_alive.at[v_idx].set(vd_alive, mode="drop")
        v_first = v_first.at[v_idx].set(vd_first, mode="drop")
        e_lat = e_lat.at[e_idx].set(ed_lat, mode="drop")
        e_alive = e_alive.at[e_idx].set(ed_alive, mode="drop")
        e_first = e_first.at[e_idx].set(ed_first, mode="drop")
        return v_lat, v_alive, v_first, e_lat, e_alive, e_first

    return _ledger.instrument(
        "device_sweep.apply",
        jax.jit(apply, donate_argnums=(0, 1, 2, 3, 4, 5)))


@functools.lru_cache(maxsize=256)
def _compiled_run(program: VertexProgram, n: int, m: int, k: int, tdt: str):
    """Mask-compute + superstep program over the resident fold state —
    one compile per (program, shapes, #windows), shared across hops AND
    across DeviceSweep instances of the same padded size."""
    core = make_mask_runner(program, n, m, k)
    tdt = jnp.dtype(tdt)

    def run(v_lat, v_alive, v_first, e_lat, e_alive, e_first,
            vids, e_src, e_dst, time, windows):
        # window-mask compares run in the narrow time dtype: the resident
        # lat values fit it by construction, and lo clamps into range (a
        # clamped lo only widens the window past every real timestamp)
        info = jnp.iinfo(tdt)
        lo = jnp.clip(time - windows, info.min, info.max).astype(tdt)[:, None]
        nowin = (windows < 0)[:, None]
        v_masks = v_alive[None, :] & (nowin | (v_lat[None, :] >= lo))
        e_masks = e_alive[None, :] & (nowin | (e_lat[None, :] >= lo))
        # the Edges/Context contract is i64 times; only widen when the
        # program actually reads them (pad slots map to INT64_MIN exactly)
        def widen(a):
            if a.dtype == jnp.int64:
                return a
            return jnp.where(a == info.min, jnp.iinfo(jnp.int64).min,
                             a.astype(jnp.int64))
        if program.needs_vertex_times:
            v_lat, v_first = widen(v_lat), widen(v_first)
        if program.needs_edge_times:
            e_lat, e_first = widen(e_lat), widen(e_first)
        return core(v_masks, e_masks, vids, v_lat, v_first,
                    e_src, e_dst, e_lat, e_first, time, windows, {}, {})

    return _ledger.instrument(
        f"device_sweep.superstep.{type(program).__name__}", jax.jit(run))


class DeviceSweep:
    """Ascending-time range sweep with device-resident fold state.

    Drives a ``SweepBuilder`` for the host fold (delta semantics identical to
    ``build_view`` — killList propagation, delete-wins, revival), mirrors the
    touched rows into fixed-position device buffers, and dispatches compiled
    superstep programs whose window masks are derived on device.

    ``run(program, T, ...)`` returns ``(result, steps)`` as device arrays
    (async — block with ``jax.block_until_ready`` when needed). Results are
    in the GLOBAL dense vertex space: row i is vertex ``self.uv[i]``.
    """

    def __init__(self, log: EventLog):
        # fold state only (shells are vertex-side) — no add-row tracking
        self.sw = SweepBuilder(log, track_rows=False, preseed_pairs=True)
        self.tables = GlobalTables(self.sw)
        t = self.tables
        self.uv = t.uv
        self.all_enc = t.all_enc
        self.n, self.m = t.n, t.m
        self.n_pad, self.m_pad = t.n_pad, t.m_pad
        self._eng_of_rank = t.eng_of_rank

        # static device uploads — shared per log across sweeps (a repeat
        # View/rebuild over an unchanged log must not re-pay the transfer);
        # the host copies are not needed again on the single-chip path —
        # free them rather than pin O(m_pad + n_pad) numpy for the sweep's
        # lifetime
        self.e_src, self.e_dst = _device_edges(log, t)
        self.vids = jnp.asarray(t.vids)
        t.e_src = t.e_dst = t.vids = None

        # fold-state buffers (donated through every delta application), in
        # the narrow time dtype the log fits (tables.tdtype)
        self.tdtype = t.tdtype
        self._tmin = t.tmin
        tdt = jnp.dtype(self.tdtype)
        self._bufs = (
            jnp.full((self.n_pad,), self._tmin, tdt),    # v_lat
            jnp.zeros((self.n_pad,), bool),              # v_alive
            jnp.full((self.n_pad,), self._tmin, tdt),    # v_first
            jnp.full((self.m_pad,), self._tmin, tdt),    # e_lat
            jnp.zeros((self.m_pad,), bool),              # e_alive
            jnp.full((self.m_pad,), self._tmin, tdt),    # e_first
        )
        # resident-buffer gauge (obs/device.py): the fold-state buffers
        # live exactly as long as this sweep — weakref-keyed on self
        from ..obs import device as _obs_device

        _obs_device.RESIDENT.track(
            self, "fold_state",
            _obs_device.nbytes_tree(self._bufs)
            + _obs_device.nbytes_tree((self.vids,)))
        # delta chunk capacities: big enough that a typical hop is one chunk,
        # fixed so the scatter program compiles exactly once per sweep shape
        self.cap_v = max(1024, self.n_pad // 4)
        self.cap_e = max(4096, self.m_pad // 16)
        self.t_now: int | None = None
        #: host seconds spent folding + staging (includes worker-thread time
        #: when run_sweep pipelines) and fold-state bytes staged for H2D
        self.fold_seconds = 0.0
        #: fold seconds split by pipeline mode (serial lane vs forked
        #: parallel folds) — the resource ledger's fold breakdown; single
        #: writer per mode (the one prefetch worker, or the dispatch
        #: thread's consume), like fold_seconds itself
        self.fold_mode_seconds: dict = {}
        self.ship_bytes = 0
        #: run_sweep only: seconds the dispatch loop spent WAITING on the
        #: lookahead fold — 0 means the fold fully hid behind device compute
        self.fold_stall_seconds = 0.0
        #: the LAST run_sweep's fold/stage/ship/compute breakdown
        #: (``sweep_phase_summary``) — the per-sweep phase summary
        self.last_phase_seconds: dict = {}
        # a failure between fold and device apply leaves t_now ahead of
        # _bufs (the lookahead fold may even have advanced PAST the failed
        # hop) — the next fold must take the full-refresh path, never the
        # time==t_now noop or a delta scatter onto stale buffers
        self._stale = False

    # ---- incremental re-pin (live serving) ----

    def repin(self, live_log) -> str:
        """Adopt rows appended to ``live_log`` since this sweep's pin
        (``SweepBuilder.repin``). On ``"extended"`` everything stays
        valid — the dense spaces are unchanged, so the static device
        tables, the fold-state buffers and ``t_now`` keep describing the
        same coordinate space, and the next ``advance`` folds exactly
        the appended suffix as one delta instead of a from-scratch
        rebuild. Returns ``"noop"`` / ``"extended"`` / ``"rebuild"``;
        after ``"rebuild"`` the sweep must be DISCARDED (its pin may
        already be rebound past the decision point)."""
        if self._stale:
            return "rebuild"   # buffers behind the clock: re-pin fresh
        n_old = len(self.sw._t)
        status = self.sw.repin(live_log)
        if status != "extended":
            return status
        t_new = self.sw._t[n_old:]
        if self.tdtype == np.int32 and len(t_new) and not (
                int(t_new.min()) > np.iinfo(np.int32).min // 2
                and int(t_new.max()) < np.iinfo(np.int32).max // 2):
            return "rebuild"   # suffix overflows the narrowed time dtype
        return "extended"

    # ---- sweep driving ----

    def advance(self, time: int) -> None:
        """Fold events in (t_now, time] on host and mirror the touched rows
        into the device buffers. Times must be non-decreasing."""
        self._apply_staged(self._fold_hop(time))

    def _fold_hop(self, time: int) -> dict:
        """Host half of one hop: fold events in (t_now, time] and STAGE the
        touched rows as padded contiguous arrays, ready to ship. Pure
        numpy — safe to run in the prefetch worker while the previous
        hop's scatter + superstep run on device. The returned payload
        carries its own hop time (``self.t_now`` keeps moving under a
        lookahead fold)."""
        with TRACER.span("hop.fold", time=int(time),
                            engine="device_sweep") as sp:
            payload = self._fold_hop_inner(time)
            sp.set(kind=payload["kind"])
        return payload

    def _fold_hop_inner(self, time: int) -> dict:
        f0 = _time.perf_counter()
        time = int(time)
        if self.t_now is not None and time < self.t_now:
            if not self._stale:
                raise ValueError(
                    f"DeviceSweep times must ascend "
                    f"(got {time} < {self.t_now})")
            # stale REWIND recovery: a mid-sweep failure can leave the
            # lookahead fold (and t_now) PAST the hop a caller retries —
            # how far depends on thread timing, so the ascending
            # contract cannot be enforced against it. The fold only
            # ascends, so rebuild the builder from the (pinned) log and
            # refold to `time`; the stale path below restages the FULL
            # state either way, and the device buffers were already
            # behind the clock.
            self.sw = SweepBuilder(self.sw.log, track_rows=False,
                                   preseed_pairs=True)
            self.t_now = None
        advanced = self.t_now is None or time > self.t_now
        if advanced:
            self.sw._advance(time)
            self.t_now = time
        if self._stale:
            # recover from an aborted earlier hop: re-stage the FULL fold
            # state (the running sw is authoritative; the device buffers
            # are behind by an unknown number of hops). Cleared here —
            # a failed apply re-marks stale before the error propagates.
            self._stale = False
            payload = {"time": time, "kind": "full",
                       "arrays": self._stage_full()}
            self._note_fold(_time.perf_counter() - f0, "serial")
            return payload
        if not advanced:   # repeat hop on healthy buffers: nothing to ship
            return {"time": time, "kind": "noop"}
        payload = self._stage_payload(self.sw, time)
        self._note_fold(_time.perf_counter() - f0, "serial")
        return payload

    def _note_fold(self, seconds: float, mode: str) -> None:
        self.fold_seconds += seconds
        self.fold_mode_seconds[mode] = (
            self.fold_mode_seconds.get(mode, 0.0) + seconds)

    def _stage_payload(self, sw, time: int) -> dict:
        """Staged payload for ``sw``'s LAST advance (to ``time``): noop /
        full-refresh / padded delta chunks. The ONE copy of the staging
        policy — the engine-clock fold (``_fold_hop_inner``) and the
        forked parallel fold (``_fold_hop_fork``) both stage through it,
        so the two paths can never diverge."""
        d = sw.last_delta
        nv, ne = len(d["v_idx"]), len(d["e_enc"])
        if nv == 0 and ne == 0:
            return {"time": time, "kind": "noop"}
        # full-state refresh (first hop, or a delta so large that chunked
        # scatters would ship more than the whole buffers): host-assemble
        # and device_put — one transfer, no scatter program involved
        if nv > self.n_pad // 2 or ne > self.m_pad // 2:
            return {"time": time, "kind": "full",
                    "arrays": self._stage_full(sw)}
        e_pos = self.tables.eng_pos(d["e_enc"])
        n_chunks = max(-(-nv // self.cap_v), -(-ne // self.cap_e), 1)
        chunks = []
        for i in range(n_chunks):
            ov, oe = i * self.cap_v, i * self.cap_e
            # out-of-range slices are empty; pad rows scatter out of
            # bounds and are dropped
            chunks.append(self._stage_chunk(
                d["v_idx"][ov: ov + self.cap_v],
                d["v_lat"][ov: ov + self.cap_v],
                d["v_alive"][ov: ov + self.cap_v],
                d["v_first"][ov: ov + self.cap_v],
                e_pos[oe: oe + self.cap_e],
                d["e_lat"][oe: oe + self.cap_e],
                d["e_alive"][oe: oe + self.cap_e],
                d["e_first"][oe: oe + self.cap_e],
            ))
        return {"time": time, "kind": "chunks", "chunks": chunks}

    def _apply_staged(self, payload: dict) -> None:
        """Device half of one hop: ship the staged arrays and scatter them
        into the donated resident buffers (or swap in a full refresh).
        Runs on the dispatch thread; all device ops are async."""
        kind = payload["kind"]
        if kind == "noop":
            return
        with TRACER.span("hop.ship", kind=kind,
                            time=int(payload["time"])):
            self._apply_staged_inner(payload)

    def _apply_staged_inner(self, payload: dict) -> None:
        kind = payload["kind"]
        from ..utils.transfer import shared_engine

        try:
            if kind == "full":
                arrays = payload["arrays"]
                self.ship_bytes += sum(a.nbytes for a in arrays)
                self._bufs = tuple(shared_engine().put_many(arrays))
                return
            apply_fn = _compiled_apply(self.cap_v, self.cap_e,
                                       np.dtype(self.tdtype).name)
            for chunk in payload["chunks"]:
                self.ship_bytes += sum(a.nbytes for a in chunk)
                # resident state flows through donated buffers
                # (donate_argnums 0-5 in _compiled_apply) — the
                # double-buffer swap XLA gives us for free; only the
                # O(delta) staged rows cross the link
                self._bufs = apply_fn(
                    *self._bufs, *shared_engine().put_many(list(chunk)))
        except BaseException:
            # t_now already reflects this payload's fold but the buffers
            # don't (and a donated apply may have consumed them) — the
            # next fold must take the full-refresh path
            self._stale = True
            raise

    def _cast_t(self, a: np.ndarray) -> np.ndarray:
        return self.tables.cast_times(a)

    def _stage_chunk(self, v_idx, v_lat, v_alive, v_first,
                     e_idx, e_lat, e_alive, e_first) -> tuple:
        """Pad one delta chunk to the fixed scatter capacities — fresh
        contiguous arrays each hop (a reused staging buffer could alias
        the device copy on the CPU backend)."""
        def pad(a, cap, dtype):
            # pad indices with a huge POSITIVE out-of-bounds value — negative
            # indices would wrap Python-style instead of being dropped
            out = np.full(cap, 2**31 - 1 if dtype == np.int32 else 0, dtype)
            out[: len(a)] = a
            return out

        tdt = self.tdtype
        return (
            pad(v_idx, self.cap_v, np.int32),
            pad(self._cast_t(v_lat), self.cap_v, tdt),
            pad(v_alive, self.cap_v, bool),
            pad(self._cast_t(v_first), self.cap_v, tdt),
            pad(e_idx, self.cap_e, np.int32),
            pad(self._cast_t(e_lat), self.cap_e, tdt),
            pad(e_alive, self.cap_e, bool),
            pad(self._cast_t(e_first), self.cap_e, tdt),
        )

    def _apply_chunk(self, v_idx, v_lat, v_alive, v_first,
                     e_idx, e_lat, e_alive, e_first) -> None:
        self._apply_staged({"time": self.t_now, "kind": "chunks",
                            "chunks": [self._stage_chunk(
                                v_idx, v_lat, v_alive, v_first,
                                e_idx, e_lat, e_alive, e_first)]})

    def _stage_full(self, sw=None) -> tuple:
        sw = self.sw if sw is None else sw
        tdt = self.tdtype
        v_lat = np.full(self.n_pad, self._tmin, tdt)
        v_alive = np.zeros(self.n_pad, bool)
        v_first = np.full(self.n_pad, self._tmin, tdt)
        v_lat[: self.n] = self._cast_t(sw.v_lat)
        v_alive[: self.n] = sw.v_alive
        v_first[: self.n] = self._cast_t(sw.v_first)
        e_lat = np.full(self.m_pad, self._tmin, tdt)
        e_alive = np.zeros(self.m_pad, bool)
        e_first = np.full(self.m_pad, self._tmin, tdt)
        pos = self.tables.eng_pos(sw.e_enc)
        e_lat[pos] = self._cast_t(sw.e_lat)
        e_alive[pos] = sw.e_alive
        e_first[pos] = self._cast_t(sw.e_first)
        return (v_lat, v_alive, v_first, e_lat, e_alive, e_first)

    def _refresh_full(self) -> None:
        self._apply_staged({"time": self.t_now, "kind": "full",
                            "arrays": self._stage_full()})

    # ---- program dispatch ----

    def run(self, program: VertexProgram, time: int | None = None, *,
            window: int | None = None, windows=None):
        """Advance to `time` (if given) and dispatch `program` — async, like
        ``bsp.run_async``. Result rows are global dense vertex indices."""
        if not supported(program):
            raise ValueError(
                "program needs occurrences or host-materialised properties — "
                "run it through bsp.run / jobs instead")
        if time is not None:
            self.advance(time)
        if self.t_now is None:
            raise ValueError("call advance(T) (or pass time=) before run()")
        return self._dispatch(program, self.t_now, window, windows)

    def _dispatch(self, program: VertexProgram, T: int, window, windows):
        """Dispatch `program` against the CURRENT resident buffers for hop
        time ``T`` — split from ``run`` so the pipelined sweep can dispatch
        hop *i* while a lookahead fold has already moved ``t_now`` on."""
        batched = windows is not None
        if windows is not None and len(windows) == 0:
            raise ValueError("windows must be a non-empty list")
        if windows is None:
            windows = [window if window is not None else -1]
        wlist = normalize_windows(windows)

        # the device.dispatch failpoint: an injected error propagates
        # through the same except paths a real dispatch failure takes
        # (run_sweep marks _stale; the next hop rewinds through the
        # full-refresh recovery) — chaos runs exercise recovery, not a
        # parallel code path
        _faults.fire("device.dispatch")
        runner = _compiled_run(program, self.n_pad, self.m_pad, len(wlist),
                               np.dtype(self.tdtype).name)
        with TRACER.span("hop.compute", time=int(T), windows=len(wlist),
                            engine="device_sweep"):
            result, steps = runner(
                *self._bufs, self.vids, self.e_src, self.e_dst,
                jnp.asarray(int(T), jnp.int64),
                jnp.asarray(wlist, jnp.int64))
        if not batched:
            result = jax.tree_util.tree_map(lambda a: a[0], result)
        return result, steps

    def run_sweep(self, program: VertexProgram, times, *,
                  window: int | None = None, windows=None,
                  prefetch: bool | None = None):
        """Pipelined ascending range sweep: hop *i+1*'s host fold + delta
        staging run in the prefetch worker while hop *i*'s staged rows
        ship and its superstep computes — the fold → stage → ship →
        compute pipeline (``core/sweep._prefetch_pool`` is the fold/stage
        lane; resident state advances through donated device buffers and
        never copies). Returns ``(results, steps_list)`` where
        ``results[i]`` is ``run(program, times[i])``'s result — identical
        to the serial loop (tested) and independent of the pipeline depth.
        ``prefetch=False`` degrades to the serial advance/run loop (the
        bench comparison point); the default follows the ``RTPU_PREFETCH``
        kill-switch (on unless ``0`` — the same knob as the hopbatch
        engine)."""
        if prefetch is None:
            import os

            prefetch = os.environ.get("RTPU_PREFETCH", "1") != "0"
        if not supported(program):
            raise ValueError(
                "program needs occurrences or host-materialised properties — "
                "run it through bsp.run / jobs instead")
        times = [int(t) for t in times]
        if sorted(times) != times:
            raise ValueError("run_sweep times must ascend")
        # per-sweep telemetry (advance() outside run_sweep still
        # accumulates into fold_seconds/ship_bytes; each sweep reports
        # its own numbers, like hopbatch's run())
        self.fold_seconds = 0.0
        self.fold_mode_seconds = {}
        self.fold_stall_seconds = 0.0
        self.ship_bytes = 0
        from ..utils.transfer import shared_engine

        before = shared_engine().stats.as_dict()
        t_start = _time.perf_counter()
        with TRACER.span("sweep.range", engine="device_sweep",
                            hops=len(times),
                            program=type(program).__name__) as sp:
            out = self._run_sweep_impl(program, times, window, windows,
                                       prefetch)
            self.last_phase_seconds = sweep_phase_summary(
                sp, _time.perf_counter() - t_start, self.fold_seconds,
                self.fold_stall_seconds,
                shared_engine().stats.delta_since(before),
                self.ship_bytes, len(times),
                fold_modes=self.fold_mode_seconds)
        return out

    def _run_sweep_impl(self, program, times, window, windows, prefetch):
        results, steps = [], []
        if not prefetch or len(times) <= 1:
            for T in times:
                self.advance(T)
                r, s = self._dispatch(program, T, window, windows)
                results.append(r)
                steps.append(s)
            return results, steps
        import functools as _ft

        from ..core.sweep import fold_workers, prefetch_map

        if fold_workers() > 1 and not self._stale and len(times) >= 2:
            # segment-parallel host folds on forked builders (the sized
            # RTPU_FOLD_WORKERS pool); RTPU_FOLD_WORKERS=1 keeps the
            # single-worker shared-builder pipeline below
            return self._run_sweep_parallel(program, times, window,
                                            windows, results, steps)

        def step(payload, stall):
            self.fold_stall_seconds += stall
            if stall > 0:
                TRACER.complete("fold.stall", stall,
                                   time=int(payload["time"]))
            m = _metrics()
            if m is not None:
                m.h2d_stall_seconds.labels(stage="fold").inc(stall)
            self._apply_staged(payload)
            r, s = self._dispatch(program, payload["time"], window, windows)
            results.append(r)
            steps.append(s)

        try:
            prefetch_map((_ft.partial(self._fold_hop, T) for T in times),
                         step)
        except BaseException:
            # the lookahead fold may have advanced t_now past the hop whose
            # dispatch failed — buffers are behind the clock now
            self._stale = True
            raise
        return results, steps

    def _run_sweep_parallel(self, program, times, window, windows,
                            results, steps):
        """Segment-parallel sweep folds: the hop list splits into up to
        ``fold_workers()`` contiguous segments, each folded + staged on an
        INDEPENDENT fork of the sweep's builder (seeded by one bulk
        advance to the previous segment's boundary) on the sized fold
        pool, while earlier hops ship and compute on this thread. The
        per-hop payloads are identical to the serial fold's (delta
        windows per hop are unchanged), so applied state and results are
        bit-identical. The engine adopts the last segment's builder at
        the end — the host fold clock lands exactly where the serial
        sweep leaves it."""
        from ..core.sweep import fold_pool, fold_workers, prefetch_map

        if self.t_now is not None and times[0] < self.t_now:
            raise ValueError(
                f"DeviceSweep times must ascend "
                f"(got {times[0]} < {self.t_now})")
        n_seg = min(fold_workers(), len(times))
        per = -(-len(times) // n_seg)
        segs = [times[s * per:(s + 1) * per] for s in range(n_seg)]
        segs = [s for s in segs if s]

        def make_task(i: int):
            boundary = int(segs[i - 1][-1]) if i > 0 else None

            def task():
                f0 = _time.perf_counter()
                payloads = []
                # worker attr: see hopbatch._fold_groups_parallel — the
                # span rides the request trace via the pool-handoff
                # context; the attr names the worker without a metadata
                # join
                with TRACER.span("hop.fold", hops=len(segs[i]),
                                    engine="device_sweep",
                                    mode="parallel",
                                    worker=_threading.current_thread(
                                        ).name):
                    sw = self.sw.fork()
                    prev = sw.t_prev
                    if boundary is not None and (prev is None
                                                 or prev < boundary):
                        with TRACER.span("fold.checkpoint",
                                            time=boundary):
                            sw._advance(boundary)
                        prev = boundary
                    for T in segs[i]:
                        payloads.append(self._fold_hop_fork(sw, T, prev))
                        prev = int(T)
                return sw, payloads, _time.perf_counter() - f0
            return task

        last_sw = [self.sw]

        def consume(res, stall):
            sw, payloads, dt = res
            self._note_fold(dt, "parallel")
            self.fold_stall_seconds += stall
            if stall > 0:
                TRACER.complete("fold.stall", stall)
            m = _metrics()
            if m is not None:
                m.h2d_stall_seconds.labels(stage="fold").inc(stall)
                m.fold_seconds.labels("parallel").observe(dt)
            last_sw[0] = sw
            for payload in payloads:
                # t_now and self.sw only move TOGETHER at adoption below —
                # a mid-sweep failure must leave clock == host fold so the
                # stale full-refresh restages a state that covers it
                self._apply_staged(payload)
                r, s = self._dispatch(program, payload["time"], window,
                                      windows)
                results.append(r)
                steps.append(s)

        try:
            prefetch_map([make_task(i) for i in range(len(segs))], consume,
                         depth=len(segs), pool=fold_pool())
        except BaseException:
            # some forked fold/staged payload may be ahead of the applied
            # buffers — recover through the full-refresh path
            self._stale = True
            raise
        # adopt the final fork: self.sw's own clock never moved
        self.sw = last_sw[0]
        self.t_now = int(times[-1])
        return results, steps

    def _fold_hop_fork(self, sw, time: int, prev) -> dict:
        """``_fold_hop_inner`` on a forked builder: fold events in
        (prev, time] and stage the touched rows — engine state (t_now,
        stale flag, telemetry) is the driver's business, not the
        worker's."""
        time = int(time)
        with TRACER.span("hop.fold", time=time,
                            engine="device_sweep") as sp:
            if prev is not None and time <= prev:
                sp.set(kind="noop")
                return {"time": time, "kind": "noop"}
            sw._advance(time)
            payload = self._stage_payload(sw, time)
            sp.set(kind=payload["kind"])
            return payload
