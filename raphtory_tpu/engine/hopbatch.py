"""Hop-batched columnar PageRank — the whole range sweep in one dispatch.

The per-hop engines (``bsp``, ``device_sweep``) pay the device's
per-element random-access rate once per (hop, window, iteration): scalar
ranks move 4 bytes per edge endpoint. This runner instead evaluates EVERY
(hop, window) view of a range sweep simultaneously as COLUMNS of one
program: the per-edge access becomes a C-wide row move (row-tile gathers
and row segment-sums run at bandwidth, not at the per-element rate —
measured, tools/tpu_physics.py), the per-iteration dispatch overhead is
paid once for the whole sweep, and the temporal dimension is captured
up-front as per-hop fold-state COLUMNS (hop-major ``lat[j]`` /
``alive[j]`` rows of ``[H, m_pad]``/``[H, n_pad]`` arrays) built
incrementally by the host fold — deletes and revivals included, not an
add-only approximation.

This is the windowed-PageRank-specific engine behind the headline
benchmark; semantics match ``algorithms/pagerank.py`` exactly
(power iteration with dangling redistribution and tol-based halting) and
are tested column-against-``bsp.run`` per (hop, window).

Reference contrast: one compiled program per RANGE QUERY, where the
reference runs its full actor handshake once per hop
(``RangeAnalysisTask.scala:18-35``).
"""

from __future__ import annotations

import functools
import threading as _threading
import time as _time

import jax
import jax.numpy as jnp
import numpy as np

import logging

from ..core.events import EventLog
from ..core.sweep import (SweepBuilder, fold_cache, fold_pool, fold_workers,
                          log_fingerprint, prefetch_map)
from ..obs import ledger as _ledger
from ..obs.trace import TRACER
from ..utils.transfer import _metrics
from .device_sweep import (GlobalTables, _device_edges, normalize_windows,
                           sweep_phase_summary)

_log = logging.getLogger(__name__)


def _column_masks(tdt, e_lat, e_alive, v_lat, v_alive,
                  hop_of_col, T_col, w_col):
    """Per-column alive masks from the hop-major ``[H, ...]`` fold columns,
    transposed into the kernels' entity-major ``[..., C]`` layout — the ONE
    place the windowing test (``latest >= T - w``, ``w < 0`` = unwindowed)
    is written for all three compiled engines."""
    info = jnp.iinfo(tdt)
    lo = jnp.clip(T_col - w_col, info.min, info.max).astype(tdt)   # [C]
    nowin = w_col < 0
    me = (e_alive[hop_of_col] & (nowin[:, None]
                                 | (e_lat[hop_of_col] >= lo[:, None]))).T
    mv = (v_alive[hop_of_col] & (nowin[:, None]
                                 | (v_lat[hop_of_col] >= lo[:, None]))).T
    return me, mv


def _masks_from_deltas(tdt, H: int, W: int,
                       be_lat, be_alive, bv_lat, bv_alive,
                       de_pos, de_lat, de_alive,
                       dv_pos, dv_lat, dv_alive, T_col, w_col,
                       h0: bool = False):
    """Device-side fold-column rebuild: hop 0's full state plus per-hop
    touched-entity deltas (scatter-SET in hop order — delete-wins and
    revivals are already resolved by the host fold, so the delta VALUES
    are exact) replace the ``[H, m_pad]`` host-built columns. A sweep
    ships O(base + Σ delta) bytes instead of O(H · m_pad) — the term that
    made the host fold+transfer the binding cost of the headline sweep.
    ``h0=True`` additionally applies delta[0] BEFORE hop 0's column: the
    base args are then the previous dispatch's device-resident advanced
    state and delta[0] is the inter-batch catch-up, so a follow-on batch
    ships only deltas (the tunnel-link term of a chunked sweep).
    Same windowing test as ``_column_masks``; pad rows carry a huge
    positive index and are dropped by the scatter. Returns the masks plus
    the ADVANCED base (state after the last hop) for the next dispatch."""
    info = jnp.iinfo(tdt)
    lo = jnp.clip(T_col - w_col, info.min, info.max).astype(tdt)   # [C]
    nowin = w_col < 0

    def build(b_lat, b_alive, d_pos, d_lat, d_alive):
        cur_l, cur_a, cols = b_lat, b_alive, []
        for h in range(H):     # H static and small: unrolled 1D scatters
            if h or h0:
                cur_l = cur_l.at[d_pos[h]].set(d_lat[h], mode="drop")
                cur_a = cur_a.at[d_pos[h]].set(d_alive[h], mode="drop")
            sl = slice(h * W, (h + 1) * W)
            cols.append(cur_a[:, None]
                        & (nowin[sl][None, :]
                           | (cur_l[:, None] >= lo[sl][None, :])))
        # [len, H*W] hop-major + the post-last-hop state
        return jnp.concatenate(cols, axis=1), cur_l, cur_a

    me, fe_lat, fe_alive = build(be_lat, be_alive, de_pos, de_lat, de_alive)
    mv, fv_lat, fv_alive = build(bv_lat, bv_alive, dv_pos, dv_lat, dv_alive)
    return me, mv, (fe_lat, fe_alive, fv_lat, fv_alive)


def _tile_budget_bytes() -> int:
    """Resolved ``RTPU_TILE_BUDGET_MB`` in bytes. Every columnar dispatcher
    resolves this ONCE per call and threads it into the lru_cached compiled
    factories, so the budget is part of the program cache key — changing
    the env var mid-process recompiles instead of silently reusing
    programs tiled for the old budget."""
    import os

    return int(os.environ.get("RTPU_TILE_BUDGET_MB", 256)) << 20


def _traffic(m_pad: int, C: int, n_pad: int, spec) -> dict:
    """Engine-side DRAM traffic model of one message-combine superstep
    (``ops/partition.edge_traffic_model``) — attached to every compiled
    columnar kernel so the ledger can report partition-aware est HBM
    bytes next to the locality-blind XLA ``bytes_accessed`` harvest."""
    from ..ops.partition import edge_traffic_model

    return edge_traffic_model(m_pad, C, n_pad, spec)


def _edge_tile_for(m_pad: int, C: int, budget_bytes: int) -> int | None:
    """Edge-tile length for the columnar kernels, or None for single-shot.

    The per-iteration payload ``[m_pad, C] f32`` is the scale limiter: at
    28M pairs x 128 columns it is ~14 GB — over a v5e's HBM — and the
    resulting spill is catastrophic. When the payload would exceed
    ``budget_bytes`` (``_tile_budget_bytes()``, resolved by every dispatch
    site so the knob lands in the program cache key), the edge dimension
    is processed as a
    ``lax.scan`` over equal tiles (plus one remainder slice, so no
    divisibility gymnastics) whose transient is ``tile * C * 4`` bytes."""
    if budget_bytes is None:
        # an env read here would happen at TRACE time, inside lru_cached
        # factories whose key would not carry the knob (rtpulint RT001) —
        # fail fast instead of silently caching programs tiled for a
        # budget the env var no longer holds
        raise ValueError(
            "tile budget unresolved — dispatch sites must pass "
            "_tile_budget_bytes() so RTPU_TILE_BUDGET_MB stays part of "
            "the compiled-program cache key")
    if m_pad * C * 4 <= budget_bytes or m_pad <= (1 << 16):
        return None
    step = 1 << 16
    target = max(step, budget_bytes // (C * 4))
    return min((target // step) * step, m_pad)


def _pagerank_columns(me, mv, e_src, e_dst, n_pad: int, damping: float,
                      tol: float, max_steps: int, r_init=None,
                      tile_budget: int | None = None, pcpm=None):
    """Power iteration over per-column masks ``me [m_pad, C]`` /
    ``mv [n_pad, C]`` — dangling redistribution, tol halting with
    converged-column freeze; semantics of ``algorithms/pagerank.py``.
    Shared by the general columnar kernel and the scale (device-built
    columns) kernel.

    ``r_init`` (optional ``[n_pad, C]``) warm-starts the iteration: the
    update is a contraction, so ANY masked positive start converges to the
    SAME fixed point — a near-solution (the previous hop's ranks) just
    gets there in a few steps instead of max_steps. Each column is masked
    to its own alive set, floored so newly-alive vertices get mass, and
    renormalised.

    ``pcpm`` = ``(spec, slot, u_src)`` switches the edge operands to the
    destination-binned layout (``ops/partition.py``): ``me``/``e_src``/
    ``e_dst`` are then the BINNED ``[B(, C)]`` arrays (ids stay global, so
    every reduce keeps its shape) and the superstep gather goes through
    the per-(partition, src) pre-aggregation buckets. Binned edges are
    (partition, src)-ordered, so destination ids are NOT sorted — the
    scatter instead stays inside one cache-resident partition slice
    (docs/KERNELS.md). Float sums reorder: results agree to reduction
    tolerance, not bitwise."""
    C = me.shape[1]
    dst_sorted = pcpm is None
    # Edge traffic is tiled past the payload budget (_edge_tile_for): the
    # f32 view of the mask and the per-iteration gather payload are both
    # [m_pad, C] transients that at 28M pairs x 128 columns outgrow a
    # v5e's HBM — the resulting spill, not compute, bound the scale sweep.
    tile = _edge_tile_for(e_src.shape[0], C, tile_budget)
    if tile is not None:
        n_main = (e_src.shape[0] // tile) * tile
        main = (e_src[:n_main].reshape(-1, tile),
                e_dst[:n_main].reshape(-1, tile),
                me[:n_main].reshape(-1, tile, C))
        rem = (e_src[n_main:], e_dst[n_main:], me[n_main:])
        # carry seed rides the mask's varying axes: under the
        # column-sharded shard_map(check_vma=True) the accumulator must
        # enter the scan column-varying, like the while_loop seeds below
        acc0 = (jnp.zeros((n_pad, C), jnp.float32)
                + (mv[0] & False).astype(jnp.float32)[None, :])

        def tiled_sum(payload_of, by_dst):
            def step(acc, inp):
                es, ed, mk = inp
                return acc + jax.ops.segment_sum(
                    payload_of(es, mk), ed if by_dst else es,
                    num_segments=n_pad,
                    # tiles are contiguous slices of the globally
                    # (dst, src)-sorted order — UNLESS binned, whose
                    # (partition, src) order leaves dst unsorted
                    indices_are_sorted=by_dst and dst_sorted), None

            acc, _ = jax.lax.scan(step, acc0, main)
            if rem[0].shape[0]:
                es, ed, mk = rem
                acc = acc + jax.ops.segment_sum(
                    payload_of(es, mk), ed if by_dst else es,
                    num_segments=n_pad,
                    indices_are_sorted=by_dst and dst_sorted)
            return acc

        out_deg = tiled_sum(
            lambda es, mk: mk.astype(jnp.float32), by_dst=False)
    else:
        # out-degree per column: combine at src (unsorted scatter, once);
        # the f32 view of the mask fuses into the scatter-add
        out_deg = jax.ops.segment_sum(me.astype(jnp.float32), e_src,
                                      num_segments=n_pad)
    n_act = jnp.maximum(jnp.sum(mv.astype(jnp.float32), axis=0), 1.0)
    r0 = jnp.where(mv, 1.0 / n_act[None, :], 0.0).astype(jnp.float32)
    if r_init is not None:
        warm = jnp.where(mv, jnp.maximum(r_init, 0.0), 0.0)
        warm = warm + jnp.where(mv, 0.1 / n_act[None, :], 0.0)
        warm = warm / jnp.maximum(jnp.sum(warm, axis=0, keepdims=True),
                                  1e-30)
        r0 = warm.astype(jnp.float32)
    inv_deg = 1.0 / jnp.maximum(out_deg, 1.0)
    dangling_mask = mv & (out_deg == 0)

    def body(carry):
        step, r, halted = carry
        rd = r * inv_deg
        if tile is not None:
            agg = tiled_sum(
                lambda es, mk: jnp.where(mk, rd[es, :], 0.0), by_dst=True)
        elif pcpm is not None and pcpm[0].preagg:
            # PCPM two-level gather: one state row per (partition, src)
            # bucket — each source read ONCE per partition it reaches —
            # then a streaming expansion through the resident bucket
            spec, slot, u_src = pcpm
            vals = rd[u_src, :]                       # [P*cap_u, C]
            payload = jnp.where(me, vals[slot, :], 0.0)
            agg = jax.ops.segment_sum(
                payload, e_dst, num_segments=n_pad,
                indices_are_sorted=False)
        else:
            # row gather [m, C]; the bool mask gates via where — only the
            # bool mask stays live across the loop
            payload = jnp.where(me, rd[e_src, :], 0.0)
            agg = jax.ops.segment_sum(
                payload, e_dst, num_segments=n_pad,
                indices_are_sorted=dst_sorted)
        dangling = jnp.sum(jnp.where(dangling_mask, r, 0.0), axis=0)
        new = ((1.0 - damping) / n_act[None, :]
               + damping * (agg + dangling[None, :] / n_act[None, :]))
        new = jnp.where(mv, new, 0.0).astype(jnp.float32)
        col_done = jnp.all((jnp.abs(new - r) < tol) | ~mv, axis=0)
        # freeze converged columns
        new = jnp.where(halted[None, :], r, new)
        return step + 1, new, halted | col_done

    def cond(carry):
        step, _, halted = carry
        return (step < max_steps) & ~jnp.all(halted)

    # seed the non-array carry components from mv (numeric no-ops): under
    # shard_map(check_vma=True) on a column-sharded mesh the loop carry
    # must enter with the same varying-axes type it leaves with, and both
    # step and halted become column-varying through the halting logic
    seed_false = mv[0] & False                                 # all-False
    step0 = jnp.int32(0) + (mv[0, 0] & False).astype(jnp.int32)
    steps, r, _ = jax.lax.while_loop(
        cond, body, (step0, r0, seed_false))
    return r.T, steps   # [C, n_pad], hop-major columns


def _bin_masks(me, pcpm_args):
    """Host-column edge masks → the binned layout, in-program: one
    loop-invariant permutation gather, amortised over the supersteps.
    ``pcpm_args`` = (spec, perm, valid, slot, u_src) as the dispatcher
    appended them; returns (binned me, (spec, slot, u_src)) for the
    kernel bodies."""
    spec, perm, valid, slot, u_src = pcpm_args
    return me[perm, :] & valid[:, None], (spec, slot, u_src)


@functools.lru_cache(maxsize=64)
def _compiled(n_pad: int, m_pad: int, H: int, C: int, damping: float,
              tol: float, max_steps: int, tdt: str, warm: bool = False,
              tile_budget: int | None = None, pcpm=None):
    tdt = jnp.dtype(tdt)

    def run(e_src, e_dst, e_lat, e_alive, v_lat, v_alive,
            hop_of_col, T_col, w_col, *rest):
        me, mv = _column_masks(tdt, e_lat, e_alive, v_lat, v_alive,
                               hop_of_col, T_col, w_col)
        pc = None
        if pcpm is not None:
            *rest, perm, valid, slot, u_src = rest
            me, pc = _bin_masks(me, (pcpm, perm, valid, slot, u_src))
        # warm arg: previous chunk's full [C, n_pad] output; tail slice +
        # per-hop tile in-program (see _compiled_delta)
        W = C // H
        r0 = jnp.tile(rest[0][-W:], (H, 1)).T if warm else None
        return _pagerank_columns(me, mv, e_src, e_dst, n_pad,
                                 damping, tol, max_steps, r_init=r0,
                                 tile_budget=tile_budget, pcpm=pc)

    return _ledger.instrument("hopbatch.pagerank_cols", jax.jit(run),
                              traffic=_traffic(m_pad, C, n_pad, pcpm))


@functools.lru_cache(maxsize=64)
def _compiled_delta(kind: str, n_pad: int, m_pad: int, H: int, W: int,
                    U_e: int, U_v: int, tdt: str, warm: bool,
                    algo_args: tuple, weighted: bool = False,
                    U_w: int = 0, h0: bool = False,
                    tile_budget: int | None = None, pcpm=None):
    """Delta-fed columnar kernels: masks rebuilt on device from base state
    + per-hop deltas (``_masks_from_deltas``), then the shared algorithm
    body. ``kind``: pagerank | cc | bfs (``weighted`` adds a per-pair
    weight state rebuilt the same way); ``algo_args`` is the algorithm's
    static parameter tuple. ``h0=True`` is the resident-base variant: the
    base inputs are the previous dispatch's advanced state, delta[0] is
    applied before hop 0. Every variant returns ``(result, steps,
    advanced_base)`` so the caller can keep the fold state on device.

    ``pcpm`` (a ``PartitionSpec``) is the destination-binned variant: the
    PAIR-side base arrays arrive pre-binned from the host, the pair delta
    positions are pre-remapped to binned slots, and ``e_src``/``e_dst``
    are the layout's global ``b_src``/``b_dst`` — the mask rebuild is then
    IDENTICAL code over the binned coordinate space, and the advanced
    base stays binned across resident batches. Trailing args carry the
    layout's (slot, u_src) bucket tables."""
    tdt_ = jnp.dtype(tdt)

    def run(e_src, e_dst, be_lat, be_alive, bv_lat, bv_alive,
            de_pos, de_lat, de_alive, dv_pos, dv_lat, dv_alive,
            T_col, w_col, *rest):
        pc = None
        if pcpm is not None:
            *rest, slot, u_src = rest
            pc = (pcpm, slot, u_src)
        me, mv, adv = _masks_from_deltas(
            tdt_, H, W, be_lat, be_alive, bv_lat, bv_alive,
            de_pos, de_lat, de_alive, dv_pos, dv_lat, dv_alive,
            T_col, w_col, h0=h0)
        if kind == "pagerank":
            damping, tol, max_steps = algo_args
            # warm arg is the previous chunk's FULL output [C, n_pad]; the
            # tail slice + per-hop tile happen in-program (host-side array
            # ops would be extra tunnel round-trips between dispatches)
            r0 = jnp.tile(rest[0][-W:], (H, 1)).T if warm else None
            out, steps = _pagerank_columns(
                me, mv, e_src, e_dst, n_pad, damping, tol, max_steps,
                r_init=r0, tile_budget=tile_budget, pcpm=pc)
            return out, steps, adv
        if kind == "cc":
            (max_steps,) = algo_args
            l0 = jnp.tile(rest[0][-W:], (H, 1)).T if warm else None
            out, steps = _cc_columns(me, mv, e_src, e_dst, n_pad, max_steps,
                                     tile_budget=tile_budget, pcpm=pc,
                                     l_init=l0)
            return out, steps, adv
        max_steps, directed = algo_args
        ew = 1.0
        nxt = 1   # rest[0] is the seed mask; weights then warm follow
        if weighted:
            w_base, dw_pos, dw_val = rest[nxt], rest[nxt + 1], rest[nxt + 2]
            nxt += 3
            cur_w, cols = w_base, []
            for h in range(H):   # same unrolled rebuild as the masks
                if h or h0:
                    cur_w = cur_w.at[dw_pos[h]].set(dw_val[h], mode="drop")
                cols.append(jnp.broadcast_to(
                    cur_w[:, None], (cur_w.shape[0], W)))
            ew = jnp.concatenate(cols, axis=1)   # [m_pad, C] hop-major
            adv = adv + (cur_w,)
        d0 = jnp.tile(rest[nxt][-W:], (H, 1)).T if warm else None
        out, steps = _bfs_columns(me, mv, e_src, e_dst, n_pad, max_steps,
                                  directed, rest[0], ew,  # rest[0]: seeds
                                  tile_budget=tile_budget, pcpm=pc,
                                  d_init=d0)
        return out, steps, adv

    return _ledger.instrument(f"hopbatch.delta.{kind}", jax.jit(run),
                              traffic=_traffic(m_pad, H * W, n_pad, pcpm))


def _pad_hop_deltas(deltas, H: int, tdt):
    """Pad per-hop (pos, lat, alive) delta lists to a fixed ``[H, U]``
    shape (hop 0 is empty: its state IS the base). Pad index 2^31-1 is
    dropped by the device scatter."""
    longest = max((len(p) for p, _, _ in deltas), default=1)
    U = max(256, 1 << int(np.ceil(np.log2(max(longest, 1)))))
    pos = np.full((H, U), 2**31 - 1, np.int32)
    lat = np.zeros((H, U), tdt)
    alive = np.zeros((H, U), bool)
    for h, (p, l, a) in enumerate(deltas):
        pos[h, : len(p)] = p
        lat[h, : len(l)] = l
        alive[h, : len(a)] = a
    return U, pos, lat, alive


def run_columns_delta(kind, tables, base, deltas_e, deltas_v, hop_times,
                      windows, *, algo_args: tuple, seed_mask=None,
                      e_src_dev=None, e_dst_dev=None, r_init=None,
                      weight_base=None, weight_deltas=None,
                      h0_delta: bool = False, ship_counter=None,
                      layout=None):
    """Dispatch a delta-fed columnar kernel (``kind``: pagerank|cc|bfs)
    over ``_HopBatched._fold_deltas`` output; returns ``(result, steps,
    advanced_base)``. ``weight_base`` + ``weight_deltas`` ([(pos, val)]
    per hop) turn bfs into weighted SSSP with the weight state rebuilt on
    device too. ``h0_delta=True`` means ``base`` (and ``weight_base``)
    are the previous dispatch's device-resident advanced state and
    delta[0] carries the inter-batch catch-up — the sweep then ships
    O(Σ delta) bytes with no full-table upload at all.

    ``layout`` (``ops/partition.PartitionLayout``) routes the dispatch
    through the destination-binned kernels: pair-side base state is
    permuted into the binned layout HERE (one O(m) fancy-index, skipped
    entirely on resident batches whose device base is already binned) and
    pair delta positions are remapped O(Σ delta); the layout's spec rides
    into the compiled-program cache key."""
    H, C, _, T_col, w_col = _column_layout(hop_times, windows)
    W = C // H
    be_lat, be_alive, bv_lat, bv_alive = base
    tdt = tables.tdtype
    U_e, de_pos, de_lat, de_alive = _pad_hop_deltas(deltas_e, H, tdt)
    U_v, dv_pos, dv_lat, dv_alive = _pad_hop_deltas(deltas_v, H, tdt)
    weighted = weight_base is not None
    U_w = 0
    if weighted:
        longest = max((len(p) for p, _ in weight_deltas), default=1)
        U_w = max(256, 1 << int(np.ceil(np.log2(max(longest, 1)))))
        dw_pos = np.full((H, U_w), 2**31 - 1, np.int32)
        dw_val = np.zeros((H, U_w), np.float32)
        for h, (p, v) in enumerate(weight_deltas):
            dw_pos[h, : len(p)] = p
            dw_val[h, : len(v)] = v
    if layout is not None:
        if not h0_delta:
            # host engine-order base → binned (resident bases are the
            # previous BINNED dispatch's advanced state, passed through)
            be_lat, be_alive = layout.bin_base(be_lat, be_alive)
            if weighted:
                weight_base = layout.bin_values(weight_base)
        de_pos = layout.remap_positions(de_pos)
        if weighted:
            dw_pos = layout.remap_positions(dw_pos)
        b_src, b_dst, _valid, b_slot, b_usrc, _perm = layout.device_args()
        e_src_dev, e_dst_dev = b_src, b_dst
    runner = _compiled_delta(kind, tables.n_pad, tables.m_pad, H, W,
                             U_e, U_v, np.dtype(tdt).name,
                             r_init is not None, tuple(algo_args),
                             weighted, U_w, h0_delta, _tile_budget_bytes(),
                             None if layout is None else layout.spec)
    if ship_counter is not None:
        # FOLD-STATE host→device payload of THIS dispatch (padded shapes;
        # device-resident inputs — h0 base, cached tables — ship nothing).
        # O(C) column descriptors and per-dispatch seed masks are excluded
        # on BOTH fold paths, so host-vs-delta numbers compare like for
        # like (engine ship_bytes docstring).
        shipped = [de_pos, de_lat, de_alive, dv_pos, dv_lat, dv_alive]
        if not h0_delta:
            shipped += [a for a in base]
        if weighted:
            shipped += [dw_pos, dw_val]
            if not h0_delta:
                shipped.append(weight_base)
        ship_counter(int(sum(a.nbytes for a in shipped)))
    extra = []
    if seed_mask is not None:
        extra.append(seed_mask)
    if weighted:
        extra.extend((weight_base, dw_pos, dw_val))
    if r_init is not None:
        extra.append(r_init)
    if layout is not None:
        extra.extend((b_slot, b_usrc))   # device-resident bucket tables
    # the whole dispatch payload ships through the pipelined engine: array
    # k+1 stages while k is on the wire, each slice retried on transport
    # errors (device-resident inputs pass through untouched)
    from ..utils.transfer import shared_engine

    with TRACER.span("hop.compute", kind=kind, hops=H, cols=H * W,
                        resident_base=h0_delta, pcpm=layout is not None):
        return runner(*shared_engine().put_many([
            e_src_dev if e_src_dev is not None else tables.e_src,
            e_dst_dev if e_dst_dev is not None else tables.e_dst,
            be_lat, be_alive, bv_lat, bv_alive,
            de_pos, de_lat, de_alive, dv_pos, dv_lat, dv_alive,
            T_col, w_col, *extra]))


def _edge_accumulate(seg, payload_of, combine, init, e_from, e_to, me, ew,
                     n_pad: int, tile, sorted_: bool):
    """``combine(acc, seg(payload_of(ef, mk, ex), et))`` over the edge
    dimension — single-shot when ``tile`` is None, else a ``lax.scan``
    over equal tiles plus a remainder slice (transient bounded at
    tile*C). ``ew`` is an optional per-edge [m_pad, C] operand (weighted
    traversal), sliced alongside. ``init`` must carry the vma the caller's
    loop state carries (see the while_loop seeds)."""
    C = me.shape[1]

    def one(ef, et, mk, ex):
        return seg(payload_of(ef, mk, ex), et, num_segments=n_pad,
                   indices_are_sorted=sorted_)

    if tile is None:
        return combine(init, one(e_from, e_to, me, ew))
    n_main = (e_from.shape[0] // tile) * tile
    xs = (e_from[:n_main].reshape(-1, tile),
          e_to[:n_main].reshape(-1, tile),
          me[:n_main].reshape(-1, tile, C)) + (
        (ew[:n_main].reshape(-1, tile, C),) if ew is not None else ())

    def step(acc, inp):
        ef, et, mk = inp[:3]
        ex = inp[3] if len(inp) > 3 else None
        return combine(acc, one(ef, et, mk, ex)), None

    acc, _ = jax.lax.scan(step, init, xs)
    if n_main < e_from.shape[0]:
        acc = combine(acc, one(e_from[n_main:], e_to[n_main:], me[n_main:],
                               ew[n_main:] if ew is not None else None))
    return acc


def _cc_columns(me, mv, e_src, e_dst, n_pad: int, max_steps: int,
                tile_budget: int | None = None, pcpm=None, l_init=None):
    """Columnar min-label propagation — connected components for every
    (hop, window) column at once (semantics of
    ``algorithms/connected_components.py``: undirected min over both
    directions, labels are global padded indices). Shared by the
    single-device kernel and the column-sharded mesh runner. ``pcpm``
    switches to the destination-binned operands (``_pagerank_columns``
    docstring); min reductions are order-exact, so binned results stay
    BITWISE equal to the unbinned route.

    ``l_init`` ([n_pad, C] i32) warm-starts the propagation from a
    previous epoch's labels: the start is ``min(own index, l_init)``.
    The fixed point of min-label propagation is the min over each
    component of the START values, so the warm result equals the cold
    one iff every warm label is an index of a vertex in the same
    component — true when the graph only GAINED edges/vertices since the
    labels were computed (components only merge; a vertex's old label
    indexes a vertex of its old component ⊆ its new component). Callers
    enforce that monotonicity gate (``jobs/live.py``)."""
    I32_MAX = jnp.iinfo(jnp.int32).max
    lab0 = jnp.where(mv, jnp.arange(n_pad, dtype=jnp.int32)[:, None],
                     I32_MAX)
    if l_init is not None:
        lab0 = jnp.where(mv, jnp.minimum(lab0, l_init), I32_MAX)
    tile = _edge_tile_for(e_src.shape[0], me.shape[1], tile_budget)
    max0 = jnp.full_like(lab0, I32_MAX) \
        + (mv[0] & False).astype(jnp.int32)[None, :]   # vma-seeded

    def body(carry):
        step, lab, halted = carry

        def pull(idx_from, idx_to, sorted_, pre=None):
            pay = lambda ef, mk, _: jnp.where(mk, lab[ef, :], I32_MAX)
            if pre is not None and tile is None and pre[0].preagg:
                _, slot, u_src = pre
                vals = lab[u_src, :]                  # bucket gather
                pay = lambda ef, mk, _: jnp.where(mk, vals[slot, :],
                                                  I32_MAX)
            return _edge_accumulate(
                jax.ops.segment_min, pay,
                jnp.minimum, max0, idx_from, idx_to, me, None,
                n_pad, tile, sorted_)

        agg = jnp.minimum(pull(e_src, e_dst, pcpm is None, pre=pcpm),
                          pull(e_dst, e_src, False))
        new = jnp.where(mv, jnp.minimum(lab, agg), I32_MAX)
        col_done = jnp.all(new == lab, axis=0)
        new = jnp.where(halted[None, :], lab, new)
        return step + 1, new, halted | col_done

    def cond(carry):
        step, _, halted = carry
        return (step < max_steps) & ~jnp.all(halted)

    # vma-safe carry seeds, as in _pagerank_columns
    steps, lab, _ = jax.lax.while_loop(
        cond, body,
        (jnp.int32(0) + (mv[0, 0] & False).astype(jnp.int32),
         lab0, mv[0] & False))
    return lab.T, steps   # [C, n_pad]


@functools.lru_cache(maxsize=64)
def _compiled_cc(n_pad: int, m_pad: int, H: int, C: int, max_steps: int,
                 tdt: str, tile_budget: int | None = None, pcpm=None):
    tdt = jnp.dtype(tdt)

    def run(e_src, e_dst, e_lat, e_alive, v_lat, v_alive,
            hop_of_col, T_col, w_col, *rest):
        me, mv = _column_masks(tdt, e_lat, e_alive, v_lat, v_alive,
                               hop_of_col, T_col, w_col)
        pc = None
        if pcpm is not None:
            me, pc = _bin_masks(me, (pcpm,) + rest[-4:])
        return _cc_columns(me, mv, e_src, e_dst, n_pad, max_steps,
                           tile_budget=tile_budget, pcpm=pc)

    return _ledger.instrument("hopbatch.cc_cols", jax.jit(run),
                              traffic=_traffic(m_pad, C, n_pad, pcpm))


def _bfs_columns(me, mv, e_src, e_dst, n_pad: int, max_steps: int,
                 directed: bool, seed_mask, ew,
                 tile_budget: int | None = None, pcpm=None, d_init=None):
    """Columnar min-plus traversal (``algorithms/traversal.SSSP``
    semantics); ``ew`` is 1.0 for hop counting or [m_pad, C] f32 weights
    (BINNED when ``pcpm`` is set, like ``me``/``e_src``/``e_dst`` — see
    ``_pagerank_columns``). Min-plus is order-exact, so binned results
    stay bitwise equal. Shared by the single-device kernel and the
    column-sharded runner.

    ``d_init`` ([n_pad, C] f32) warm-starts the relaxation with
    ``min(cold seed, d_init)``: valid whenever every finite ``d_init``
    entry is a REALIZABLE path length in the current graph — true when
    edges/vertices were only ADDED (at unit/unchanged weight) since the
    distances were computed, so old shortest paths still exist and
    relaxation can only tighten them. Callers enforce the gate
    (``jobs/live.py``); weighted SSSP never warm-starts (a re-add can
    RAISE a pair's weight, leaving stale under-estimates)."""
    INF = jnp.float32(jnp.inf)
    d0 = jnp.where(mv & seed_mask[:, None], 0.0, INF)
    if d_init is not None:
        d0 = jnp.where(mv, jnp.minimum(d0, d_init), INF)
    tile = _edge_tile_for(e_src.shape[0], me.shape[1], tile_budget)
    ew_arr = None if not hasattr(ew, "shape") or ew.ndim == 0 else ew
    inf0 = jnp.full_like(d0, INF) \
        + (mv[0] & False).astype(jnp.float32)[None, :]   # vma-seeded

    def body(carry):
        step, dist, halted = carry

        def pull(idx_from, idx_to, sorted_, pre=None):
            pay = lambda ef, mk, ex: jnp.where(
                mk, dist[ef, :] + (ew if ex is None else ex), INF)
            if pre is not None and tile is None and pre[0].preagg:
                _, slot, u_src = pre
                vals = dist[u_src, :]                 # bucket gather
                pay = lambda ef, mk, ex: jnp.where(
                    mk, vals[slot, :] + (ew if ex is None else ex), INF)
            return _edge_accumulate(
                jax.ops.segment_min, pay,
                jnp.minimum, inf0, idx_from, idx_to, me, ew_arr,
                n_pad, tile, sorted_)

        agg = pull(e_src, e_dst, pcpm is None, pre=pcpm)
        if not directed:
            agg = jnp.minimum(agg, pull(e_dst, e_src, False))
        new = jnp.where(mv, jnp.minimum(dist, agg), INF)
        col_done = jnp.all(new == dist, axis=0)
        new = jnp.where(halted[None, :], dist, new)
        return step + 1, new, halted | col_done

    def cond(carry):
        step, _, halted = carry
        return (step < max_steps) & ~jnp.all(halted)

    # vma-safe carry seeds, as in _pagerank_columns
    steps, dist, _ = jax.lax.while_loop(
        cond, body,
        (jnp.int32(0) + (mv[0, 0] & False).astype(jnp.int32),
         d0, mv[0] & False))
    return dist.T, steps   # [C, n_pad]


@functools.lru_cache(maxsize=64)
def _compiled_bfs(n_pad: int, m_pad: int, H: int, C: int, max_steps: int,
                  directed: bool, tdt: str, weighted: bool = False,
                  tile_budget: int | None = None, pcpm=None):
    tdt = jnp.dtype(tdt)

    def run(e_src, e_dst, e_lat, e_alive, v_lat, v_alive,
            hop_of_col, T_col, w_col, seed_mask, *rest):
        me, mv = _column_masks(tdt, e_lat, e_alive, v_lat, v_alive,
                               hop_of_col, T_col, w_col)
        ew = rest[0][hop_of_col].T if weighted else 1.0   # [m_pad, C]
        pc = None
        if pcpm is not None:
            me, pc = _bin_masks(me, (pcpm,) + rest[-4:])
            if weighted:
                ew = ew[rest[-4], :]   # weights follow the edge permutation
        return _bfs_columns(me, mv, e_src, e_dst, n_pad, max_steps,
                            directed, seed_mask, ew,
                            tile_budget=tile_budget, pcpm=pc)

    return _ledger.instrument("hopbatch.bfs_cols", jax.jit(run),
                              traffic=_traffic(m_pad, C, n_pad, pcpm))


def _seed_mask(tables, seed_vids) -> np.ndarray:
    """Global dense-space seed mask from external vertex ids (absent ids
    ignored)."""
    seed_mask = np.zeros(tables.n_pad, bool)
    seeds = np.asarray(sorted({int(v) for v in seed_vids}), np.int64)
    if len(seeds) and len(tables.uv):
        pos = np.clip(np.searchsorted(tables.uv, seeds), 0,
                      len(tables.uv) - 1)
        ok = tables.uv[pos] == seeds
        seed_mask[pos[ok]] = True
    return seed_mask


def _layout_dispatch_args(layout):
    """(e_src_dev, e_dst_dev, trailing pcpm args) for a host-column
    dispatch through the binned kernels — the edge operands become the
    layout's global ``b_src``/``b_dst`` and the kernels bin the fold-state
    masks in-program via the appended (perm, valid, slot, u_src)."""
    b_src, b_dst, valid, slot, u_src, perm = layout.device_args()
    return b_src, b_dst, (perm, valid, slot, u_src)


def run_bfs_columns(tables, e_lat, e_alive, v_lat, v_alive, hop_times,
                    windows, seed_vids, *, directed: bool = False,
                    max_steps: int = 100, e_src_dev=None, e_dst_dev=None,
                    weight_cols=None, layout=None):
    """Columnar min-plus traversal over prebuilt fold columns;
    ``seed_vids`` are external vertex ids looked up in the global dense
    space (absent ids ignored). ``weight_cols`` ([H, m_pad] f32, missing
    folded to 1.0) turns hop counting into weighted SSSP."""
    H, C, hop_of_col, T_col, w_col = _column_layout(hop_times, windows)
    seed_mask = _seed_mask(tables, seed_vids)
    runner = _compiled_bfs(tables.n_pad, tables.m_pad, H, C, int(max_steps),
                           bool(directed), np.dtype(tables.tdtype).name,
                           weight_cols is not None, _tile_budget_bytes(),
                           None if layout is None else layout.spec)
    extra = (seed_mask,) if weight_cols is None \
        else (seed_mask, weight_cols)
    if layout is not None:
        e_src_dev, e_dst_dev, pc = _layout_dispatch_args(layout)
        extra = extra + pc
    return _dispatch_columns(runner, tables,
                             (e_lat, e_alive, v_lat, v_alive),
                             hop_of_col, T_col, w_col, e_src_dev, e_dst_dev,
                             *extra)


def run_cc_columns(tables, e_lat, e_alive, v_lat, v_alive, hop_times,
                   windows, *, max_steps: int = 100,
                   e_src_dev=None, e_dst_dev=None, layout=None):
    """Columnar connected components over prebuilt per-hop fold columns."""
    H, C, hop_of_col, T_col, w_col = _column_layout(hop_times, windows)
    runner = _compiled_cc(tables.n_pad, tables.m_pad, H, C, int(max_steps),
                          np.dtype(tables.tdtype).name, _tile_budget_bytes(),
                          None if layout is None else layout.spec)
    extra = ()
    if layout is not None:
        e_src_dev, e_dst_dev, extra = _layout_dispatch_args(layout)
    return _dispatch_columns(runner, tables,
                             (e_lat, e_alive, v_lat, v_alive),
                             hop_of_col, T_col, w_col, e_src_dev, e_dst_dev,
                             *extra)


def _payload_nbytes(obj) -> int:
    """Recursive numpy-array byte count of a fold payload — what the
    bounded fold cache accounts an entry at."""
    if obj is None:
        return 0
    if isinstance(obj, np.ndarray):
        return obj.nbytes
    if isinstance(obj, (list, tuple)):
        return sum(_payload_nbytes(x) for x in obj)
    return 8   # scalars (hop times in vshell rows)


class _HopBatched:
    """Shared incremental fold → per-hop state columns (deletes included).

    ``run(hop_times, windows, chunks=k)`` splits the sweep into ``k``
    equal hop groups and pipelines them: group ``i+1``'s HOST fold +
    staging run in the lookahead prefetch worker (``RTPU_PREFETCH=0``
    disables) while group ``i``'s payload ships through the pipelined
    transfer engine and its supersteps run on DEVICE — fold → stage →
    ship → compute, the pipelining a one-dispatch sweep can't have.
    Equal group sizes reuse one compiled program. Results match
    ``chunks=1`` (hop-major concatenation; tested — bitwise for the
    integer/min-plus kernels, within solver tolerance for PageRank,
    whose differently-shaped chunk programs may round f32 reductions
    differently on some XLA versions)."""

    def __init__(self, log: EventLog):
        # fold state only — the columnar engines never emit GraphViews, so
        # the per-hop add-row list merges are skipped entirely
        self.sw = SweepBuilder(log, track_rows=False, preseed_pairs=True)
        self.tables = GlobalTables(self.sw)
        # cache key for the device edge tables: the CALLER's log object
        # (sw.log is a fresh pin per engine and would never hit)
        self._log = log
        #: host seconds spent folding + writing columns in the LAST run()
        #: (callers report it as snapshot-build time; under the lookahead
        #: prefetcher this is WORKER time, overlapped with device compute)
        self.fold_seconds = 0.0
        #: the LAST run()'s fold seconds split by pipeline mode
        #: (serial / parallel / cache_hit replay) — the resource ledger's
        #: fold breakdown. Single writer per mode within one run (the one
        #: prefetch worker, or the dispatch thread's consume), and read
        #: only after the run's folds have drained.
        self.fold_mode_seconds: dict = {}
        #: seconds the LAST run()'s dispatch loop spent WAITING on the
        #: lookahead fold — 0 means the fold hid entirely behind compute
        self.fold_stall_seconds = 0.0
        #: host→device FOLD-STATE payload bytes of the LAST run() — the
        #: quantity the resident-base design exists to minimise. Excluded
        #: on both fold paths, so comparisons are like for like: the
        #: per-log static tables (ship once per log), O(C) column
        #: descriptors, and per-dispatch seed masks.
        self.ship_bytes = 0
        #: the LAST run()'s fold/stage/ship/compute breakdown
        #: (``device_sweep.sweep_phase_summary``)
        self.last_phase_seconds: dict = {}
        # static edge tables upload LAZILY on the first dispatch (callers
        # that only use the host fold — e.g. the column-sharded mesh
        # route — never pay the device transfer), then cache
        self._edges = None
        # running host base for the delta-fold path (built on first use)
        self._delta_base = None
        # device-resident advanced base: the last delta dispatch's
        # post-final-hop fold state, fed back as the next dispatch's base
        # so follow-on chunks/batches ship only deltas (the host↔device
        # link, not the fold, is the binding cost on a tunnelled device)
        self._dev_base = None
        # the PCPM layout spec the resident base is expressed in (None =
        # engine order): a knob flip between batches must drop residency,
        # never scatter one layout's delta onto the other's state
        self._dev_base_spec = None
        # the run's resolved partition layout (ops/partition.py), fixed
        # for the whole run at its start — None on the unbinned route
        self._active_layout = None
        # cross-epoch warm seed (run(..., warm_state=...)): initialises
        # the FIRST dispatch's iteration from a previous run's output —
        # the live epoch engine's warm-start channel (jobs/live.py)
        self._epoch_seed = None

    @property
    def _e_src(self):
        if self._edges is None:
            self._edges = _device_edges(self._log, self.tables)
        return self._edges[0]

    @property
    def _e_dst(self):
        if self._edges is None:
            self._edges = _device_edges(self._log, self.tables)
        return self._edges[1]

    def _drop_residency(self) -> None:
        """Forget the device-resident advanced base AND retire its
        resident-gauge row (obs/device.py) — every site that invalidates
        residency goes through here, or /devicez keeps reporting device
        bytes the backend already freed."""
        self._dev_base = None
        from ..obs import device as _obs_device

        _obs_device.RESIDENT.drop(self, "advanced_base")

    def _delta_base_args(self, ship_base):
        """(base_for_dispatch, h0_delta): the device-resident advanced
        state when the fold shipped no base snapshot, else the host
        snapshot (first batch, or residency was invalidated)."""
        if ship_base is None:
            return tuple(self._dev_base[:4]), True
        return ship_base, False

    def _count_ship(self, nbytes: int) -> None:
        self.ship_bytes += int(nbytes)

    def _run_delta(self, fn):
        """Run a delta dispatch and keep its advanced base device-resident;
        any dispatch-time failure drops residency so the next batch falls
        back to shipping a fresh base snapshot (execute-time failures are
        the jobs layer's concern — it rebuilds the engine)."""
        from ..obs import device as _obs_device

        try:
            out, steps, adv = fn()
        except Exception:
            self._drop_residency()
            raise
        self._dev_base = adv
        self._dev_base_spec = (None if self._active_layout is None
                               else self._active_layout.spec)
        # resident-buffer gauge (obs/device.py): the advanced base is
        # what the next batch scatters onto instead of shipping a full
        # snapshot — a live row, re-upserted per delta dispatch
        _obs_device.RESIDENT.track(self, "advanced_base",
                                   _obs_device.nbytes_tree(adv))
        return out, steps

    def repin(self) -> str:
        """Adopt rows appended to the live log since this engine's pin
        (``SweepBuilder.repin``): on ``"extended"`` every piece of engine
        state stays valid — the dense dictionaries and pair table are
        unchanged, so ``GlobalTables``, the cached device edge tables,
        the host delta base AND the device-resident advanced base all
        keep describing the same coordinate space, and the next ``run``
        folds exactly the appended suffix. Returns ``"noop"`` /
        ``"extended"`` / ``"rebuild"``; after ``"rebuild"`` the engine
        must be DISCARDED and rebuilt over the live log (its pin may
        already be rebound past the decision point)."""
        n_old = len(self.sw._t)
        status = self.sw.repin(self._log)
        if status != "extended":
            return status
        t_new = self.sw._t[n_old:]
        tdt = np.dtype(self.tables.tdtype)
        if tdt == np.int32 and len(t_new) and not (
                int(t_new.min()) > np.iinfo(np.int32).min // 2
                and int(t_new.max()) < np.iinfo(np.int32).max // 2):
            return "rebuild"   # suffix overflows the narrowed time dtype
        return "extended"

    def _sync_layout(self):
        """Resolve the partition layout ONCE per run (``RTPU_PCPM`` /
        ``RTPU_PARTITIONS`` are dispatch-time knobs), and drop the
        device-resident advanced base when it is expressed in a different
        edge layout than this run will dispatch in — a catch-up delta
        remapped for one layout scattered onto the other's state would be
        silently wrong, not slow."""
        from ..ops import partition as _partition

        lay = _partition.resolve(self._log, self.tables,
                                 _tile_budget_bytes())
        spec = None if lay is None else lay.spec
        if self._dev_base is not None and self._dev_base_spec != spec:
            self._drop_residency()
        self._active_layout = lay
        return lay

    #: set True by subclasses whose iteration is a contraction (safe to
    #: warm-start from the previous chunk's solution)
    supports_warm_start = False

    #: subclasses whose kernel has a delta-fed variant (device-side mask
    #: rebuild, ``_masks_from_deltas``; SSSP additionally rebuilds its
    #: weight state from base + per-hop deltas)
    supports_delta_fold = False

    #: subclasses whose DELTA kernel accepts a cross-epoch warm seed
    #: (``run(..., warm_state=...)``) under the caller-enforced monotone
    #: gate — CC/BFS min-merge warm init. Contraction engines
    #: (``supports_warm_start``) accept the seed on every path instead.
    supports_epoch_warm = False

    #: set False by subclasses whose fold threads extra SEQUENTIAL state
    #: through the engine (SSSP's weight cursor) — they keep the serial
    #: shared-builder pipeline regardless of ``RTPU_FOLD_WORKERS``
    supports_parallel_fold = True

    def _use_delta_fold(self) -> bool:
        import os

        if not self.supports_delta_fold:
            return False
        return os.environ.get("RTPU_FOLD", "delta") != "host"

    def host_column_bytes(self, n_hops: int) -> int:
        """Host bytes the fold will materialise for an ``n_hops`` sweep —
        O(base) on the delta path, O(H · (m_pad + n_pad)) on the
        host-column path. Routing layers size their admission guards from
        THIS, not from engine internals."""
        t = self.tables
        per_row = np.dtype(t.tdtype).itemsize + 1   # lat + alive
        if self._use_delta_fold():
            return (t.m_pad + t.n_pad) * per_row
        return n_hops * (t.m_pad + t.n_pad) * per_row

    def device_mask_bytes(self, n_cols: int) -> int:
        """Device bytes of the [m_pad+n_pad, C] bool masks every columnar
        kernel holds across its superstep loop."""
        return (self.tables.m_pad + self.tables.n_pad) * n_cols

    def _dispatch_cols(self, cols, hop_times, windows, r_init=None):
        raise NotImplementedError

    def _dispatch_deltas(self, payload, hop_times, windows, r_init=None):
        raise NotImplementedError

    def run(self, hop_times, windows, chunks: int = 1,
            warm_start: bool = False, hop_callback=None, warm_state=None):
        """``chunks=k`` pipelines the sweep; ``warm_start=True``
        additionally initialises each chunk's columns from the previous
        chunk's LAST-hop ranks (same fixed point, reached in far fewer
        steps when consecutive hops differ little). Warm-started results
        agree with cold ones to the solver tolerance, not bitwise.

        ``warm_state`` (a previous ``run``'s output, ``[C_prev, n_pad]``
        with the SAME window count) seeds the FIRST dispatch the same
        way — the cross-epoch warm channel of the live epoch engine.
        Contraction engines (PageRank) accept it unconditionally; for
        CC/BFS the min-merge warm init is only equivalent under the
        monotone (add-only, unwindowed) gate the CALLER must enforce
        (``jobs/live.py``; kernel docstrings state the argument), and it
        is ignored on the host-column path, which has no warm plumbing.

        With ``RTPU_FOLD_WORKERS`` > 1 the chunk folds run CONCURRENTLY
        on forked builders (bit-identical payloads — docs/FOLD.md), and
        ``hop_callback`` may fire from worker threads in any hop order —
        key captures by the hop time argument, never by call order. An
        exact (log, hop grid) repeat serves its fold from the bounded
        cross-request fold cache (``RTPU_FOLD_CACHE_MB``); on a hit the
        callback replays from cached per-hop vertex state and
        ``fold_seconds`` stays ~0."""
        self.fold_seconds = 0.0
        self.fold_mode_seconds = {}
        self.fold_stall_seconds = 0.0
        self.ship_bytes = 0
        self._sync_layout()
        if warm_start and not self.supports_warm_start:
            raise ValueError(
                f"{type(self).__name__} cannot warm-start: its superstep "
                "is not a contraction (stale state would be wrong, not "
                "just slower)")
        self._epoch_seed = None
        if warm_state is not None and (
                self.supports_warm_start
                or (self.supports_epoch_warm and self._use_delta_fold())):
            self._epoch_seed = warm_state
        hop_times = [int(x) for x in hop_times]
        chunks = max(1, min(int(chunks), len(hop_times)))
        from ..utils.transfer import shared_engine

        before = shared_engine().stats.as_dict()
        t_start = _time.perf_counter()
        try:
            with TRACER.span("sweep.columnar",
                                engine=type(self).__name__,
                                hops=len(hop_times), chunks=chunks) as sp:
                out = self._run_chunks(hop_times, windows, chunks,
                                       warm_start, hop_callback)
                self.last_phase_seconds = sweep_phase_summary(
                    sp, _time.perf_counter() - t_start, self.fold_seconds,
                    self.fold_stall_seconds,
                    shared_engine().stats.delta_since(before),
                    self.ship_bytes, len(hop_times),
                    fold_modes=self.fold_mode_seconds)
            return out
        except Exception:
            # ANY mid-run failure (fold, hop_callback, dispatch) may leave
            # the host fold ahead of the device-resident base — drop
            # residency so the next batch ships a fresh snapshot instead
            # of silently scattering onto a stale device state. The HOST
            # base must go too: an advance that aborted after consuming
            # events but before _apply_delta_to_base leaves it missing
            # that window (last_delta only spans the latest advance), so
            # the next batch must re-materialise from the sweep's full
            # state, not snapshot the stale running base.
            self._drop_residency()
            self._delta_base = None
            raise

    def _use_prefetch(self) -> bool:
        import os

        return os.environ.get("RTPU_PREFETCH", "1") != "0"

    def _observe_fold(self, seconds: float, mode: str) -> None:
        # the per-mode split feeds the resource ledger (fold_seconds
        # itself stays the modes' sum EXCEPT cache_hit replay, which is
        # accounted as a mode but never as fold time — a hit's fold cost
        # is, by contract, 0)
        self.fold_mode_seconds[mode] = (
            self.fold_mode_seconds.get(mode, 0.0) + float(seconds))
        m = _metrics()
        if m is not None:
            m.fold_seconds.labels(mode).observe(float(seconds))

    def _fold_token(self):
        """Engine-specific component of the fold-cache key. The base fold
        payload depends only on the log and the hop grid — PageRank, CC
        and BFS over the same log SHARE cached payloads; engines whose
        fold carries extra state (SSSP weights) must disambiguate."""
        return None

    def _cache_key(self, cache, delta: bool, hop_times, n_groups: int):
        if cache is None:
            return None
        if self.sw.t_prev is not None and hop_times[0] < self.sw.t_prev:
            return None   # the fold path owns the backward-batch refusal
        if len(set(hop_times)) != len(hop_times):
            return None   # duplicate hops: capture order is ambiguous
        # the per-hop vertex-state capture (shell replay) alone would
        # outgrow the bound at scale — don't materialise H*n*17 bytes the
        # put would only refuse
        if len(hop_times) * len(self.sw.uv) * 17 > cache.max_bytes:
            return None
        return ("fold", log_fingerprint(self.sw.log), self._fold_token(),
                "delta" if delta else "cols", tuple(hop_times),
                int(n_groups))

    @staticmethod
    def _capture_cb(hop_callback, cap):
        """Wrap ``hop_callback`` to ALSO capture the per-hop vertex fold
        state (the reducer-shell inputs) into ``cap`` — what a fold-cache
        hit replays so callback-bearing jobs can skip folding too."""
        if cap is None:
            return hop_callback

        def cb(T, sw):
            cap.append((int(T), sw.v_lat.copy(), sw.v_alive.copy(),
                        sw.v_first.copy()))
            if hop_callback is not None:
                hop_callback(T, sw)
        return cb

    @staticmethod
    def _replay_vshells(vshells, hop_callback) -> None:
        from types import SimpleNamespace

        for T, vl, va, vf in vshells:
            hop_callback(T, SimpleNamespace(v_lat=vl, v_alive=va,
                                            v_first=vf))

    def _maybe_cache(self, cache, key, payloads, cap, delta) -> None:
        """Insert this sweep's fold output into the cross-request cache.
        Delta payloads are only replayable on a fresh engine when group 0
        shipped a full base snapshot (a resident fold's payload assumes
        THIS engine's device state)."""
        if cache is None or key is None or any(
                p is None for p in payloads):
            return
        if delta and payloads[0][0] is None:
            return
        vshells = sorted(cap, key=lambda r: r[0]) if cap else None
        nbytes = _payload_nbytes(payloads) + _payload_nbytes(vshells)
        cache.put(key, (list(payloads), vshells), nbytes)

    def _dispatch_group(self, payload, group, windows, delta, warm_start,
                        outs, steps_box) -> None:
        r_init = None
        if warm_start and outs:
            # previous chunk's FULL output; the kernel slices its last
            # hop's W windowed rows and tiles them per hop of this
            # group IN-PROGRAM — no extra host-issued device ops
            # between dispatches (each is a tunnel round-trip)
            r_init = outs[-1]                              # [per*W, n_pad]
        elif not outs and self._epoch_seed is not None:
            # first dispatch of an epoch run: seed from the PREVIOUS
            # run's output (same tail-slice-and-tile contract as the
            # intra-run warm chunks; jobs/live.py owns the validity gate)
            r_init = self._epoch_seed
        if delta:
            out, st = self._dispatch_deltas(payload, group, windows,
                                            r_init=r_init)  # async
        else:
            out, st = self._dispatch_cols(payload, group, windows,
                                          r_init=r_init)   # async
        outs.append(out)
        steps_box[0] = jnp.maximum(steps_box[0], st)

    def _run_chunks(self, hop_times, windows, chunks, warm_start,
                    hop_callback):
        if sorted(hop_times) != hop_times:
            raise ValueError("hop_times must ascend")
        if self.sw.t_prev is not None and hop_times[0] < self.sw.t_prev:
            raise ValueError(
                f"hop_times must continue forward from the previous batch "
                f"(got {hop_times[0]} < {self.sw.t_prev}); build a fresh "
                f"{type(self).__name__} to go back in history")
        delta = self._use_delta_fold()
        if chunks == 1 or len(hop_times) % chunks:
            # unequal groups would compile one program per distinct size —
            # pipeline only when the split is clean
            if warm_start and chunks > 1:
                _log.warning(
                    "%d hops do not split into %d equal chunks — running "
                    "one cold dispatch (warm_start has no effect)",
                    len(hop_times), chunks)
            groups = [list(hop_times)]
        else:
            per = len(hop_times) // chunks
            groups = [hop_times[c * per: (c + 1) * per]
                      for c in range(chunks)]

        # ---- cross-request fold cache: an exact (log, hop grid) repeat
        # skips folding entirely (the repeated-REST-range serving story)
        cache = fold_cache()
        key = self._cache_key(cache, delta, hop_times, len(groups))
        if key is not None:
            hit = cache.get(key)
            if hit is not None:
                payloads, vshells = hit
                if hop_callback is None or vshells is not None:
                    # the warm path still emits a hop.fold span (near-zero
                    # duration, mode=cache_hit): a traced sweep's phase
                    # timeline must show WHERE the fold went — "served
                    # from cache" — not silently omit the phase, and the
                    # ledger's fold breakdown records the replay the same
                    # way (fold_seconds stays 0: a hit's fold cost IS 0)
                    f0 = _time.perf_counter()
                    with TRACER.span("hop.fold", hops=len(hop_times),
                                        engine=type(self).__name__,
                                        mode="cache_hit"):
                        if hop_callback is not None:
                            self._replay_vshells(vshells, hop_callback)
                    self._observe_fold(_time.perf_counter() - f0,
                                       "cache_hit")
                    led = _ledger.current()
                    if led is not None:
                        led.fold_cache_event(hit=True)
                    outs, steps_box = [], [jnp.int32(0)]
                    for c, g in enumerate(groups):
                        self._dispatch_group(payloads[c], g, windows,
                                             delta, warm_start, outs,
                                             steps_box)
                    # the hit advanced the DEVICE base to the cached
                    # grid's last hop while the host fold clock (self.sw)
                    # never moved — a later resident batch would scatter
                    # an older catch-up delta onto that newer state. Drop
                    # residency: the next batch ships a base from the
                    # host clock, which is always consistent.
                    self._drop_residency()
                    return jnp.concatenate(outs, axis=0), steps_box[0]
                # cached without shells but this job needs them: refold
            led = _ledger.current()
            if led is not None:
                # a None hit AND the shell-less-entry refold both cost
                # this query a full fold — the ledger counts both as
                # misses (the global FoldCache stats count raw lookups)
                led.fold_cache_event(hit=False)

        workers = fold_workers()
        if (workers > 1 and self.supports_parallel_fold
                and self._use_prefetch() and len(hop_times) > 1):
            return self._fold_dispatch_parallel(
                groups, windows, warm_start, hop_callback, delta,
                cache, key, workers)
        return self._fold_dispatch_serial(
            groups, windows, warm_start, hop_callback, delta, cache, key)

    def _fold_dispatch_serial(self, groups, windows, warm_start,
                              hop_callback, delta, cache, key):
        """The shared-builder pipeline: groups fold one at a time on the
        single prefetch worker (``RTPU_PREFETCH_DEPTH`` of them queued
        ahead) while earlier groups ship and compute — today's behaviour,
        and the only safe shape for engines whose fold mutates shared
        state (``supports_parallel_fold = False``)."""
        cap = [] if key is not None else None
        cb = self._capture_cb(hop_callback, cap)
        payloads = [None] * len(groups)
        outs, steps_box = [], [jnp.int32(0)]

        def fold(c, group, lookahead: bool):
            # a lookahead fold runs BEFORE the previous group's delta
            # dispatch is issued — it must assume that dispatch will leave
            # a device-resident base (assume_resident), or chunk 2+ would
            # re-ship a full base snapshot the serial loop never ships
            with TRACER.span("hop.fold", hops=len(group),
                                engine=type(self).__name__):
                t0 = _time.perf_counter()
                if delta:
                    _, p = self._fold_deltas(group, cb,
                                             assume_resident=lookahead)
                else:
                    _, p = self._fold_columns(group, cb)
                self._observe_fold(_time.perf_counter() - t0, "serial")
            return c, group, p

        def dispatch(fold_out, stall):
            c, group, payload = fold_out
            self.fold_stall_seconds += stall
            if stall > 0:
                TRACER.complete("fold.stall", stall, hops=len(group))
            payloads[c] = payload
            self._dispatch_group(payload, group, windows, delta,
                                 warm_start, outs, steps_box)

        if self._use_prefetch() and len(groups) > 1:
            # hop-lookahead prefetch: group c+1's host fold + staging run
            # in the prefetch worker while group c's payload ships and its
            # columnar program runs on device — fold → stage → ship →
            # compute. Dispatch (result order) stays on THIS thread.
            prefetch_map(
                (functools.partial(fold, c, g, c > 0)
                 for c, g in enumerate(groups)),
                dispatch)
        else:
            for c, g in enumerate(groups):
                dispatch(fold(c, g, False), 0.0)
        self._maybe_cache(cache, key, payloads, cap, delta)
        return jnp.concatenate(outs, axis=0), steps_box[0]

    def fold_payloads(self, hop_times, chunks: int = 1):
        """Fold the sweep's chunk payloads WITHOUT dispatching — the
        serial/parallel fold A/B surface (``bench.py --config
        fold_parallel`` and the equivalence tests). Honours
        ``RTPU_FOLD_WORKERS`` exactly like ``run()`` (serial pipeline at
        1, forked parallel folds above); the fold cache is never
        consulted — this measures/exercises folding itself. Returns
        ``(groups, payloads)``, one payload per dispatch group, identical
        to what ``run(hop_times, ..., chunks=chunks)`` would dispatch."""
        hop_times = [int(x) for x in hop_times]
        chunks = max(1, min(int(chunks), len(hop_times)))
        self._sync_layout()
        if chunks > 1 and len(hop_times) % chunks:
            chunks = 1
        per = len(hop_times) // chunks
        groups = [hop_times[c * per:(c + 1) * per] for c in range(chunks)]
        delta = self._use_delta_fold()
        workers = fold_workers()
        if (workers > 1 and self.supports_parallel_fold
                and len(hop_times) > 1):
            # checkpoints participate (key=None keeps payload entries
            # out): repeated folds seed their forks at the boundaries
            # and skip the prefix re-fold — the serving steady state
            payloads, _ = self._fold_groups_parallel(
                groups, None, delta, fold_cache(), None, workers,
                lambda c, p: None)
            return groups, payloads
        payloads = []
        for c, g in enumerate(groups):
            t0 = _time.perf_counter()
            if delta:
                # chunks 1+ fold all-delta exactly like the pipelined
                # run (the previous chunk's dispatch leaves a resident
                # base); chunk 0 ships the base snapshot
                _, p = self._fold_deltas(g, None, assume_resident=c > 0)
            else:
                _, p = self._fold_columns(g, None)
            self._observe_fold(_time.perf_counter() - t0, "serial")
            payloads.append(p)
        return groups, payloads

    def _fold_dispatch_parallel(self, groups, windows, warm_start,
                                hop_callback, delta, cache, key, workers):
        outs, steps_box = [], [jnp.int32(0)]

        def on_payload(c, payload):
            self._dispatch_group(payload, groups[c], windows, delta,
                                 warm_start, outs, steps_box)

        payloads, cap = self._fold_groups_parallel(
            groups, hop_callback, delta, cache, key, workers, on_payload)
        self._maybe_cache(cache, key, payloads, cap, delta)
        return jnp.concatenate(outs, axis=0), steps_box[0]

    def _fold_groups_parallel(self, groups, hop_callback, delta, cache,
                              key, workers, on_payload):
        """Parallel chunk folds: every fold unit runs on an INDEPENDENT
        fork of the sweep's builder (seeded by one bulk advance to the
        previous unit's boundary — or a cached checkpoint), concurrently
        on the sized ``fold_pool``. A single dispatch group additionally
        sub-splits across workers (every column row / delta list is
        absolute state, so parts just concatenate). ``on_payload(c,
        payload)`` fires on THIS thread as each dispatch group completes,
        in group order; results are bit-identical to the serial fold
        (tested per engine). ``hop_callback`` runs on worker threads and
        may interleave across units — callers key their capture by hop
        time, not call order."""
        if len(groups) == 1 and len(groups[0]) >= 2:
            hops0 = groups[0]
            n_sub = min(workers, len(hops0))
            per = -(-len(hops0) // n_sub)
            units = [{"c": 0, "hops": hops0[u * per:(u + 1) * per],
                      "off": u * per} for u in range(n_sub)]
            units = [u for u in units if u["hops"]]
        else:
            units = [{"c": c, "hops": g, "off": 0}
                     for c, g in enumerate(groups)]
        left_in_group = [0] * len(groups)
        for u in units:
            left_in_group[u["c"]] += 1

        fp = log_fingerprint(self.sw.log) if cache is not None else None
        cfg = self.sw._config()
        resident0 = delta and self._dev_base is not None
        cols_out = None
        if not delta:
            # the host-column path advances the fold WITHOUT maintaining
            # the running delta base — residency must drop here exactly
            # like serial ``_fold_columns``, or a later delta batch would
            # scatter onto a device state frozen several batches back
            self._delta_base = None
            self._drop_residency()
            cols_out = [self._alloc_columns(len(g)) for g in groups]
        cap = [] if key is not None else None
        cb = self._capture_cb(hop_callback, cap)

        def make_task(u: int):
            unit = units[u]
            if u > 0:
                boundary = int(units[u - 1]["hops"][-1])
            elif delta and resident0:
                # the resident chain pins unit 0 to the live engine
                # clock: its catch-up delta must cover exactly
                # (engine clock, first hop] — a checkpoint seed ahead of
                # the clock would drop updates the device never saw
                boundary = None
            else:
                # non-resident unit 0 emits ABSOLUTE state (base snapshot
                # / column rows) — seed it at its own first hop so a warm
                # checkpoint store removes the hop-0 bulk fold too
                boundary = int(unit["hops"][0])

            def task():
                t0 = _time.perf_counter()
                # worker attr: the pool thread's name on the span itself,
                # so /tracez?trace_id= shows WHICH fold worker ran each
                # unit without joining against thread metadata (the span
                # still joins the request's trace via the pool-handoff
                # context adopted by prefetch_map — core/sweep.py)
                with TRACER.span("hop.fold", hops=len(unit["hops"]),
                                    engine=type(self).__name__,
                                    mode="parallel",
                                    worker=_threading.current_thread(
                                        ).name):
                    sw = self._seed_fork(boundary, cache, fp, cfg)
                    if delta:
                        ship = unit["c"] == 0 and unit["off"] == 0 \
                            and not resident0
                        part = self._fold_deltas_fork(sw, unit["hops"],
                                                      ship, cb)
                    else:
                        part = None
                        self._fold_columns_fork(sw, unit["hops"], cb,
                                                cols_out[unit["c"]],
                                                unit["off"])
                return u, sw, part, _time.perf_counter() - t0
            return task

        pending: dict[int, list] = {}
        payloads = [None] * len(groups)
        last_sw = [None]

        def consume(res, stall):
            u, sw, part, dt = res
            self.fold_seconds += dt
            self._observe_fold(dt, "parallel")
            self.fold_stall_seconds += stall
            if stall > 0:
                TRACER.complete("fold.stall", stall,
                                   hops=len(units[u]["hops"]))
            last_sw[0] = sw
            c = units[u]["c"]
            pending.setdefault(c, []).append(part)
            left_in_group[c] -= 1
            if left_in_group[c]:
                return
            parts = pending.pop(c)
            if delta:
                payload = parts[0] if len(parts) == 1 \
                    else self._merge_delta_parts(parts)
            else:
                payload = cols_out[c]
                self.ship_bytes += sum(a.nbytes for a in payload)
            payloads[c] = payload
            on_payload(c, payload)

        prefetch_map([make_task(u) for u in range(len(units))], consume,
                     depth=len(units), pool=fold_pool())
        # adopt the final fork: the engine's host fold clock ends at the
        # sweep's last hop, exactly like the serial path. The running
        # host base was never advanced — drop it (resident batches
        # re-materialise it lazily from the adopted builder's state).
        self.sw = last_sw[0]
        self._delta_base = None
        return payloads, cap

    def _seed_fork(self, boundary, cache, fp, cfg):
        """Fork the sweep's builder at ``boundary`` (exclusive upper time
        of every earlier unit's hops): nearest cached checkpoint when one
        is ahead of the live builder, else the live state, then one bulk
        advance — recorded back as a checkpoint for the next request."""
        if boundary is None:
            return self.sw.fork()
        cp = cache.nearest_checkpoint(fp, cfg, boundary) \
            if cache is not None else None
        t0 = self.sw.t_prev
        if cp is not None and (t0 is None or cp.t_prev > t0):
            sw = self.sw.fork(cp)
        else:
            sw = self.sw.fork()
        if sw.t_prev is None or sw.t_prev < boundary:
            with TRACER.span("fold.checkpoint", time=int(boundary),
                                seeded_from=(-1 if sw.t_prev is None
                                             else int(sw.t_prev))):
                sw._advance(boundary)
            if cache is not None:
                cache.put_checkpoint(fp, sw.checkpoint())
        return sw

    @staticmethod
    def _merge_delta_parts(parts):
        """Concatenate sub-unit delta payloads of ONE dispatch group:
        part 0 may carry the base; per-hop delta lists append in hop
        order (each sub-unit's hop 0 is the catch-up delta from the
        previous unit's boundary — exactly the serial fold's windows)."""
        base = parts[0][0]
        deltas_e, deltas_v = [], []
        for p in parts:
            deltas_e.extend(p[1])
            deltas_v.extend(p[2])
        return (base, deltas_e, deltas_v)

    def _alloc_columns(self, H: int):
        t = self.tables
        return (np.full((H, t.m_pad), t.tmin, t.tdtype),
                np.zeros((H, t.m_pad), bool),
                np.full((H, t.n_pad), t.tmin, t.tdtype),
                np.zeros((H, t.n_pad), bool))

    def _fold_columns_fork(self, sw, group, hop_callback, out,
                           off: int) -> None:
        """Column fold of one unit on a FORKED builder, written into
        ``out`` rows [off, off+len): every row is absolute fold state, so
        units fold independently and the assembled arrays are
        bit-identical to the serial ``_fold_columns``."""
        t = self.tables
        e_lat, e_alive, v_lat, v_alive = out
        for j, T in enumerate(group):
            sw._advance(T)
            if hop_callback is not None:
                hop_callback(T, sw)
            r = off + j
            if j == 0:
                pos = t.eng_pos(sw.e_enc)
                e_lat[r, pos] = t.cast_times(sw.e_lat)
                e_alive[r, pos] = sw.e_alive
                nv = len(sw.uv)
                v_lat[r, :nv] = t.cast_times(sw.v_lat)
                v_alive[r, :nv] = sw.v_alive
                continue
            e_lat[r] = e_lat[r - 1]
            e_alive[r] = e_alive[r - 1]
            v_lat[r] = v_lat[r - 1]
            v_alive[r] = v_alive[r - 1]
            d = sw.last_delta
            if len(d["e_enc"]):
                dpos = t.eng_pos(d["e_enc"])
                e_lat[r, dpos] = t.cast_times(d["e_lat"])
                e_alive[r, dpos] = d["e_alive"]
            if len(d["v_idx"]):
                v_lat[r, d["v_idx"]] = t.cast_times(d["v_lat"])
                v_alive[r, d["v_idx"]] = d["v_alive"]

    def _fold_deltas_fork(self, sw, group, ship_base: bool, hop_callback):
        """Delta fold of one unit on a FORKED builder — the parallel twin
        of ``_fold_deltas``: no engine state is touched, so any number of
        units fold concurrently. ``ship_base`` makes hop 0 a full base
        snapshot (the first unit of a non-resident sweep); otherwise
        every hop ships as a delta, hop 0 being the catch-up from the
        previous unit's boundary — the same windows the serial fold
        produces, so the assembled payload is bit-identical."""
        tdt = self.tables.tdtype
        deltas_e, deltas_v = [], []
        base = None
        empty = (np.empty(0, np.int32), np.empty(0, tdt),
                 np.empty(0, bool))
        for j, T in enumerate(group):
            sw._advance(T)
            if hop_callback is not None:
                hop_callback(T, sw)
            if j == 0 and ship_base:
                base = self._materialise_base(sw)
                deltas_e.append(empty)
                deltas_v.append(empty)
            else:
                de, dv = self._delta_eng(sw.last_delta)
                deltas_e.append(de)
                deltas_v.append(dv)
        return (base, deltas_e, deltas_v)

    def _materialise_base(self, sw):
        """Full engine-coordinate base arrays from a builder's fold state
        (the delta path's hop-0 snapshot)."""
        t = self.tables
        tdt = t.tdtype
        be_lat = np.full(t.m_pad, t.tmin, tdt)
        be_alive = np.zeros(t.m_pad, bool)
        pos = t.eng_pos(sw.e_enc)
        be_lat[pos] = t.cast_times(sw.e_lat)
        be_alive[pos] = sw.e_alive
        bv_lat = np.full(t.n_pad, t.tmin, tdt)
        bv_alive = np.zeros(t.n_pad, bool)
        nv = len(sw.uv)
        bv_lat[:nv] = t.cast_times(sw.v_lat)
        bv_alive[:nv] = sw.v_alive
        return (be_lat, be_alive, bv_lat, bv_alive)

    def _fold_columns(self, hop_times, hop_callback=None):
        f0 = _time.perf_counter()
        # this path advances the shared SweepBuilder WITHOUT updating the
        # running delta base — a later delta-fold call must rebuild it or
        # it would scatter one hop's delta onto a stale base
        self._delta_base = None
        self._drop_residency()
        t = self.tables
        hop_times = [int(x) for x in hop_times]
        if sorted(hop_times) != hop_times:
            raise ValueError("hop_times must ascend")
        if self.sw.t_prev is not None and hop_times[0] < self.sw.t_prev:
            # the incremental fold only moves forward; a backward batch on
            # the advanced clock would silently fold nothing (DeviceSweep
            # raises for the same reason)
            raise ValueError(
                f"hop_times must continue forward from the previous batch "
                f"(got {hop_times[0]} < {self.sw.t_prev}); build a fresh "
                f"{type(self).__name__} to go back in history")
        H = len(hop_times)

        # host fold -> hop-major state columns [H, m_pad]/[H, n_pad]: hop 0
        # writes the full fold state, every later hop memcpys the previous
        # row (contiguous in this layout) and scatters only the hop's exact
        # touched-entity delta (``sweep.last_delta``) — one O(m) scatter,
        # then an O(m) contiguous memcpy plus an O(delta) scatter per hop,
        # instead of an O(m) scattered write per hop
        tdt = t.tdtype
        e_lat = np.full((H, t.m_pad), t.tmin, tdt)
        e_alive = np.zeros((H, t.m_pad), bool)
        v_lat = np.full((H, t.n_pad), t.tmin, tdt)
        v_alive = np.zeros((H, t.n_pad), bool)

        for j, T in enumerate(hop_times):
            self.sw._advance(T)
            if hop_callback is not None:
                # post-advance fold state, e.g. for per-hop reducer shells
                hop_callback(T, self.sw)
            if j == 0:
                pos = t.eng_pos(self.sw.e_enc)
                e_lat[0, pos] = t.cast_times(self.sw.e_lat)
                e_alive[0, pos] = self.sw.e_alive
                nv = len(self.sw.uv)
                v_lat[0, :nv] = t.cast_times(self.sw.v_lat)
                v_alive[0, :nv] = self.sw.v_alive
                continue
            e_lat[j] = e_lat[j - 1]
            e_alive[j] = e_alive[j - 1]
            v_lat[j] = v_lat[j - 1]
            v_alive[j] = v_alive[j - 1]
            d = self.sw.last_delta
            if len(d["e_enc"]):
                dpos = t.eng_pos(d["e_enc"])
                e_lat[j, dpos] = t.cast_times(d["e_lat"])
                e_alive[j, dpos] = d["e_alive"]
            if len(d["v_idx"]):
                v_lat[j, d["v_idx"]] = t.cast_times(d["v_lat"])
                v_alive[j, d["v_idx"]] = d["v_alive"]
        self.fold_seconds += _time.perf_counter() - f0
        self.ship_bytes += (e_lat.nbytes + e_alive.nbytes
                            + v_lat.nbytes + v_alive.nbytes)
        return hop_times, (e_lat, e_alive, v_lat, v_alive)

    def _delta_eng(self, d):
        """``sweep.last_delta`` → engine-coordinate (pos, lat, alive)
        triples — shared by the running-base scatter and the forked
        parallel fold."""
        t = self.tables
        de = (t.eng_pos(d["e_enc"]).astype(np.int32),
              t.cast_times(d["e_lat"]), d["e_alive"].astype(bool))
        dv = (d["v_idx"].astype(np.int32), t.cast_times(d["v_lat"]),
              d["v_alive"].astype(bool))
        return de, dv

    def _apply_delta_to_base(self):
        """Scatter the sweep's last delta into the RUNNING host base
        (O(delta)); returns the delta in engine coordinates."""
        de, dv = self._delta_eng(self.sw.last_delta)
        be_lat, be_alive, bv_lat, bv_alive = self._delta_base
        be_lat[de[0]] = de[1]
        be_alive[de[0]] = de[2]
        bv_lat[dv[0]] = dv[1]
        bv_alive[dv[0]] = dv[2]
        return de, dv

    def _fold_deltas(self, hop_times, hop_callback=None,
                     assume_resident: bool = False):
        """Delta-fold: the state at each batch's first hop (the base) plus
        per-hop touched-entity (pos, lat, alive) lists — the device
        rebuilds the hop columns (``_masks_from_deltas``). Host work and
        H2D bytes are O(base + Σ delta) instead of O(H · m_pad): the cost
        that made the host fold the binding term of the headline sweep.
        The base is a RUNNING array updated by O(delta) scatters, so
        chunked (pipelined) sweeps pay the full-table materialisation
        once, not per chunk. ``assume_resident=True`` is the lookahead
        prefetcher's promise that the PREVIOUS group's delta dispatch will
        have left a device-resident advanced base by the time this
        payload dispatches (the fold runs before that dispatch is issued;
        a dispatch failure aborts the sweep before the payload is used)."""
        f0 = _time.perf_counter()
        t = self.tables
        hop_times = [int(x) for x in hop_times]
        if sorted(hop_times) != hop_times:
            raise ValueError("hop_times must ascend")
        if self.sw.t_prev is not None and hop_times[0] < self.sw.t_prev:
            raise ValueError(
                f"hop_times must continue forward from the previous batch "
                f"(got {hop_times[0]} < {self.sw.t_prev}); build a fresh "
                f"{type(self).__name__} to go back in history")
        tdt = t.tdtype
        deltas_e, deltas_v = [], []
        ship_base = None
        # a live device-resident base makes this batch all-delta: hop 0's
        # catch-up ships in the delta[0] slot instead of a base snapshot
        resident = assume_resident or self._dev_base is not None
        if resident and self._delta_base is None \
                and self.sw.t_prev is not None:
            # a parallel fold adopted a forked builder and dropped the
            # running base — rebuild it at the adopted clock (the same
            # time the device-resident state sits at) so the resident
            # all-delta contract survives across batch styles
            self._delta_base = list(self._materialise_base(self.sw))
        resident = resident and self._delta_base is not None
        empty = (np.empty(0, np.int32), np.empty(0, tdt),
                 np.empty(0, bool))
        for j, T in enumerate(hop_times):
            self.sw._advance(T)
            if hop_callback is not None:
                hop_callback(T, self.sw)
            if self._delta_base is None:
                # first batch, first hop: materialise from the full fold
                self._delta_base = list(self._materialise_base(self.sw))
            else:
                de, dv = self._apply_delta_to_base()
                if j > 0 or resident:
                    deltas_e.append(de)
                    deltas_v.append(dv)
            if j == 0 and not resident:
                # snapshot the running base as this batch's upload (the
                # arrays keep mutating through later hops; jnp.asarray is
                # async, so the copy must be taken now)
                ship_base = tuple(a.copy() for a in self._delta_base)
                deltas_e.append(empty)
                deltas_v.append(empty)
        self.fold_seconds += _time.perf_counter() - f0
        return hop_times, (ship_base, deltas_e, deltas_v)


class HopBatchedPageRank(_HopBatched):
    """Windowed PageRank over a full hop sweep in one device call.

    ``run(hop_times, windows)`` returns ``(ranks, steps)`` with ranks
    ``[H*W, n_pad]`` ordered hop-major (hop 0's windows first), rows in the
    global dense vertex space (``self.tables.uv``).
    """

    supports_warm_start = True   # power iteration is a contraction
    supports_delta_fold = True

    def __init__(self, log: EventLog, damping: float = 0.85,
                 tol: float = 1e-7, max_steps: int = 20):
        super().__init__(log)
        self.damping, self.tol, self.max_steps = damping, tol, max_steps

    def _dispatch_cols(self, cols, hop_times, windows, r_init=None):
        return run_columns(
            self.tables, *cols, hop_times, windows,
            damping=self.damping, tol=self.tol, max_steps=self.max_steps,
            e_src_dev=self._e_src, e_dst_dev=self._e_dst, r_init=r_init,
            layout=self._active_layout)

    def _dispatch_deltas(self, payload, hop_times, windows, r_init=None):
        base, deltas_e, deltas_v = payload
        base, h0 = self._delta_base_args(base)
        return self._run_delta(lambda: run_columns_delta(
            "pagerank", self.tables, base, deltas_e, deltas_v,
            hop_times, windows,
            algo_args=(float(self.damping), float(self.tol),
                       int(self.max_steps)),
            e_src_dev=self._e_src, e_dst_dev=self._e_dst, r_init=r_init,
            h0_delta=h0, ship_counter=self._count_ship,
            layout=self._active_layout))


class HopBatchedBFS(_HopBatched):
    """Windowed BFS hop counting over a full sweep in one call; distances
    are f32 with inf for unreached (SSSP-with-unit-weights semantics)."""

    supports_delta_fold = True
    supports_epoch_warm = True   # min-merge seed (gate: _bfs_columns)

    def __init__(self, log: EventLog, seeds, directed: bool = False,
                 max_steps: int = 100):
        super().__init__(log)
        self._seeds = tuple(seeds)
        self.directed = directed
        self.max_steps = max_steps
        # seeds are fixed per engine: upload the dense seed mask once so
        # chunked/resident sweeps don't re-ship an n_pad bool per dispatch
        self._seed_dev = None

    @property
    def seeds(self):
        """Seed vertex ids — fixed at construction (the device seed mask
        is cached; build a new engine for different seeds)."""
        return self._seeds

    @property
    def _seed(self):
        if self._seed_dev is None:
            self._seed_dev = jnp.asarray(_seed_mask(self.tables,
                                                    self.seeds))
        return self._seed_dev

    def _dispatch_cols(self, cols, hop_times, windows, r_init=None):
        assert r_init is None   # guarded by supports_warm_start
        return run_bfs_columns(
            self.tables, *cols, hop_times, windows, self.seeds,
            directed=self.directed, max_steps=self.max_steps,
            e_src_dev=self._e_src, e_dst_dev=self._e_dst,
            layout=self._active_layout)

    def _dispatch_deltas(self, payload, hop_times, windows, r_init=None):
        # r_init is the cross-epoch warm seed (min-merged distances);
        # validity is gated by the caller (_bfs_columns docstring)
        base, deltas_e, deltas_v = payload
        base, h0 = self._delta_base_args(base)
        return self._run_delta(lambda: run_columns_delta(
            "bfs", self.tables, base, deltas_e, deltas_v,
            hop_times, windows,
            algo_args=(int(self.max_steps), bool(self.directed)),
            seed_mask=self._seed, r_init=r_init,
            e_src_dev=self._e_src, e_dst_dev=self._e_dst, h0_delta=h0,
            ship_counter=self._count_ship, layout=self._active_layout))


class HopBatchedSSSP(HopBatchedBFS):
    """Weighted min-plus traversal over a full sweep in one call.

    Per-pair weights are the LATEST numeric value of ``weight_prop`` at
    each hop (``_materialise_prop`` semantics incl. the (time, event-row)
    tie-break, ``snapshot.py``), folded incrementally into hop-major
    ``[H, m_pad]`` columns next to the alive/lat columns; pairs that never
    set the key weigh 1.0 (``SSSP.message``'s NaN rule). Immutable keys
    (earliest-wins) are refused — the ascending fold is last-wins."""

    supports_delta_fold = True   # weights rebuild on device too
    #: a weight update can RAISE a pair's weight — old distances become
    #: stale under-estimates, so SSSP never takes a cross-epoch seed
    supports_epoch_warm = False

    #: the weight fold advances a SEQUENTIAL cursor over the sorted
    #: update stream — chunk folds cannot fork it independently yet
    supports_parallel_fold = False

    def _fold_token(self):
        # weighted payloads carry per-pair weight state — never share a
        # cache entry with the weightless engines (or other weight keys)
        return ("sssp", self.weight_prop, bool(self.directed))

    def host_column_bytes(self, n_hops: int) -> int:
        extra = self.tables.m_pad * 4   # weight base (delta path)
        if not self._use_delta_fold():
            extra = n_hops * self.tables.m_pad * 4   # [H, m_pad] f32 cols
        return super().host_column_bytes(n_hops) + extra

    def device_mask_bytes(self, n_cols: int) -> int:
        # the kernel holds a persistent [m_pad, C] f32 ew next to the
        # bool masks — 4 extra bytes per (pair, column)
        return (super().device_mask_bytes(n_cols)
                + self.tables.m_pad * n_cols * 4)

    def __init__(self, log: EventLog, seeds, weight_prop: str,
                 directed: bool = False, max_steps: int = 100):
        super().__init__(log, seeds, directed=directed, max_steps=max_steps)
        log = self.sw.log
        if weight_prop in log.props._key_ids \
                and log.props.is_immutable(log.props._key_ids[weight_prop]):
            raise ValueError(
                f"{weight_prop!r} is an immutable (earliest-wins) key — "
                "the incremental weight fold is last-wins; use the "
                "per-view path")
        self.weight_prop = weight_prop
        t = self.tables
        # all numeric rows of the key on EDGE_ADD events, sorted by
        # (time, event-row) — the same order _materialise_prop's lexsort
        # picks "latest" from — plus a running per-pair state row
        self._w_state = np.ones(t.m_pad, np.float32)
        if weight_prop in log.props._key_ids:
            kid = log.props._key_ids[weight_prop]
            pe = log.props.column("event")
            sel = ((log.props.column("key") == kid)
                   & (log.props.column("tag") == log.props.NUM_TAG))
            ev = pe[sel]
            kinds = log.column("kind")[ev]
            from ..core.events import EDGE_ADD
            ev = ev[kinds == EDGE_ADD]
            val = log.props.column("num")[sel][kinds == EDGE_ADD]
            # stored NaNs weigh 1.0 exactly like missing values
            # (``SSSP.message``'s rule) — raw NaN would poison the whole
            # column through the min-plus relaxation
            val = np.where(np.isnan(val), 1.0, val)
            tt = log.column("time")[ev]
            order = np.lexsort((ev, tt))
            self._w_t = tt[order]
            self._w_val = val[order].astype(np.float32)
            enc = self.sw._pack(self.sw._dense(log.column("src")[ev]),
                                self.sw._dense(log.column("dst")[ev]))
            self._w_pos = t.eng_pos(enc)[order]
        else:
            self._w_t = np.empty(0, np.int64)
            self._w_val = np.empty(0, np.float32)
            self._w_pos = np.empty(0, np.int64)
        self._w_cursor = 0

    def repin(self) -> str:
        n_old = len(self.sw._t)
        status = super().repin()
        if status != "extended":
            return status
        # extend the sorted weight-update stream with the suffix's
        # props. The consumed prefix [:_w_cursor] is immutable history
        # (times ≤ t_prev); the unconsumed tail re-sorts against the new
        # updates, whose times interleave past the cursor (both are >
        # t_prev — SweepBuilder.repin's watermark guard). A STABLE sort
        # by time alone reproduces the (time, event-row) lexsort order:
        # each block is already in it, and every suffix event row is
        # greater than every pinned one.
        log = self.sw.log
        if self.weight_prop not in log.props._key_ids:
            return "extended"
        kid = log.props._key_ids[self.weight_prop]
        if log.props.is_immutable(kid):
            return "rebuild"   # key turned earliest-wins: __init__ refuses
        pe = log.props.column("event")
        sel = ((pe >= n_old) & (log.props.column("key") == kid)
               & (log.props.column("tag") == log.props.NUM_TAG))
        ev = pe[sel]
        if not len(ev):
            return "extended"
        from ..core.events import EDGE_ADD

        kinds = log.column("kind")[ev]
        val = log.props.column("num")[sel][kinds == EDGE_ADD]
        ev = ev[kinds == EDGE_ADD]
        if not len(ev):
            return "extended"
        val = np.where(np.isnan(val), 1.0, val).astype(np.float32)
        tt = log.column("time")[ev]
        order = np.lexsort((ev, tt))
        enc = self.sw._pack(self.sw._dense(log.column("src")[ev]),
                            self.sw._dense(log.column("dst")[ev]))
        pos = self.tables.eng_pos(enc)
        cur = self._w_cursor
        t_cat = np.concatenate([self._w_t[cur:], tt[order]])
        v_cat = np.concatenate([self._w_val[cur:], val[order]])
        p_cat = np.concatenate([self._w_pos[cur:], pos[order]])
        tail = np.argsort(t_cat, kind="stable")
        self._w_t = np.concatenate([self._w_t[:cur], t_cat[tail]])
        self._w_val = np.concatenate([self._w_val[:cur], v_cat[tail]])
        self._w_pos = np.concatenate([self._w_pos[:cur], p_cat[tail]])
        return "extended"

    def _weight_cols(self, hop_times):
        t = self.tables
        H = len(hop_times)
        W = np.empty((H, t.m_pad), np.float32)
        for j, T in enumerate(hop_times):
            hi = int(np.searchsorted(self._w_t, T, side="right"))
            if hi > self._w_cursor:
                # ascending (time, row) order: last write = latest value
                self._w_state[self._w_pos[self._w_cursor:hi]] = \
                    self._w_val[self._w_cursor:hi]
                self._w_cursor = hi
            W[j] = self._w_state
        return W

    def _fold_columns(self, hop_times, hop_callback=None):
        hop_times, cols = super()._fold_columns(hop_times, hop_callback)
        wcols = self._weight_cols(hop_times)
        self.ship_bytes += wcols.nbytes
        return hop_times, (*cols, wcols)

    def _weight_deltas(self, hop_times, resident: bool = False):
        """Per-hop (pos, val) weight updates + the running state at hop 0
        of this batch — the delta twin of ``_weight_cols``. ``resident``
        mirrors the mask fold's decision: hop 0's catch-up ships as
        delta[0] against the device-held weight state, w_base is None."""
        wd = []
        w_base = None
        for j, T in enumerate(hop_times):
            hi = int(np.searchsorted(self._w_t, T, side="right"))
            pos = self._w_pos[self._w_cursor:hi].astype(np.int32)
            val = self._w_val[self._w_cursor:hi]
            if (j > 0 or resident) and len(pos):
                # last-wins per pair WITHIN the hop: XLA scatter order is
                # undefined for duplicate indices, so the dedup must happen
                # here (the host fold's sequential assignment is last-wins
                # by construction). Hop 0's slice — the bulk of a cold
                # sweep — folds into the base instead, no dedup needed.
                u_last = np.unique(pos[::-1], return_index=True)[1]
                sel = np.sort(len(pos) - 1 - u_last)
                pos, val = pos[sel], val[sel]
            if hi > self._w_cursor:
                self._w_state[self._w_pos[self._w_cursor:hi]] = \
                    self._w_val[self._w_cursor:hi]
                self._w_cursor = hi
            if j == 0 and not resident:
                # updates at/before hop 0 belong to the base
                w_base = self._w_state.copy()
                wd.append((pos[:0], val[:0]))
            else:
                wd.append((pos, val))
        return w_base, wd

    def _fold_deltas(self, hop_times, hop_callback=None,
                     assume_resident: bool = False):
        hop_times, payload = super()._fold_deltas(hop_times, hop_callback,
                                                  assume_resident)
        # payload[0] is None exactly when the mask fold went all-delta
        # against the device-resident base — the weight fold must match
        return hop_times, (*payload,
                           *self._weight_deltas(hop_times,
                                                resident=payload[0] is None))

    def _dispatch_cols(self, cols, hop_times, windows, r_init=None):
        assert r_init is None   # guarded by supports_warm_start
        *base, wcols = cols
        return run_bfs_columns(
            self.tables, *base, hop_times, windows, self.seeds,
            directed=self.directed, max_steps=self.max_steps,
            e_src_dev=self._e_src, e_dst_dev=self._e_dst,
            weight_cols=wcols, layout=self._active_layout)

    def _dispatch_deltas(self, payload, hop_times, windows, r_init=None):
        # never warm-started: a weight update can RAISE a pair's weight,
        # making old distances stale under-estimates (_bfs_columns
        # docstring) — the live engine always iterates SSSP cold
        assert r_init is None
        base, deltas_e, deltas_v, w_base, w_deltas = payload
        base, h0 = self._delta_base_args(base)
        if h0:
            w_base = self._dev_base[4]   # device-resident weight state
        return self._run_delta(lambda: run_columns_delta(
            "bfs", self.tables, base, deltas_e, deltas_v, hop_times,
            windows, algo_args=(int(self.max_steps), bool(self.directed)),
            seed_mask=self._seed,
            e_src_dev=self._e_src, e_dst_dev=self._e_dst,
            weight_base=w_base, weight_deltas=w_deltas, h0_delta=h0,
            ship_counter=self._count_ship, layout=self._active_layout))


class HopBatchedCC(_HopBatched):
    """Windowed connected components over a full hop sweep in one call;
    labels decode via ``tables.uv[label]`` (min vid of the component)."""

    supports_delta_fold = True
    supports_epoch_warm = True   # min-merge seed (gate: _cc_columns)

    def __init__(self, log: EventLog, max_steps: int = 100):
        super().__init__(log)
        self.max_steps = max_steps

    def _dispatch_deltas(self, payload, hop_times, windows, r_init=None):
        # r_init is the cross-epoch warm seed (min-merged labels);
        # validity is gated by the caller (_cc_columns docstring)
        base, deltas_e, deltas_v = payload
        base, h0 = self._delta_base_args(base)
        return self._run_delta(lambda: run_columns_delta(
            "cc", self.tables, base, deltas_e, deltas_v,
            hop_times, windows, algo_args=(int(self.max_steps),),
            r_init=r_init,
            e_src_dev=self._e_src, e_dst_dev=self._e_dst, h0_delta=h0,
            ship_counter=self._count_ship, layout=self._active_layout))

    def _dispatch_cols(self, cols, hop_times, windows, r_init=None):
        assert r_init is None   # guarded by supports_warm_start
        return run_cc_columns(
            self.tables, *cols, hop_times, windows,
            max_steps=self.max_steps,
            e_src_dev=self._e_src, e_dst_dev=self._e_dst,
            layout=self._active_layout)


def _dispatch_columns(runner, tables, cols, hop_of_col, T_col,
                      w_col, e_src_dev, e_dst_dev, *extra):
    """Shared device dispatch for the columnar runners (`extra` appends
    runner-specific trailing args, e.g. the BFS seed mask). The payload —
    on the host-column path the [H, m_pad] fold columns, the largest
    per-dispatch ship in the system — goes through the pipelined transfer
    engine: array k+1 stages while k is on the wire, per-slice retry."""
    from ..utils.transfer import shared_engine

    with TRACER.span("hop.compute", cols=int(len(T_col))):
        return runner(*shared_engine().put_many([
            e_src_dev if e_src_dev is not None else tables.e_src,
            e_dst_dev if e_dst_dev is not None else tables.e_dst,
            *cols, hop_of_col, T_col, w_col, *extra]))


@functools.lru_cache(maxsize=16)
def _compiled_scale(n_pad: int, m_pad: int, H: int, W: int, U_e: int,
                    U_v: int, damping: float, tol: float, max_steps: int,
                    scan_masks: bool = False,
                    tile_budget: int | None = None, pcpm=None):
    """Scale variant of the columnar PageRank: per-hop fold state is
    REBUILT ON DEVICE from the base state plus per-hop update lists, so a
    sweep ships O(base + deltas) bytes instead of O(m_pad * H) — at
    10^8-edge scale the ``[H, m_pad]`` columns cannot cross the host link.

    Add-only streams only (``core/bulk.py`` contract): alive == ever-seen,
    so every mask is ONE threshold compare ``lat >= thr`` with
    ``thr = max(T - w, 0)`` (windowed) or 0 (unwindowed), and hop state is
    a running scatter-max of update times. Update lists are (pos, t) pairs
    padded with (0, INT32_MIN) — a max no-op.

    ``scan_masks=True`` builds the hop rebuild as a ``lax.scan`` over hops
    instead of an H-way unrolled block — an HLO ~H times smaller, kept as
    the fallback shape for remote compilers that choke on the unrolled
    program (RTPU_SCALE_MASKS=scan); results are identical (tested)."""

    def run(e_src, e_dst, base_e, base_v, de_pos, de_t, dv_pos, dv_t, thr,
            *rest):
        thr_hw = thr.reshape(H, W)
        # binned variant: hop state still advances in ENGINE order (the
        # update lists target engine positions) — only the mask COLUMNS
        # are emitted through the layout permutation, one cheap 1-D
        # gather of the running scatter-max per hop
        pc = perm = valid = None
        if pcpm is not None:
            perm, valid, slot, u_src = rest
            pc = (pcpm, slot, u_src)

        def hop_masks(base, d_pos, d_t, bin_rows: bool):
            def col_of(cur, th):
                if bin_rows and perm is not None:
                    return (cur[perm][:, None] >= th[None, :]) \
                        & valid[:, None]
                return cur[:, None] >= th[None, :]

            if scan_masks:
                def step(cur, inp):
                    pos, tt, th = inp
                    cur = cur.at[pos].max(tt)
                    return cur, col_of(cur, th)               # [len, W]

                _, cols = jax.lax.scan(step, base, (d_pos, d_t, thr_hw))
                # [H, len, W] -> [len, H*W] hop-major
                return jnp.swapaxes(cols, 0, 1).reshape(
                    cols.shape[1], H * W)
            cur, cols = base, []
            for h in range(H):     # H static and small: unrolled
                cur = cur.at[d_pos[h]].max(d_t[h])
                cols.append(col_of(cur, thr[h * W:(h + 1) * W]))
            return jnp.concatenate(cols, axis=1)   # [len, H*W] hop-major
        me = hop_masks(base_e, de_pos, de_t, True)
        mv = hop_masks(base_v, dv_pos, dv_t, False)
        return _pagerank_columns(me, mv, e_src, e_dst, n_pad,
                                 damping, tol, max_steps,
                                 tile_budget=tile_budget, pcpm=pc)

    return _ledger.instrument("hopbatch.pagerank_scale", jax.jit(run),
                              traffic=_traffic(m_pad, H * W, n_pad, pcpm))


def _delta_fingerprint(deltas_e, deltas_v) -> tuple:
    """Cheap identity of the delta lists a scale payload was built from:
    per-hop lengths plus an xor checksum over BOTH the pos and time
    arrays (same positions with different update times are different
    deltas). O(Σ delta) memory-bandwidth work — a payload built from
    DIFFERENT deltas must fail loudly in ``run_scale_columns`` instead of
    returning mislabelled results."""
    def xor(a):
        a = np.asarray(a)
        if not len(a):
            return 0
        return int(np.bitwise_xor.reduce(a.astype(np.int64, copy=False)))

    def fp(deltas):
        return tuple((int(len(p)), xor(p) ^ (xor(t) << 1))
                     for p, t in deltas)

    return fp(deltas_e), fp(deltas_v)


def prepare_scale_payload(deltas_e, deltas_v, hop_times, windows):
    """Pad the per-hop update lists and compute the column thresholds ONCE
    for repeated ``run_scale_columns`` calls over the same sweep: the
    padded delta arrays are the largest per-call ship (256 MB at 134M
    events) and re-padding + re-uploading them per timed sweep would put
    host→device transfer inside the measured loop. Returns
    ``(U_e, U_v, de_pos, de_t, dv_pos, dv_t, thr)`` with the big arrays
    moved via the chunked resilient path."""
    from ..utils.transfer import device_put_chunked

    H = len(hop_times)
    wlist = normalize_windows(windows)
    W = len(wlist)
    thr = np.zeros(H * W, np.int32)
    for j, T in enumerate(int(x) for x in hop_times):
        for i, w in enumerate(wlist):
            thr[j * W + i] = 0 if w < 0 else max(int(T) - int(w), 0)

    def pad_for(deltas):
        longest = max((len(p) for p, _ in deltas), default=1)
        return max(1024, 1 << int(np.ceil(np.log2(max(longest, 1)))))

    def pad_deltas(deltas, U):
        pos = np.zeros((H, U), np.int32)
        t = np.full((H, U), np.iinfo(np.int32).min, np.int32)
        for h, (p, tt) in enumerate(deltas):
            if len(p) > U:
                raise ValueError(f"delta {h} exceeds pad {U}")
            pos[h, : len(p)] = p
            t[h, : len(p)] = tt
        return pos, t

    U_e, U_v = pad_for(deltas_e), pad_for(deltas_v)
    de_pos, de_t = pad_deltas(deltas_e, U_e)
    dv_pos, dv_t = pad_deltas(deltas_v, U_v)
    # fingerprint: (hop_times, windows) grid AND the delta lists (per-hop
    # lengths + pos checksums) — a payload prepared for one sweep must not
    # silently relabel another same-shape sweep's results
    fp = (tuple(int(x) for x in hop_times), tuple(wlist),
          _delta_fingerprint(deltas_e, deltas_v))
    return (U_e, U_v, device_put_chunked(de_pos), device_put_chunked(de_t),
            device_put_chunked(dv_pos), device_put_chunked(dv_t),
            jnp.asarray(thr), fp)


def run_scale_columns(bulk, base_e, base_v, deltas_e, deltas_v, hop_times,
                      windows, *, damping: float = 0.85, tol: float = 0.0,
                      max_steps: int = 20, e_src_dev=None, e_dst_dev=None,
                      prepared=None):
    """Columnar PageRank over ``core.bulk.bulk_hop_deltas`` output: uploads
    the base fold rows and per-hop update lists, rebuilds hop state on
    device, runs every (hop, window) view as one column. Returns
    ``(ranks [H*W, n_pad] hop-major, steps)``; unwindowed views use a
    negative window (same convention as ``run_columns``). ``prepared``
    (from ``prepare_scale_payload``) supplies pre-uploaded delta pads so
    repeated sweeps ship nothing."""
    H = len(hop_times)
    wlist = normalize_windows(windows)
    W = len(wlist)
    if prepared is None:
        prepared = prepare_scale_payload(deltas_e, deltas_v, hop_times,
                                         windows)
        U_e, U_v, de_pos, de_t, dv_pos, dv_t, thr, fp = prepared
    else:
        # caller-supplied payload: verify it was built from THESE deltas
        # and THIS grid (the fresh-built branch above trivially was —
        # don't re-walk O(Σ delta) bytes to prove it)
        U_e, U_v, de_pos, de_t, dv_pos, dv_t, thr, fp = prepared
        want = (tuple(int(x) for x in hop_times), tuple(wlist),
                _delta_fingerprint(deltas_e, deltas_v))
        if fp[:2] != want[:2]:
            raise ValueError(
                "prepared payload was built for a different sweep grid "
                f"(prepared {fp[0][:2]}.../{fp[1]}, called with "
                f"{want[0][:2]}.../{want[1]}) — prepare_scale_payload must "
                "see the SAME hop_times/windows (and the same deltas)")
        if len(fp) > 2 and fp[2] != want[2]:
            raise ValueError(
                "prepared payload was built from DIFFERENT delta lists "
                "(per-hop length/checksum mismatch) — results would be "
                "mislabelled; re-run prepare_scale_payload on these deltas")
    import os

    from ..ops import partition as _partition

    scan_masks = os.environ.get("RTPU_SCALE_MASKS", "unroll") == "scan"
    budget = _tile_budget_bytes()
    # RTPU_PCPM / RTPU_PARTITIONS resolved here, at dispatch — the spec
    # carries both knobs into the compiled-program cache key
    layout = _partition.resolve(bulk, bulk, budget)
    extra = ()
    if layout is not None:
        b_src, b_dst, valid, slot, u_src, perm = layout.device_args()
        e_src_dev, e_dst_dev = b_src, b_dst
        extra = (perm, valid, slot, u_src)
    runner = _compiled_scale(bulk.n_pad, bulk.m_pad, H, W, U_e, U_v,
                             float(damping), float(tol), int(max_steps),
                             scan_masks, budget,
                             None if layout is None else layout.spec)
    return runner(
        e_src_dev if e_src_dev is not None else jnp.asarray(bulk.e_src),
        e_dst_dev if e_dst_dev is not None else jnp.asarray(bulk.e_dst),
        jnp.asarray(base_e), jnp.asarray(base_v),
        de_pos, de_t, dv_pos, dv_t, thr, *extra)


def _column_layout(hop_times, windows):
    """Hop-major (hop 0's windows first) column layout shared by every
    columnar runner — the ONE place the ordering is defined."""
    H = len(hop_times)
    wlist = normalize_windows(windows)
    hop_of_col = np.repeat(np.arange(H, dtype=np.int32), len(wlist))
    T_col = np.asarray([int(x) for x in hop_times], np.int64)[hop_of_col]
    w_col = np.asarray(wlist * H, np.int64)
    return H, H * len(wlist), hop_of_col, T_col, w_col


def stack_grids(grids):
    """Multi-REQUEST column stacking: merge per-request ``(hop_times,
    windows)`` grids into ONE dispatch grid — the serving scheduler's
    entry point into the columnar engines (jobs/scheduler.py).

    Concurrent requests over the same log and algorithm family differ
    only in WHICH (hop, window) views they want; each view is one column
    of a columnar dispatch, so the batch grid is simply the cross
    product of the hop union and the window union — a superset of every
    member's own grid (extra cells are the coalescing overhead the
    scheduler's column cap bounds). Returns ``(hops, wlist, cols)``:

    * ``hops`` — ascending union of all hop times (ints, deduplicated);
    * ``wlist`` — union of the normalized windows (``None`` → -1, the
      engine convention), first-seen order, deduplicated;
    * ``cols`` — per request, the flat column indices of ITS cells in
      the batch result (hop-major ``_column_layout`` order), listed hops
      ascending × that request's own window order — exactly the order a
      serial per-request dispatch would have emitted them in, so the
      demux is an index gather, never a re-sort.
    """
    hops = sorted({int(t) for ts, _ in grids for t in ts})
    wlist: list[int] = []
    for _, ws in grids:
        for w in normalize_windows(ws):
            if w not in wlist:
                wlist.append(w)
    W = len(wlist)
    hop_idx = {t: j for j, t in enumerate(hops)}
    cols = []
    for ts, ws in grids:
        nws = [wlist.index(w) for w in normalize_windows(ws)]
        cols.append([hop_idx[int(t)] * W + i
                     for t in sorted({int(x) for x in ts}) for i in nws])
    return hops, wlist, cols


def run_columns(tables, e_lat, e_alive, v_lat, v_alive, hop_times, windows,
                *, damping: float = 0.85, tol: float = 1e-7,
                max_steps: int = 20, e_src_dev=None, e_dst_dev=None,
                r_init=None, layout=None):
    """Dispatch the columnar PageRank over prebuilt per-hop fold columns —
    shared by the incremental-fold class above and the add-only bulk loader
    (``core/bulk.bulk_hop_columns``). `tables` needs the GlobalTables /
    BulkGraph surface (n_pad, m_pad, e_src, e_dst, tdtype). ``r_init``
    (the previous chunk's full ``[C, n_pad]`` hop-major output, device)
    warm-starts the power iteration: the kernel slices its last hop's W
    rows and tiles them per hop IN-PROGRAM — see ``_compiled``."""
    H, C, hop_of_col, T_col, w_col = _column_layout(hop_times, windows)
    runner = _compiled(tables.n_pad, tables.m_pad, H, C, float(damping),
                       float(tol), int(max_steps),
                       np.dtype(tables.tdtype).name, r_init is not None,
                       _tile_budget_bytes(),
                       None if layout is None else layout.spec)
    extra = () if r_init is None else (r_init,)
    if layout is not None:
        e_src_dev, e_dst_dev, pc = _layout_dispatch_args(layout)
        extra = extra + pc
    return _dispatch_columns(runner, tables,
                             (e_lat, e_alive, v_lat, v_alive),
                             hop_of_col, T_col, w_col, e_src_dev, e_dst_dev,
                             *extra)
