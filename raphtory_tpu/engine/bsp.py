"""The BSP superstep engine — one jit-compiled SPMD program per algorithm.

Replaces the reference's actor-driven superstep machinery: the
``AnalysisTask`` coordinator counting ``Ready``/``EndStep`` acks and probing
message quiescence (``AnalysisTask.scala:197-283``), ``ReaderWorker``
executing ``analyse()`` per shard (``ReaderWorker.scala:159-219``), and the
``VertexMutliQueue`` double-buffered mailboxes. In the compiled model the
barrier is implicit (it's one XLA program), quiescence/vote counting is a
reduction, and the message exchange is a gather + segment-combine.

Batched windows (``ReaderWorker.scala:180-187`` running the algorithm once
per window against a shrinking lens) become a leading window axis driven by
``jax.vmap`` — every window advances in the same compiled superstep, and
halted windows freeze via ``jnp.where``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.snapshot import GraphView
from ..ops.segment import combine_tree, segment_combine
from .program import Context, Edges, VertexProgram

_elem = {"sum": jnp.add, "min": jnp.minimum, "max": jnp.maximum}


def _merge_aggs(op: str, a, b):
    return jax.tree_util.tree_map(_elem[op], a, b)


def make_runner(program: VertexProgram, n: int, m: int, k: int):
    """The raw (unjitted) superstep program for given padded shapes — the
    jittable forward step of the framework; see also ``__graft_entry__``."""

    def one_superstep(state, v_mask, e_mask, out_deg, in_deg, ctx, edges):
        agg = None
        if program.direction in ("out", "both"):
            src_state = jax.tree_util.tree_map(lambda a: a[edges.src], state)
            payload = program.message(src_state, edges)
            agg = combine_tree(payload, edges.dst, n, program.combiner,
                               e_mask, indices_are_sorted=True)
        if program.direction in ("in", "both"):
            src_state = jax.tree_util.tree_map(lambda a: a[edges.dst], state)
            payload = program.message(src_state, edges)
            agg_in = combine_tree(payload, edges.src, n, program.combiner,
                                  e_mask, indices_are_sorted=False)
            agg = agg_in if agg is None else _merge_aggs(program.combiner, agg, agg_in)
        new_state, votes = program.update(state, agg, ctx)
        halted = jnp.all(votes | ~v_mask)
        return new_state, halted

    def run(v_masks, e_masks, vids, v_latest, v_first,
            e_src, e_dst, e_latest, e_first,
            time, windows, eprops, vprops):
        # per-window degrees: one segment-sum over the masked edge set
        ones = jnp.ones((m,), jnp.int32)

        def degs(em):
            ind = segment_combine(ones, e_dst, n, "sum", em, True)
            out = segment_combine(ones, e_src, n, "sum", em, False)
            return out, ind

        out_deg, in_deg = jax.vmap(degs)(e_masks)

        def mk_ctx(kk, step):
            return Context(
                n=n, time=time, window=windows[kk], v_mask=v_masks[kk],
                vids=vids, v_latest_time=v_latest, v_first_time=v_first,
                out_deg=out_deg[kk], in_deg=in_deg[kk],
                n_active=jnp.sum(v_masks[kk].astype(jnp.int32)),
                step=step, vprops=vprops,
            )

        def init_k(kk):
            return program.init(mk_ctx(kk, jnp.int32(0)))

        state0 = jax.vmap(init_k)(jnp.arange(k))

        def step_k(kk, st, step):
            ctx = mk_ctx(kk, step)
            ek = Edges(src=e_src, dst=e_dst, mask=e_masks[kk], time=e_latest,
                       first_time=e_first, props=eprops, step=step)
            return one_superstep(st, v_masks[kk], e_masks[kk],
                                 out_deg[kk], in_deg[kk], ctx, ek)

        vstep = jax.vmap(step_k, in_axes=(0, 0, None))

        if program.max_steps > 0:
            def cond(carry):
                step, _, halted = carry
                return (step < program.max_steps) & ~jnp.all(halted)

            def body(carry):
                step, st, halted = carry
                new_st, new_halt = vstep(jnp.arange(k), st, step)
                # freeze halted windows
                st = jax.tree_util.tree_map(
                    lambda old, new: jnp.where(
                        halted.reshape((k,) + (1,) * (new.ndim - 1)), old, new),
                    st, new_st)
                return step + 1, st, halted | new_halt

            steps, state, halted = jax.lax.while_loop(
                cond, body, (jnp.int32(0), state0, jnp.zeros((k,), bool)))
        else:
            steps, state = jnp.int32(0), state0

        def fin_k(kk, st):
            return program.finalize(st, mk_ctx(kk, steps))

        result = jax.vmap(fin_k, in_axes=(0, 0))(jnp.arange(k), state)
        return result, steps

    return run


@functools.lru_cache(maxsize=256)
def _compiled_runner(program: VertexProgram, n: int, m: int, k: int,
                     prop_keys: tuple, vprop_keys: tuple):
    """One compiled program per (algorithm instance, padded shapes, #windows).

    Range sweeps at the same bucketed shape hit this cache — the amortisation
    the reference never had (fresh handshake per hop,
    ``RangeAnalysisTask.scala:18-35``).
    """
    return jax.jit(make_runner(program, n, m, k))


def _gather_props(view: GraphView, keys, kind: str):
    out = {}
    for name in keys:
        arr = view.edge_prop(name) if kind == "e" else view.vertex_prop(name)
        out[name] = jnp.asarray(arr, jnp.float32)
    return out


def run_async(
    program: VertexProgram,
    view: GraphView,
    *,
    window: int | None = None,
    windows=None,
):
    """Dispatch a vertex program against a view WITHOUT waiting for the
    device: returns (result, steps) as device arrays. Range sweeps use this
    to pipeline host snapshot builds with device compute — hop i+1's
    snapshot folds while hop i's supersteps run.

    window=None, windows=None → plain view ({View,Range}AnalysisTask).
    window=w                  → single window (Windowed*).
    windows=[w0 > w1 > ...]   → batched windows, one result per window
                                (BWindowed*; leading axis on the result).
    """
    batched = windows is not None
    if windows is not None and len(windows) == 0:
        raise ValueError("windows must be a non-empty list of window sizes")
    if windows is None:
        windows = [window if window is not None else -1]
    wlist = list(windows)
    k = len(wlist)

    # Occurrence-based temporal programs (EthereumTaintTracking-style) run
    # over the multigraph of edge-add events rather than deduped edges —
    # the analogue of iterating raw edge history via
    # ``getOutgoingNeighborsAfter`` (VertexVisitor.scala:33).
    if program.needs_occurrences:
        if view.occ_src is None:
            raise ValueError(
                "program needs occurrences: build the view with "
                "include_occurrences=True")
        e_src, e_dst = view.occ_src, view.occ_dst
        e_latest = e_first = view.occ_time
        e_base_mask = view.occ_mask  # dst-sorted, like the deduped edges
    else:
        e_src, e_dst = view.e_src, view.e_dst
        e_latest, e_first = view.e_latest_time, view.e_first_time
        e_base_mask = view.e_mask
    m_pad = len(e_src)

    v_masks = np.empty((k, view.n_pad), bool)
    e_masks = np.empty((k, m_pad), bool)
    for i, w in enumerate(wlist):
        if w is None or w < 0:
            v_masks[i] = view.v_mask
            e_masks[i] = e_base_mask
        else:
            vm, _ = view.window_masks([w])
            v_masks[i] = vm[0]
            e_masks[i] = e_base_mask & (e_latest >= view.time - w)

    runner = _compiled_runner(
        program, view.n_pad, m_pad, k,
        tuple(program.edge_props), tuple(program.vertex_props),
    )
    if program.needs_occurrences and program.edge_props:
        raise NotImplementedError(
            "edge_props on occurrence programs not yet supported")
    eprops = _gather_props(view, program.edge_props, "e")
    vprops = _gather_props(view, program.vertex_props, "v")
    win_arr = jnp.asarray([(-1 if w is None else int(w)) for w in wlist], jnp.int64)

    result, steps = runner(
        jnp.asarray(v_masks), jnp.asarray(e_masks),
        jnp.asarray(view.vids), jnp.asarray(view.v_latest_time),
        jnp.asarray(view.v_first_time),
        jnp.asarray(e_src), jnp.asarray(e_dst),
        jnp.asarray(e_latest), jnp.asarray(e_first),
        jnp.asarray(view.time, jnp.int64), win_arr, eprops, vprops,
    )
    if not batched:
        result = jax.tree_util.tree_map(lambda a: a[0], result)
    return result, steps


def run(
    program: VertexProgram,
    view: GraphView,
    *,
    window: int | None = None,
    windows=None,
):
    """Blocking ``run_async``: waits for the device and returns
    (result, int steps)."""
    result, steps = run_async(program, view, window=window, windows=windows)
    return result, int(steps)
