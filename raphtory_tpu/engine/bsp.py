"""The BSP superstep engine — one jit-compiled SPMD program per algorithm.

Replaces the reference's actor-driven superstep machinery: the
``AnalysisTask`` coordinator counting ``Ready``/``EndStep`` acks and probing
message quiescence (``AnalysisTask.scala:197-283``), ``ReaderWorker``
executing ``analyse()`` per shard (``ReaderWorker.scala:159-219``), and the
``VertexMutliQueue`` double-buffered mailboxes. In the compiled model the
barrier is implicit (it's one XLA program), quiescence/vote counting is a
reduction, and the message exchange is a gather + segment-combine.

Batched windows (``ReaderWorker.scala:180-187`` running the algorithm once
per window against a shrinking lens) become a leading window axis driven by
``jax.vmap`` — every window advances in the same compiled superstep, and
halted windows freeze via ``jnp.where``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.snapshot import GraphView
from ..obs import ledger as _ledger
from ..obs.trace import TRACER, block_steps
from ..ops.segment import (partition_segment_reduce, segment_combine,
                           segment_sum_sorted_csr)
from .program import Context, Edges, VertexProgram

_elem = {"sum": jnp.add, "min": jnp.minimum, "max": jnp.maximum}


def _merge_aggs(op: str, a, b):
    return jax.tree_util.tree_map(_elem[op], a, b)


def _unpack_bits(packed: jnp.ndarray, n: int) -> jnp.ndarray:
    """u8[k, n//8] (little bit order) → bool[k, n]. Window masks ship to the
    device bit-packed: on a host with few cores, H2D staging competes with
    the snapshot builds of a range sweep, so bytes on the wire matter."""
    bits = (packed[:, :, None] >> jnp.arange(8, dtype=packed.dtype)) & 1
    return bits.reshape(packed.shape[0], n).astype(bool)


def make_runner(program: VertexProgram, n: int, m: int, k: int, pcpm=None):
    """The raw (unjitted) superstep program for given padded shapes — the
    jittable forward step of the framework; see also ``__graft_entry__``.

    The returned fn takes BIT-PACKED masks (u8[k, n//8] / u8[k, m//8],
    little bit order). Arrays a program opts out of (``needs_vids`` /
    ``needs_vertex_times`` / ``needs_edge_times`` False) may be passed as
    1-element dummies — the runner substitutes pad defaults on device, so
    the host never stages or transfers them. ``pcpm`` (a
    ``ops.partition.PartitionSpec``) appends the destination-binned
    layout operands (perm, valid, b_dst) — see ``make_mask_runner``."""
    core = make_mask_runner(program, n, m, k, pcpm)

    def run(v_masks_p, e_masks_p, vids, v_latest, v_first,
            e_src, e_dst, e_latest, e_first,
            time, windows, eprops, vprops, *rest):
        return core(_unpack_bits(v_masks_p, n), _unpack_bits(e_masks_p, m),
                    vids, v_latest, v_first, e_src, e_dst, e_latest, e_first,
                    time, windows, eprops, vprops, *rest)

    return run


def make_mask_runner(program: VertexProgram, n: int, m: int, k: int,
                     pcpm=None):
    """The superstep core over UNPACKED bool masks (v_masks[k,n],
    e_masks[k,m]) — shared by the bit-packed host path (``make_runner``) and
    the device-resident sweep engine (``device_sweep.py``), which computes
    the masks on device and so never packs.

    The window batch is evaluated as ONE FLAT graph of k*n vertices / k*m
    edges (per-window segment ids offset by kk*n) rather than vmapping the
    gather/segment-combine per window: one scatter instead of k batched
    scatters. This is also a deliberate dodge of a TPU backend miscompile
    observed with [vmapped scatter inside a while_loop whose condition
    depends on carried state] — with the flat layout the halt-early
    condition is safe (verified against host references in
    tests/test_engine_algorithms.py::
    test_pagerank_batched_windows_match_single)."""

    def run(v_masks, e_masks, vids, v_latest, v_first,
            e_src, e_dst, e_latest, e_first,
            time, windows, eprops, vprops, *rest):
        if pcpm is not None:
            # destination-binned exchange (ops/partition.py): the sorted
            # combine's flat scatter becomes P dense per-partition
            # reductions, each into a cache-resident n_per-row block
            b_perm, b_valid, b_dst = rest
            b_local = (b_dst.reshape(pcpm.partitions, pcpm.cap)
                       - jnp.arange(pcpm.partitions,
                                    dtype=b_dst.dtype)[:, None]
                       * pcpm.n_per)
        if not program.needs_vids:
            vids = jnp.full((n,), -1, jnp.int64)
        if not program.needs_vertex_times:
            v_latest = jnp.full((n,), jnp.iinfo(jnp.int64).min, jnp.int64)
            v_first = v_latest
        if not program.needs_edge_times:
            e_latest = jnp.full((m,), jnp.iinfo(jnp.int64).min, jnp.int64)
            e_first = e_latest

        # flat (window-major) edge space: ids offset by kk*n
        voffs = (jnp.arange(k, dtype=jnp.int32) * n)[:, None]
        flat_dst = (e_dst[None, :] + voffs).reshape(-1)   # [k*m]; dst-sorted
        flat_src = (e_src[None, :] + voffs).reshape(-1)   # per window block
        em_flat = e_masks.reshape(-1)

        def tile_e(a):
            return jnp.broadcast_to(a[None, :], (k,) + a.shape).reshape(
                (k * m,) + a.shape[1:])

        def combine_flat(tree_flat, ids, sorted_):
            # the segmented-scan combine beats XLA's scatter lowering ~3x
            # per element on TPU but is a multi-pass loser on CPU (whose
            # native scatter-add is one pass) — pick per backend at trace
            # time; per-window blocks keep results bitwise equal to k=1 runs
            use_scan = (program.combiner == "sum" and sorted_
                        and jax.default_backend() == "tpu")
            # the binned route owns the DESTINATION direction (the layout
            # bins by dst); the reverse direction keeps the flat scatter
            use_pcpm = pcpm is not None and sorted_ and not use_scan

            def leaf(x):
                if use_pcpm:
                    xb = x.reshape((k, m) + x.shape[1:])[:, b_perm]
                    mb = em_flat.reshape(k, m)[:, b_perm] \
                        & b_valid[None, :]
                    P, cap = pcpm.partitions, pcpm.cap
                    out = jax.vmap(
                        lambda xw, mw: partition_segment_reduce(
                            xw.reshape((P, cap) + x.shape[1:]),
                            b_local, pcpm.n_per, n, program.combiner,
                            mw.reshape(P, cap)))(xb, mb)
                    return out                       # [k, n, ...]
                if use_scan:
                    out = segment_sum_sorted_csr(x, ids, k * n, em_flat,
                                                 block_size=m)
                else:
                    out = segment_combine(x, ids, k * n, program.combiner,
                                          em_flat, indices_are_sorted=sorted_)
                return out.reshape((k, n) + x.shape[1:])
            return jax.tree_util.tree_map(leaf, tree_flat)

        # per-window degrees: one flat segment-sum over the masked edge set
        ones_flat = jnp.ones((k * m,), jnp.int32)
        in_deg = segment_combine(ones_flat, flat_dst, k * n, "sum",
                                 em_flat, True).reshape(k, n)
        out_deg = segment_combine(ones_flat, flat_src, k * n, "sum",
                                  em_flat, False).reshape(k, n)

        def mk_ctx(kk, step):
            return Context(
                n=n, time=time, window=windows[kk], v_mask=v_masks[kk],
                vids=vids, v_latest_time=v_latest, v_first_time=v_first,
                out_deg=out_deg[kk], in_deg=in_deg[kk],
                n_active=jnp.sum(v_masks[kk].astype(jnp.int32)),
                step=step, vprops=vprops,
            )

        def init_k(kk):
            return program.init(mk_ctx(kk, jnp.int32(0)))

        state0 = jax.vmap(init_k)(jnp.arange(k))

        def flat_edges(step):
            # Edges contract: src/dst are the per-window vertex indices
            # (programs compare them, e.g. self-loop drops) — NOT offset
            return Edges(src=tile_e(e_src), dst=tile_e(e_dst), mask=em_flat,
                         time=tile_e(e_latest), first_time=tile_e(e_first),
                         props=jax.tree_util.tree_map(tile_e, eprops),
                         step=step)

        def gather_flat(state, ids):
            return jax.tree_util.tree_map(
                lambda a: a.reshape((k * n,) + a.shape[2:])[ids], state)

        def custom_flat(tree_flat, ids):
            agg = program.exchange(tree_flat, ids, k * n, em_flat)
            return jax.tree_util.tree_map(
                lambda a: a.reshape((k, n) + a.shape[1:]), agg)

        def step_all(st, step):
            ek = flat_edges(step)
            custom = program.combiner == "custom"
            agg = None
            if program.direction in ("out", "both"):
                payload = program.message(gather_flat(st, flat_src), ek)
                agg = (custom_flat(payload, flat_dst) if custom
                       else combine_flat(payload, flat_dst, True))
            if program.direction in ("in", "both"):
                payload = program.message(gather_flat(st, flat_dst), ek)
                agg_in = (custom_flat(payload, flat_src) if custom
                          else combine_flat(payload, flat_src, False))
                agg = agg_in if agg is None else _merge_aggs(
                    program.combiner, agg, agg_in)

            def upd_k(kk, stk, aggk):
                new, votes = program.update(stk, aggk, mk_ctx(kk, step))
                return new, jnp.all(votes | ~v_masks[kk])

            return jax.vmap(upd_k, in_axes=(0, 0, 0))(jnp.arange(k), st, agg)

        if program.max_steps > 0:
            def cond(carry):
                step, _, halted = carry
                return (step < program.max_steps) & ~jnp.all(halted)

            def body(carry):
                step, st, halted = carry
                new_st, new_halt = step_all(st, step)
                # freeze halted windows
                st = jax.tree_util.tree_map(
                    lambda old, new: jnp.where(
                        halted.reshape((k,) + (1,) * (new.ndim - 1)), old, new),
                    st, new_st)
                return step + 1, st, halted | new_halt

            steps, state, halted = jax.lax.while_loop(
                cond, body, (jnp.int32(0), state0, jnp.zeros((k,), bool)))
        else:
            steps, state = jnp.int32(0), state0

        def fin_k(kk, st):
            return program.finalize(st, mk_ctx(kk, steps))

        result = jax.vmap(fin_k, in_axes=(0, 0))(jnp.arange(k), state)
        return result, steps

    return run


@functools.lru_cache(maxsize=256)
def _compiled_runner(program: VertexProgram, n: int, m: int, k: int,
                     prop_keys: tuple, vprop_keys: tuple, pcpm=None):
    """One compiled program per (algorithm instance, padded shapes, #windows).

    Range sweeps at the same bucketed shape hit this cache — the amortisation
    the reference never had (fresh handshake per hop,
    ``RangeAnalysisTask.scala:18-35``). ``pcpm`` (a ``PartitionSpec``,
    resolved by the DISPATCH site so ``RTPU_PCPM``/``RTPU_PARTITIONS``
    are part of this cache key) selects the destination-binned exchange."""
    from ..ops.partition import edge_traffic_model

    return _ledger.instrument(f"bsp.superstep.{type(program).__name__}",
                              jax.jit(make_runner(program, n, m, k, pcpm)),
                              traffic=edge_traffic_model(m, k, n, pcpm))


def _view_layout(view: GraphView, e_src, e_dst, occurrences: bool):
    """Destination-binned layout for a view's edge table, or None when
    ``RTPU_PCPM`` keeps the flat exchange. Knobs are read HERE, at
    dispatch, and reach the compiled runner's cache key through the
    layout's spec. The REAL row count matters: the cap-padded pow2 tail
    (dst = n_pad-1) must become invalid cap-pad slots, not binned edges
    that inflate the last partition's capacity by the pad count."""
    from ..ops import partition as _partition

    if occurrences:
        rows = view._occ_rows
        m = int((rows >= 0).sum()) if rows is not None else len(e_src)
    else:
        m = int(view.m_active)
    return _partition.resolve(
        view, _partition.HostTables(e_src, e_dst, view.n_pad, m),
        _partition.tile_budget_bytes(), tag="occ" if occurrences else "e")


def _gather_props(view: GraphView, keys, kind: str):
    out = {}
    for name in keys:
        if kind == "occ":
            arr = view.occ_prop(name)  # per-occurrence (per-event) values
        elif kind == "e":
            arr = view.edge_prop(name)
        else:
            arr = view.vertex_prop(name)
        out[name] = jnp.asarray(arr, jnp.float32)
    return out


def run_async(
    program: VertexProgram,
    view: GraphView,
    *,
    window: int | None = None,
    windows=None,
):
    """Dispatch a vertex program against a view WITHOUT waiting for the
    device: returns (result, steps) as device arrays. Range sweeps use this
    to pipeline host snapshot builds with device compute — hop i+1's
    snapshot folds while hop i's supersteps run.

    window=None, windows=None → plain view ({View,Range}AnalysisTask).
    window=w                  → single window (Windowed*).
    windows=[w0 > w1 > ...]   → batched windows, one result per window
                                (BWindowed*; leading axis on the result).
    """
    batched = windows is not None
    if program.combiner == "custom" and program.direction == "both":
        raise ValueError(
            "combiner='custom' requires direction 'out' or 'in' — merging "
            "two custom aggregations is not well-defined")
    if windows is not None and len(windows) == 0:
        raise ValueError("windows must be a non-empty list of window sizes")
    if windows is None:
        windows = [window if window is not None else -1]
    wlist = list(windows)
    k = len(wlist)

    # Occurrence-based temporal programs (EthereumTaintTracking-style) run
    # over the multigraph of edge-add events rather than deduped edges —
    # the analogue of iterating raw edge history via
    # ``getOutgoingNeighborsAfter`` (VertexVisitor.scala:33).
    if program.needs_occurrences:
        if view.occ_src is None:
            raise ValueError(
                "program needs occurrences: build the view with "
                "include_occurrences=True")
        e_src, e_dst = view.occ_src, view.occ_dst
        e_latest = e_first = view.occ_time
        e_base_mask = view.occ_mask  # dst-sorted, like the deduped edges
    else:
        e_src, e_dst = view.e_src, view.e_dst
        e_latest, e_first = view.e_latest_time, view.e_first_time
        e_base_mask = view.e_mask
    m_pad = len(e_src)

    v_masks = np.empty((k, view.n_pad), bool)
    e_masks = np.empty((k, m_pad), bool)
    for i, w in enumerate(wlist):
        if w is None or w < 0:
            v_masks[i] = view.v_mask
            e_masks[i] = e_base_mask
        else:
            vm, _ = view.window_masks([w])
            v_masks[i] = vm[0]
            e_masks[i] = e_base_mask & (e_latest >= view.time - w)

    # build the layout only when the binned route can actually engage:
    # custom exchanges and in-only programs never take the sorted-combine
    # path, and on TPU the sum combine lowers through the segmented scan
    # (combine_flat's use_scan) — paying an O(m log m) build + upload per
    # fresh view for a route that won't run would be pure overhead
    binnable = (program.combiner != "custom"
                and program.direction in ("out", "both")
                and not (program.combiner == "sum"
                         and jax.default_backend() == "tpu"))
    layout = _view_layout(view, e_src, e_dst,
                          program.needs_occurrences) if binnable else None
    extra = ()
    if layout is not None:
        b_src, b_dst, b_valid, _slot, _u, b_perm = layout.device_args()
        extra = (b_perm, b_valid, b_dst)
    runner = _compiled_runner(
        program, view.n_pad, m_pad, k,
        tuple(program.edge_props), tuple(program.vertex_props),
        None if layout is None else layout.spec,
    )
    eprops = _gather_props(
        view, program.edge_props,
        "occ" if program.needs_occurrences else "e")
    vprops = _gather_props(view, program.vertex_props, "v")
    win_arr = jnp.asarray([(-1 if w is None else int(w)) for w in wlist], jnp.int64)

    dummy64 = jnp.zeros((1,), jnp.int64)
    with TRACER.span("bsp.dispatch", n=int(view.n_pad), m=int(m_pad),
                        windows=k, time=int(view.time),
                        program=type(program).__name__,
                        pcpm=layout is not None):
        result, steps = runner(
            jnp.asarray(np.packbits(v_masks, axis=1, bitorder="little")),
            jnp.asarray(np.packbits(e_masks, axis=1, bitorder="little")),
            jnp.asarray(view.vids) if program.needs_vids else dummy64,
            (jnp.asarray(view.v_latest_time)
             if program.needs_vertex_times else dummy64),
            (jnp.asarray(view.v_first_time)
             if program.needs_vertex_times else dummy64),
            jnp.asarray(e_src), jnp.asarray(e_dst),
            jnp.asarray(e_latest) if program.needs_edge_times else dummy64,
            jnp.asarray(e_first) if program.needs_edge_times else dummy64,
            jnp.asarray(view.time, jnp.int64), win_arr, eprops, vprops,
            *extra,
        )
    if not batched:
        result = jax.tree_util.tree_map(lambda a: a[0], result)
    return result, steps


def run(
    program: VertexProgram,
    view: GraphView,
    *,
    window: int | None = None,
    windows=None,
):
    """Blocking ``run_async``: waits for the device and returns
    (result, int steps)."""
    result, steps = run_async(program, view, window=window, windows=windows)
    _, steps = block_steps(lambda: (None, steps))
    return result, steps
