"""The vertex-program contract — TPU-native ``Analyser`` equivalent.

The reference's user algorithm contract is the ``Analyser`` trait
(``core/analysis/API/Analyser.scala:30-63``): ``setup()``, ``analyse()`` (one
superstep of per-vertex code sending point-to-point messages), result
reducers, ``defineMaxSteps()``. Here an algorithm is a frozen dataclass of
pure array functions over the WHOLE vertex/edge set at once:

    init(ctx)                  -> state pytree          (Analyser.setup)
    message(src_state, edge)   -> payload pytree        (messageNeighbour)
    update(state, agg, ctx)    -> (state, halt_votes)   (Analyser.analyse + voteToHalt)
    finalize(state, ctx)       -> result pytree         (returnResults)

Being a frozen dataclass makes the program hashable, so the engine passes it
to jit as a static argument: one compiled superstep program per
(algorithm, hyperparams, padded shapes) — reused across every hop of a range
sweep (the reference re-runs the whole actor handshake per hop,
``RangeAnalysisTask.scala:18-35``).

Messages always flow along edges; ``direction`` picks out-edges ('out':
src→dst), in-edges ('in': dst→src), or 'both'. Aggregation at the receiver is
an associative-commutative ``combiner`` ('sum' | 'min' | 'max') — the
narrowing of the reference's arbitrary typed messages that makes vertex
messaging a segment reduction (SURVEY.md §2.9) — OR ``'custom'``: the
program's ``exchange`` hook receives the raw flat payloads with their
destination segment ids and reduces them itself (sort-based routing — see
``ops.segment.segment_mode``), recovering inbox-style algorithms (label
histograms, majority votes) the elementwise combiners cannot express
(``VertexVisitor.scala:99-161`` generality).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class Edges:
    """Per-edge arrays visible to ``message`` (masked rows are neutralised by
    the engine). ``time``/``first_time`` are the latest/earliest history
    points — the temporal columns that power time-aware algorithms.

    ``src``/``dst`` are GLOBAL padded vertex indices in every engine (on a
    single device global == local). Programs may compare them (e.g. drop
    self-loops) but must not index local per-shard arrays with them."""

    src: jnp.ndarray          # i32[m] global padded source index
    dst: jnp.ndarray          # i32[m] global padded destination index
    mask: jnp.ndarray         # bool[m] (already window-restricted)
    time: jnp.ndarray         # i64[m] latest activity <= T (occurrence time
                              #        for needs_occurrences programs)
    first_time: jnp.ndarray   # i64[m]
    props: dict[str, jnp.ndarray]   # f32[m] per requested key
    step: jnp.ndarray = 0     # i32 scalar: current superstep (for
                              # counter-based randomness etc.)


@dataclass(frozen=True)
class Context:
    """Per-superstep global context visible to ``init``/``update``/``finalize``.

    The analogue of the reference's injected ``sysSetup(context, managerCount,
    proxy: GraphLens, workerID)`` (``Analyser.scala:37-42``) — but the "lens"
    is just arrays.
    """

    n: int                    # LOCAL padded vertex count (static; = global on 1 device)
    time: jnp.ndarray         # i64 scalar: view timestamp
    window: jnp.ndarray       # i64 scalar: window size (-1 = none)
    v_mask: jnp.ndarray       # bool[n] in-view/in-window vertices (local rows)
    vids: jnp.ndarray         # i64[n] global ids (-1 pad)
    v_latest_time: jnp.ndarray
    v_first_time: jnp.ndarray
    out_deg: jnp.ndarray      # i32[n] under current mask
    in_deg: jnp.ndarray       # i32[n]
    n_active: jnp.ndarray     # i32 scalar: GLOBAL active vertex count
    step: jnp.ndarray         # i32 scalar: current superstep
    vprops: dict[str, jnp.ndarray]
    # Sharding context. On a sharded mesh, a program sees only its device's
    # rows; `v_offset` is the global index of local row 0 and `axis_name` the
    # mesh axis for cross-shard reductions. Programs that need global scalars
    # (e.g. PageRank's dangling mass) MUST use ctx.global_sum — on one device
    # it degrades to a plain jnp.sum.
    v_offset: jnp.ndarray = 0      # i32 scalar
    axis_name: str | None = None   # static

    @property
    def num_vertices(self) -> jnp.ndarray:
        """GLOBAL active vertex count as f32 (PageRank-style normalisers)."""
        return self.n_active.astype(jnp.float32)

    def global_sum(self, x: jnp.ndarray) -> jnp.ndarray:
        s = jnp.sum(x)
        if self.axis_name is not None:
            s = jax.lax.psum(s, self.axis_name)
        return s

    def global_max(self, x: jnp.ndarray) -> jnp.ndarray:
        s = jnp.max(x)
        if self.axis_name is not None:
            s = jax.lax.pmax(s, self.axis_name)
        return s

    def global_index(self) -> jnp.ndarray:
        """i32[n]: global padded index of each local row (CC labels etc.)."""
        return jnp.asarray(self.v_offset, jnp.int32) + jnp.arange(self.n, dtype=jnp.int32)


class VertexProgram:
    """Base class; subclass as @dataclass(frozen=True) with hyperparams as
    fields. Class attributes configure the engine."""

    combiner: str = "sum"
    direction: str = "out"          # 'out' | 'in' | 'both'
    max_steps: int = 20
    edge_props: tuple[str, ...] = ()
    vertex_props: tuple[str, ...] = ()
    needs_occurrences: bool = False  # multigraph temporal algorithms
    # Array-requirement declarations. Defaults are conservative (everything
    # ships to the device); a program that never reads ctx.vids /
    # ctx.v_{latest,first}_time / edge.{time,first_time} on device should
    # set the matching flag False — the engine then skips staging and
    # transferring those arrays entirely (a large share of per-hop H2D bytes
    # in range sweeps). With a flag False the corresponding ctx/edge fields
    # hold pad defaults (-1 / INT64_MIN) on device.
    needs_vids: bool = True
    needs_vertex_times: bool = True
    needs_edge_times: bool = True
    # True when the program's overridden ``reduce`` reads only the
    # vertex-side view fields (vids / v_mask / v_latest_time /
    # window_masks()[0]) — the amortised sweep engines hand reducers a
    # lightweight shell without edge masks or property joins. Programs whose
    # reducers touch edges or properties keep the default False and run on
    # the full per-view path. (A non-overridden reduce is pass-through and
    # always safe.)
    reduce_shell_safe: bool = False
    # Monotone min-merge declaration — the eligibility gate for the sparse
    # frontier comm route (``parallel/frontier.py``). True asserts ALL of:
    #   * ``combiner == "min"`` and state is a SINGLE array leaf;
    #   * ``update(state, agg, ctx)`` is elementwise
    #     ``where(v_mask, min(state, agg), pad)`` for a fixed pad constant
    #     equal to the min-identity of the state dtype — so merging
    #     per-owner partial updates elementwise-min reproduces the dense
    #     result bitwise, and a no-message superstep is a fixed point;
    #   * halt votes are exactly ``new == state`` (quiescence == no change);
    #   * ``init``/``update``/``finalize`` never read ``ctx.out_deg`` /
    #     ``ctx.in_deg`` (the sparse route computes degrees from the local
    #     edge subset only — see docs/COMM.md "monotone-min contract").
    # ConnectedComponents and SSSP/BFS satisfy this; PageRank-style dense
    # fixpoints must keep the default False.
    monotone_min: bool = False

    @property
    def cost_label(self) -> str:
        """Algorithm label the resource ledger files this program's cost
        under (``raphtory_query_cost_*{algorithm=...}`` metrics, /costz
        recent-query rows, kernel names in the registry). Class name by
        default; override when one class serves several user-facing
        algorithms."""
        return type(self).__name__

    # -- pure array functions --

    def init(self, ctx: Context) -> Any:
        raise NotImplementedError

    def message(self, src_state: Any, edge: Edges) -> Any:
        """Payload sent along each edge, computed from the SENDER's state.
        For direction='in' the "sender" is the edge's dst vertex; for 'both'
        it's called once per direction."""
        raise NotImplementedError

    def exchange(self, payload: Any, seg_ids: jnp.ndarray,
                 num_segments: int, mask: jnp.ndarray) -> Any:
        """combiner='custom' only: reduce the flat per-edge ``payload``
        pytree (leaves [m, ...]) into per-vertex aggregates (leaves
        [num_segments, ...]). ``seg_ids[m]`` is each payload's destination
        segment; rows with ``mask`` False must not contribute. Runs inside
        the compiled superstep on every engine (single-chip and mesh) —
        use static-shape segment ops (``segment_combine``, ``segment_mode``)
        only. Restricted to direction 'out' or 'in' (merging two custom
        aggregations is not well-defined)."""
        raise NotImplementedError

    def update(self, state: Any, agg: Any, ctx: Context):
        """Fold the combined inbox into new state; return (state, halt_votes)
        with halt_votes bool[n] True where the vertex votes to halt."""
        raise NotImplementedError

    def finalize(self, state: Any, ctx: Context) -> Any:
        return state

    # -- host-side reduction (Analyser.processResults analogue) --

    def reduce(self, result, view, window=None):
        """Turn device results into the job-level answer (host code).
        Default: pass through."""
        return result
