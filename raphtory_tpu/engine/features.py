"""Windowed feature aggregation at scale — the bandwidth-bound engine.

Scalar vertex programs (PageRank, CC) move 4 bytes per edge endpoint, so at
any scale their superstep is bound by the accelerator's per-element
random-access rate — the one primitive graph workloads can't tile. This
engine propagates F-WIDE feature rows instead (GNN-style mean aggregation
over the temporal window): every memory access becomes a 128-lane row-tile
move, which the TPU executes at HBM bandwidth. It is the "embedding /
representation over a temporal window" workload class the reference cannot
express at all (its analysers push scalars through actor mailboxes —
``Analyser.scala:30-63``), and the scale benchmark where the chip, not the
host, sets the ceiling.

Design:
* operates on a ``DeviceSweep``'s resident fold state — the window mask
  ``alive ∧ latest ≥ T − W`` (``Entity.scala:193-201`` semantics) is
  computed on device, nothing ships per hop;
* the edge axis is processed in fixed chunks under one ``lax.scan`` so the
  [m, F] payload never materialises (HBM holds 2 chunk tiles, not 50 GB);
* aggregation is sum + degree-normalise (mean), the GraphSAGE-mean shape;
  ``self_weight`` mixes each vertex's own features back in.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .device_sweep import DeviceSweep


@functools.lru_cache(maxsize=64)
def _compiled_propagate(n_pad: int, m_pad: int, chunk: int, F: int,
                        rounds: int, self_weight: float, tdt: str,
                        fdt: str = "float32", pcpm=None):
    """``fdt`` is the feature STORAGE dtype: bfloat16 halves the HBM bytes
    of the per-edge row gathers (the term this engine is bound by on TPU)
    while accumulation, degree-normalise and the L2 norm stay float32 —
    the standard mixed-precision aggregation recipe.

    ``pcpm`` (``ops/partition.PartitionSpec``) is the partition-centric
    route: the edge scan walks DESTINATION PARTITIONS instead of raw
    chunks. Per partition the kernel gathers each distinct source row
    ONCE into a pre-aggregation bucket (``[cap_u, F]``), expands it as a
    streaming read, and reduces into a dense ``[n_per, F]`` block — the
    per-edge F-wide row gather this engine is bound by shrinks by the
    bucket dedup factor, and the accumulator slice is cache-resident.
    Sum order changes: results agree to f32 tolerance (bitwise under
    ``RTPU_PCPM=0``)."""
    tdt = jnp.dtype(tdt)
    fdt = jnp.dtype(fdt)
    C = m_pad // chunk

    def propagate(X, e_src, e_dst, e_lat, e_alive, time, window, *rest):
        X = X.astype(fdt)
        info = jnp.iinfo(tdt)
        lo = jnp.clip(time - window, info.min, info.max).astype(tdt)
        mask = e_alive & ((window < 0) | (e_lat >= lo))   # [m_pad]
        if pcpm is not None:
            P, n_per = pcpm.partitions, pcpm.n_per
            cap, cap_u = pcpm.cap, pcpm.cap_u
            b_perm, b_valid, b_dst, b_slot, u_src = rest
            bm = (mask[b_perm] & b_valid).reshape(P, cap)
            iota = jnp.arange(P, dtype=jnp.int32)[:, None]
            loc = b_dst.reshape(P, cap) - iota * n_per
            sl = b_slot.reshape(P, cap) - iota * cap_u
            u2 = u_src.reshape(P, cap_u)

            def deg_body(_, ins):
                loc_p, mk_p = ins
                return None, jax.ops.segment_sum(
                    mk_p.astype(jnp.float32), loc_p, num_segments=n_per)

            _, degs = jax.lax.scan(deg_body, None, (loc, bm))
            deg = degs.reshape(P * n_per)[:n_pad]
        else:
            src_c = e_src.reshape(C, chunk)
            dst_c = e_dst.reshape(C, chunk)
            msk_c = mask.reshape(C, chunk)
            ones = jnp.ones((chunk,), jnp.float32)

            # masked in-degree is round-invariant — one per-element pass
            # total, not one per round
            def deg_body(deg, ins):
                d, mk = ins
                return deg + jax.ops.segment_sum(
                    jnp.where(mk, ones, 0.0), d, num_segments=n_pad,
                    indices_are_sorted=True), None

            deg, _ = jax.lax.scan(deg_body,
                                  jnp.zeros((n_pad,), jnp.float32),
                                  (dst_c, msk_c))
        inv_deg = 1.0 / jnp.maximum(deg, 1.0)

        def one_round(H, _):
            if pcpm is not None:
                def part_body(_, ins):
                    u_p, sl_p, loc_p, mk_p = ins
                    # ONE fdt row per distinct (partition, src) — the
                    # bucket dedup is the whole gather-traffic win
                    vals = H[u_p, :].astype(jnp.float32)   # [cap_u, F]
                    G = jnp.where(mk_p[:, None], vals[sl_p, :], 0.0)
                    return None, jax.ops.segment_sum(
                        G, loc_p, num_segments=n_per)

                _, aggs = jax.lax.scan(part_body, None, (u2, sl, loc, bm))
                agg = aggs.reshape(P * n_per, F)[:n_pad]
            else:
                def chunk_body(agg, ins):
                    s, d, mk = ins
                    # gather reads fdt rows from HBM; the f32 convert
                    # happens in-flight, so bf16 storage halves the
                    # streamed bytes
                    G = jnp.where(mk[:, None], H[s, :].astype(jnp.float32),
                                  0.0)
                    return agg + jax.ops.segment_sum(
                        G, d, num_segments=n_pad,
                        indices_are_sorted=True), None

                agg, _ = jax.lax.scan(
                    chunk_body, jnp.zeros((n_pad, F), jnp.float32),
                    (src_c, dst_c, msk_c))
            H2 = agg * inv_deg[:, None]
            H2 = self_weight * H.astype(jnp.float32) \
                + (1.0 - self_weight) * H2
            # row L2 normalise keeps magnitudes bounded across rounds
            norm = jnp.sqrt(jnp.sum(H2 * H2, axis=1, keepdims=True))
            return (H2 / jnp.maximum(norm, 1e-12)).astype(fdt), None

        H, _ = jax.lax.scan(one_round, X, None, length=rounds)
        return H

    return jax.jit(propagate)


class FeatureAggregator:
    """GNN-style windowed mean aggregation over a device-resident sweep.

    ``propagate(X, T, window, rounds)`` advances the sweep to T and returns
    the propagated [n_pad, F] features (async device array). Rows are the
    sweep's global dense vertex space (``ds.uv``)."""

    def __init__(self, ds: DeviceSweep, feature_dim: int = 128,
                 chunk: int = 1 << 22, self_weight: float = 0.5,
                 dtype: str = "float32"):
        self.ds = ds
        self.F = feature_dim
        # chunk must divide m_pad; shrink to m_pad when the graph is small
        self.chunk = min(chunk, ds.m_pad)
        while ds.m_pad % self.chunk:
            self.chunk //= 2
        self.self_weight = float(self_weight)
        # feature storage dtype: "bfloat16" halves the HBM-bound row
        # traffic on TPU; accumulation stays float32 (_compiled_propagate)
        self.dtype = jnp.dtype(dtype)
        # host copies of the edge tables for the partition-layout build —
        # the sweep dropped its own after upload, so the first resolve
        # pulls them back once (D2H of 2 * m_pad i32)
        self._host_tables = None
        # the spec the LAST propagate dispatched with (None = unbinned) —
        # what traffic_bytes reports on, without re-resolving anything
        self._active_spec = None

    def _pcpm_layout(self):
        """Resolved partition layout for this aggregator, or None — one
        ``ops.partition.resolve`` call (knobs read per dispatch, the spec
        rides into the compiled-program cache key; layouts cached per
        sweep). The binned route additionally requires the per-partition
        transients (``[cap, F]`` payload, ``[cap_u, F]`` bucket) to fit
        the tile budget — oversized partitions fall back to the chunked
        scan."""
        import os

        from ..ops import partition as _partition

        ds = self.ds
        if not _partition.pcpm_enabled(ds.m_pad,
                                       os.environ.get("RTPU_PCPM", "auto")):
            return None
        if self._host_tables is None:
            self._host_tables = _partition.HostTables(
                np.asarray(ds.e_src), np.asarray(ds.e_dst), ds.n_pad, ds.m)
        budget = _partition.tile_budget_bytes()
        lay = _partition.resolve(ds, self._host_tables, budget)
        if lay is None or not lay.spec.preagg \
                or lay.spec.cap * self.F * 4 > budget \
                or lay.spec.cap_u * self.F * 4 > budget:
            return None
        return lay

    def random_features(self, seed: int = 0):
        """Deterministic on-device init (unit-norm rows) — no host transfer."""
        X = jax.random.normal(jax.random.PRNGKey(seed),
                              (self.ds.n_pad, self.F), jnp.float32)
        return (X / jnp.linalg.norm(X, axis=1, keepdims=True)) \
            .astype(self.dtype)

    def propagate(self, X, time: int | None = None, *,
                  window: int | None = None, rounds: int = 2):
        ds = self.ds
        if time is not None:
            ds.advance(time)
        if ds.t_now is None:
            raise ValueError("advance the sweep (or pass time=) first")
        layout = self._pcpm_layout()
        self._active_spec = None if layout is None else layout.spec
        extra = ()
        if layout is not None:
            b_src, b_dst, b_valid, b_slot, u_src, b_perm = \
                layout.device_args()
            extra = (b_perm, b_valid, b_dst, b_slot, u_src)
        fn = _compiled_propagate(
            ds.n_pad, ds.m_pad, self.chunk, self.F, int(rounds),
            self.self_weight, np.dtype(ds.tdtype).name, self.dtype.name,
            None if layout is None else layout.spec)
        v_lat, v_alive, v_first, e_lat, e_alive, e_first = ds._bufs
        return fn(X, ds.e_src, ds.e_dst, e_lat, e_alive,
                  jnp.asarray(ds.t_now, jnp.int64),
                  jnp.asarray(-1 if window is None else int(window),
                              jnp.int64), *extra)

    def traffic_bytes(self, rounds: int) -> int:
        """Approximate HBM bytes per propagate call (for utilisation
        reporting): per round, the edge axis streams a gathered F-row and
        writes it once into the accumulator, plus index/mask columns; the
        masked-degree pass runs ONCE per call (round-invariant). Reports
        the mode the LAST propagate dispatched in — a pure read, never a
        layout build. On the partition-centric route the per-edge row
        GATHER shrinks to one row per (partition, src) bucket — the dedup
        factor the binning exists for — while the expansion streams at
        fdt width."""
        fb = self.dtype.itemsize                # feature storage bytes/lane
        per_edge = self.F * (fb + 4) + 2 * 4 + 1  # fdt gather + f32 scatter
        per_vertex = self.F * (2 * 4 + fb)      # f32 acc read+write, fdt H
        s = self._active_spec
        if s is not None:
            B = s.partitions * s.cap
            u_rows = s.partitions * s.cap_u
            deg_pass = B * (4 + 1)
            per_round = (u_rows * self.F * fb          # bucket fill
                         + B * (self.F * (fb + 4) + 4 + 1)  # expand+scatter
                         + self.ds.n_pad * per_vertex)
            return deg_pass + rounds * per_round
        deg_pass = self.ds.m_pad * (4 + 1)      # dst ids + mask, one pass
        return deg_pass + rounds * (self.ds.m_pad * per_edge
                                    + self.ds.n_pad * per_vertex)

    def flops(self, rounds: int) -> int:
        """Adds/multiplies per propagate call (mean-aggregate + mix + norm)."""
        return rounds * (self.ds.m_pad * self.F          # segment adds
                         + self.ds.n_pad * self.F * 6)   # mean/mix/normalise
