"""Continuous sampling profiler — always-on, low-overhead host stacks.

"Continuous Profiling: Where Have All the Cycles Gone?" made the case
that profiles you only collect during incidents are profiles of the
wrong moment; the discipline is an always-on sampler cheap enough to
forget about. The host-side analogue here: a daemon thread walks
``sys._current_frames()`` at ``RTPU_SAMPLE_HZ`` (default off; 25 Hz
costs roughly 10 ms of interpreter time per second on this repo's
thread counts), aggregates collapsed call stacks per thread, and tags
every sample with the sampled thread's **active span and trace id**
(``Tracer.active_for``) — so a flamegraph bucket answers not just
"where do cycles go" but "which request was burning them".

Surfaces
--------
* ``/profilez`` (jobs/rest.py): JSON status; ``?format=collapsed`` emits
  the standard collapsed-stack flamegraph format (one
  ``thread;frame;frame… count`` line per distinct stack — feed it to
  ``flamegraph.pl`` / speedscope); ``?enable=0|1`` toggles at runtime.
* The flight-recorder dump: the sampler registers a Chrome-export aux
  provider, so ``/tracez?dump=1`` and the CI failure artifact carry the
  profile next to the spans (obs/trace.py ``register_aux``).
* ``RTPU_SAMPLE_DUMP`` — file path; implies sampling on at import, and
  the collapsed stacks are written there at interpreter exit.

The sampler is GIL-coarse by construction (``sys._current_frames()``
reports the frame a thread will resume at, not a true interrupt PC) —
right for attributing WALL time of Python-level phases, which is what
the fold/emit/serving paths are.
"""

from __future__ import annotations

import math
import os
import sys
import threading
import time

#: cap on DISTINCT aggregated stacks — a pathological workload (deep
#: recursion over changing line numbers) must not grow host memory
#: without bound (rtpulint RT011); overflow increments a drop counter
MAX_STACKS = 8192
#: frames kept per stack, innermost dropped first beyond this
MAX_DEPTH = 64
#: bounded ring of recent tagged samples (the span/trace join surface)
RECENT = 256


def sample_hz() -> float:
    try:
        return max(0.0, float(os.environ.get("RTPU_SAMPLE_HZ", "0")))
    except ValueError:
        return 0.0


def _tracer():
    from .trace import TRACER

    return TRACER


class SamplingProfiler:
    """Aggregating ``sys._current_frames()`` sampler.

    ``start()``/``stop()`` are idempotent and thread-safe (the REST
    toggle and the env autostart may race); the sampling thread never
    takes the aggregation lock while sleeping (rtpulint RT009) and all
    aggregation state is bounded."""

    def __init__(self, hz: float | None = None):
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        # per-GENERATION stop event, replaced on every start: stop() sets
        # only the generation it swapped out, both under the lock — a
        # stop racing a concurrent start can never kill the thread that
        # start just launched (the REST toggle races the env autostart)
        self._stop = threading.Event()
        self.hz = float(hz) if hz is not None else (sample_hz() or 25.0)
        # aggregation state (all guarded by _lock)
        self._stacks: dict[tuple, int] = {}   # (thread, frames…) → count
        self._by_trace: dict[str, int] = {}   # trace_id → samples
        from collections import deque

        self._recent: deque = deque(maxlen=RECENT)
        self.samples = 0          # per-thread samples aggregated
        self.ticks = 0            # sampler wakeups
        self.dropped_stacks = 0   # distinct-stack cap overflows
        self.evicted_traces = 0   # oldest per-trace rows evicted at cap
        self.busy_seconds = 0.0   # interpreter time spent sampling

    # ---- lifecycle ----

    def start(self, hz: float | None = None) -> bool:
        """Start sampling (idempotent — already-running returns False).
        ``hz`` overrides the rate, and applies even when already running
        (the loop re-reads it each tick) — ``/profilez?enable=1&hz=``
        must retune a live sampler, not silently no-op. ``hz <= 0`` and
        non-finite rates are refused outright: a running loop divides by
        ``hz`` each tick, and inf/nan turn the interval into a 0/nan
        wait — a busy-spin, not a sampler."""
        with self._lock:
            if hz is not None:
                hz = float(hz)
                if hz <= 0 or not math.isfinite(hz):
                    return False
                self.hz = hz
            if self._thread is not None and self._thread.is_alive():
                return False
            if self.hz <= 0 or not math.isfinite(self.hz):
                return False
            self._stop = stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, args=(stop,),
                name="profile-sampler", daemon=True)
            self._thread.start()
            return True

    def stop(self) -> bool:
        """Stop sampling (idempotent — not-running returns False). The
        aggregated profile is kept; ``clear()`` resets it."""
        with self._lock:
            t, self._thread = self._thread, None
            self._stop.set()   # this generation's event, under the lock
        if t is None or not t.is_alive():
            return False
        t.join(timeout=5.0)
        return True

    def maybe_start(self) -> bool:
        """Env-gated start: a no-op unless ``RTPU_SAMPLE_HZ`` > 0 (or a
        dump path implies sampling) — what servers call at startup."""
        hz = sample_hz()
        if hz <= 0 and not os.environ.get("RTPU_SAMPLE_DUMP"):
            return False
        return self.start(hz or None)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def clear(self) -> None:
        with self._lock:
            self._stacks.clear()
            self._by_trace.clear()
            self._recent.clear()
            self.samples = self.ticks = self.dropped_stacks = 0
            self.evicted_traces = 0
            self.busy_seconds = 0.0

    # ---- sampling ----

    @staticmethod
    def _frames_of(frame) -> tuple:
        """Root-first collapsed frames for one thread's current stack.
        The full stack is walked and truncation drops the INNERMOST
        frames — flamegraph tools merge stacks at a common root, and a
        deep stack clipped at the outer end would fragment into
        unrelated towers starting mid-stack."""
        out = []
        while frame is not None:
            code = frame.f_code
            out.append(f"{code.co_name} "
                       f"({os.path.basename(code.co_filename)}"
                       f":{frame.f_lineno})")
            frame = frame.f_back
        out.reverse()
        return tuple(out[:MAX_DEPTH])

    def sample_once(self) -> int:
        """One sampling tick over every live thread except the sampler
        itself; returns the number of threads sampled."""
        t0 = time.perf_counter()
        own = threading.get_ident()
        try:
            frames = sys._current_frames()
        except Exception:   # platform without the CPython API
            return 0
        names = {t.ident: t.name for t in threading.enumerate()}
        tracer = _tracer()
        n = 0
        rows = []
        for tid, frame in frames.items():
            if tid == own:
                continue
            stack = self._frames_of(frame)
            if not stack:
                continue
            active = tracer.active_for(tid)
            rows.append((names.get(tid, f"tid-{tid}"), stack, active))
            n += 1
        now = time.time()
        with self._lock:
            for tname, stack, active in rows:
                key = (tname,) + stack
                if key in self._stacks:
                    self._stacks[key] += 1
                elif len(self._stacks) < MAX_STACKS:
                    self._stacks[key] = 1
                else:
                    self.dropped_stacks += 1
                if active is not None:
                    trace_id, sid, span = active
                    if (trace_id not in self._by_trace
                            and len(self._by_trace) >= MAX_STACKS):
                        # evict the OLDEST-inserted trace rather than
                        # refusing new ones: a long-lived server churns
                        # through trace ids, and only recent traces are
                        # still resolvable in the flight-recorder ring
                        # anyway — saturating on day-one traffic would
                        # silently freeze the per-trace attribution
                        self._by_trace.pop(next(iter(self._by_trace)))
                        self.evicted_traces += 1
                    self._by_trace[trace_id] = \
                        self._by_trace.get(trace_id, 0) + 1
                    self._recent.append({
                        "unix": round(now, 3), "thread": tname,
                        "trace_id": trace_id, "span": span,
                        "leaf": stack[-1],
                    })
                self.samples += 1
            self.ticks += 1
            self.busy_seconds += time.perf_counter() - t0
        return n

    def _loop(self, stop: threading.Event) -> None:
        while True:
            t0 = time.perf_counter()
            self.sample_once()
            spent = time.perf_counter() - t0
            # sleep OUTSIDE any lock; rate self-corrects for sample cost
            if stop.wait(max(0.0, 1.0 / self.hz - spent)):
                return

    # ---- export ----

    def collapsed(self) -> str:
        """Flamegraph collapsed-stack format: one
        ``thread;frame;frame… count`` line per distinct stack, heaviest
        first."""
        with self._lock:
            items = sorted(self._stacks.items(),
                           key=lambda kv: -kv[1])
        return "\n".join(f"{';'.join(key)} {count}"
                         for key, count in items)

    def status(self) -> dict:
        with self._lock:
            by_trace = dict(sorted(self._by_trace.items(),
                                   key=lambda kv: -kv[1])[:32])
            recent = list(self._recent)[-32:]
            return {
                "running": self.running,
                "hz": self.hz,
                "ticks": self.ticks,
                "samples": self.samples,
                "distinct_stacks": len(self._stacks),
                "dropped_stacks": self.dropped_stacks,
                "evicted_traces": self.evicted_traces,
                "busy_seconds": round(self.busy_seconds, 4),
                "samples_by_trace": by_trace,
                "recent_tagged": recent,
            }

    def _aux_block(self):
        """Chrome-export aux payload (None while nothing was sampled) —
        folds the profile into the flight-recorder dump."""
        if not self.ticks:
            return None
        st = self.status()
        st.pop("recent_tagged", None)
        with self._lock:
            top = sorted(self._stacks.items(), key=lambda kv: -kv[1])[:64]
        st["top_stacks"] = [{"stack": list(k), "count": c} for k, c in top]
        return st


SAMPLER = SamplingProfiler()
_tracer().register_aux("profiler", SAMPLER._aux_block)

_sample_dump = os.environ.get("RTPU_SAMPLE_DUMP")
if _sample_dump or sample_hz() > 0:
    SAMPLER.maybe_start()
if _sample_dump:
    from . import exitdump as _exitdump

    def _dump_collapsed(path=_sample_dump):
        text = SAMPLER.collapsed()
        if text:
            with open(path, "w") as f:
                f.write(text + "\n")

    _exitdump.register("sample", _dump_collapsed)
