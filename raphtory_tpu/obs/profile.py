"""Device profiling hooks — the TPU-native upgrade over Kamon tracing.

The reference has no distributed tracing (SURVEY §5.1 "No spans"); on TPU
the equivalent signal is an XLA profiler trace viewable in TensorBoard /
xprof: per-op device timelines, HBM usage, and fusion boundaries. Host
phases appear on the same timeline via ``obs/trace.py`` spans, whose
``TraceAnnotation`` twins land in the device trace.
"""

from __future__ import annotations

import contextlib
import logging

import jax

_log = logging.getLogger(__name__)


@contextlib.contextmanager
def device_trace(logdir: str):
    """Capture a JAX/XLA profiler trace for the enclosed block.

    Tolerant by design: enabling tracing must never take down a sweep.
    A failed ``start_trace`` (or one refused because a profiler session
    is already active — e.g. nested ``device_trace`` blocks, or an
    operator-driven capture racing a job's own) degrades to a warning
    and a no-op, and ``stop_trace`` is only called for a session THIS
    context actually started (never from ``finally`` on someone else's).
    """
    started = False
    try:
        jax.profiler.start_trace(logdir)
        started = True
    except Exception as e:  # noqa: BLE001 — any failure degrades to no-op
        _log.warning("device_trace: start_trace(%s) failed (%s: %s) — "
                     "continuing without a profiler capture",
                     logdir, type(e).__name__, e)
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                _log.warning("device_trace: stop_trace failed (%s: %s)",
                             type(e).__name__, e)


def annotate(name: str):
    """Named span visible in the device trace (TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name)
