"""Device profiling hooks — the TPU-native upgrade over Kamon tracing.

The reference has no distributed tracing (SURVEY §5.1 "No spans"); on TPU
the equivalent signal is an XLA profiler trace viewable in TensorBoard /
xprof: per-op device timelines, HBM usage, and fusion boundaries.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def device_trace(logdir: str):
    """Capture a JAX/XLA profiler trace for the enclosed block."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span visible in the device trace (TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name)
