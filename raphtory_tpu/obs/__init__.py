"""Observability — metrics + profiling (reference L8, SURVEY §5.1/§5.5).

The reference wires Kamon counters/gauges into every actor and serves
Prometheus on :11600 (``application.conf:208-213``); here the same signal
set is prometheus_client metrics updated by the pipeline/job/compaction
layers, plus a JAX profiler hook for device traces (the capability Kamon's
AspectJ weaver has no analogue for)."""

from .trace import (TRACER, TraceContext, Tracer,   # stdlib-only —
                    span)                           # always available
from .ledger import Ledger, REGISTRY, instrument   # stdlib-only (jax lazy)
from .device import RESIDENT, TIMING               # stdlib-only (jax lazy)
from .slo import SERIES, SLO                       # stdlib-only
from .sampler import SAMPLER                       # stdlib-only
from .workload import WORKLOAD                     # stdlib-only
from .budget import BUDGET                         # stdlib-only
from .advisor import ADVISOR                       # stdlib-only
from .freshness import FRESH                       # stdlib-only (numpy lazy)

try:
    # metrics + device profiling need prometheus_client / jax, which
    # stripped transport-only environments may lack; the span tracer must
    # keep working there (utils/transfer.py relies on this degradation)
    from .metrics import METRICS, Metrics, MetricsServer
    from .profile import annotate, device_trace
except ImportError:   # pragma: no cover — stripped environment
    METRICS = Metrics = MetricsServer = None
    device_trace = annotate = None

__all__ = ["METRICS", "Metrics", "MetricsServer", "device_trace",
           "annotate", "TRACER", "TraceContext", "Tracer", "span",
           "Ledger", "REGISTRY", "instrument", "SLO", "SERIES",
           "SAMPLER", "WORKLOAD", "BUDGET", "ADVISOR", "RESIDENT",
           "TIMING", "FRESH"]
