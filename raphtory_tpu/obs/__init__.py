"""Observability — metrics + profiling (reference L8, SURVEY §5.1/§5.5).

The reference wires Kamon counters/gauges into every actor and serves
Prometheus on :11600 (``application.conf:208-213``); here the same signal
set is prometheus_client metrics updated by the pipeline/job/compaction
layers, plus a JAX profiler hook for device traces (the capability Kamon's
AspectJ weaver has no analogue for)."""

from .metrics import METRICS, MetricsServer, Metrics
from .profile import device_trace, annotate

__all__ = ["METRICS", "Metrics", "MetricsServer", "device_trace", "annotate"]
