"""Advisor — the rule-driven judgment layer over the measurement planes.

PRs 6/9/10 measure everything (per-kernel roofline classes, phase
splits, queue wait, collective skew/barrier waits, watermark lag,
fold-cache hit rates, per-tenant cost) but every knob is still hand-set
and every diagnosis is still an operator joining surfaces in their
head. The advisor does the join: a periodic evaluator reads ONLY
existing surfaces and emits evidence-linked findings with concrete knob
recommendations — ``/advisez`` renders them, ``/statusz`` embeds the
compact block, and ``/clusterz`` federation lets one process advise on
the whole mesh.

Design rules:

* **Strictly read-only.** No code path here mutates a knob, an env var,
  or any engine state — this is the evidence-to-decision bridge the
  adaptive runtime (ROADMAP item 4) will later wire to actuators; until
  then a wrong recommendation costs an operator a shrug, not an outage.
  (The read-only property is regression-tested: a tick must leave
  ``os.environ`` unchanged.)
* **Machine-readable findings.** Every finding carries a stable
  ``rule_id``, the ``knob`` it names, and an ``evidence`` block with
  the metric values, trace-id exemplars, and ledger rows that justify
  it — a future actuator (or an operator's jq) needs no prose parsing.
* **Quiet by default.** Rules demand BOTH a dominance signal and an
  evidence floor before firing; a healthy process emits zero findings
  (CI asserts exactly that on every advisor bench run).
* **RT009-clean.** The periodic thread follows the SeriesRing
  generation-stop pattern; rule evaluation and every surface read
  happen OUTSIDE the advisor's own lock, and the federation path does
  its network I/O before any lock is touched.

Knobs
-----
* ``RTPU_ADVISOR`` — the periodic evaluator (default on; the
  ``advisor_overhead`` bench's off arm).
* ``RTPU_ADVISOR_INTERVAL_S`` — tick period (default 30 s).
* ``RTPU_ADVISOR_STALE_S`` — watermark-lag floor (seconds) for the
  staleness + straggler rules (default 30; the cluster smoke lowers it
  to fire the straggler rule in CI time).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from ..analysis.sanitizer import (note_shared as _san_note,
                                  track_shared as _san_track)
from . import budget as _budget
from . import device as _device
from . import freshness as _freshness
from . import journal as _journal
from . import ledger as _ledger
from . import workload as _workload
from .slo import _metrics
from .trace import TRACER

DEFAULT_INTERVAL_S = 30.0
DEFAULT_STALE_S = 30.0
#: finding-history ring bound (RT011: a misbehaving deployment must not
#: grow the advisor's memory with every tick)
HISTORY = 64
#: recent completed-query ledgers a tick reasons over
QUERY_WINDOW = 32
#: rules judged only on a FEDERATED pass (they read /clusterz data);
#: a local tick has no evidence about mesh state, so it carries the
#: last federated verdict instead of zeroing it — otherwise every
#: background tick would clear a live straggler finding and the next
#: federated pass would re-emit it as fresh (flapping gauges + history)
CLUSTER_RULES = frozenset({"cluster-straggler", "shard-skew"})
#: how long a carried cluster finding stays credible without a fresh
#: federated pass confirming it
CLUSTER_RETAIN_S = 600.0


def enabled() -> bool:
    """Re-read per tick so the bench A/B (and operators) can flip the
    advisor without a restart."""
    return os.environ.get("RTPU_ADVISOR", "1") not in ("", "0", "false")


def interval_s() -> float:
    try:
        v = float(os.environ.get("RTPU_ADVISOR_INTERVAL_S", "")
                  or DEFAULT_INTERVAL_S)
        return max(0.05, v)
    except ValueError:
        return DEFAULT_INTERVAL_S


def stale_s() -> float:
    try:
        v = float(os.environ.get("RTPU_ADVISOR_STALE_S", "")
                  or DEFAULT_STALE_S)
        return max(0.1, v)
    except ValueError:
        return DEFAULT_STALE_S


def _finding(rule_id: str, summary: str, knob: str, recommendation: str,
             evidence: dict, severity: str = "advice") -> dict:
    return {"rule_id": rule_id, "severity": severity, "summary": summary,
            "knob": knob, "recommendation": recommendation,
            "evidence": evidence, "unix": round(time.time(), 3)}


#: the advisor's OWN recent-query ring, fed by the jobs layer BEFORE the
#: RTPU_LEDGER publication gate — /costz's ring (obs/ledger._RECENT) is
#: a ledger surface and rightly goes silent under RTPU_LEDGER=0, but the
#: advisor's queue/wall evidence is jobs-layer data that survives that
#: mode (the same contract the SLO histograms and workload accounts
#: follow). Bounded; engine phases are simply absent when nothing
#: measures them, so the phase-split rules stay honestly quiet.
_QUERIES: deque = deque(maxlen=QUERY_WINDOW * 2)
_QUERIES_LOCK = threading.Lock()


def note_query(row: dict) -> None:
    """Record one completed job's ledger snapshot for rule evaluation.
    Called by ``jobs/manager._publish_ledger`` whatever ``RTPU_LEDGER``
    says (gated only on the advisor's own knob); never raises."""
    with _QUERIES_LOCK:
        _QUERIES.append(row)


def recent_query_rows(n: int = QUERY_WINDOW) -> list[dict]:
    with _QUERIES_LOCK:
        snap = list(_QUERIES)
    return snap[-max(0, int(n)):]


def _phase_split(queries: list) -> tuple[dict, float]:
    """Aggregate phase seconds over recent ledgers + their total
    (queue_wait excluded — it is an admission signal, not a phase)."""
    split: dict[str, float] = {}
    for q in queries:
        for ph, sec in q.get("phase_seconds", {}).items():
            split[ph] = split.get(ph, 0.0) + float(sec)
    return split, sum(split.values())


def _exemplars(queries: list, n: int = 3) -> list:
    """The slowest recent queries as evidence rows (id + trace id)."""
    rows = sorted(queries, key=lambda q: -q.get("wall_seconds", 0.0))
    return [{"query_id": q.get("query_id"),
             "algorithm": q.get("algorithm"),
             "tenant": q.get("tenant"),
             "trace_id": q.get("trace_id"),
             "wall_seconds": q.get("wall_seconds")} for q in rows[:n]]


# ------------------------------------------------------------- the rules
#
# Each rule is a pure function signals-dict -> finding-or-None. The
# signals dict is assembled by gather_signals(); tests feed synthetic
# dicts. Threshold constants live beside their rule. docs/OBSERVABILITY
# "Advisor" documents the catalogue row-for-row from RULES below.


def rule_hbm_bound_pcpm(sig: dict) -> dict | None:
    """Compute-dominant AND hbm-bound kernels dominate the device bytes
    AND the operator has EXPLICITLY disabled the partition-centric
    kernels — the measured evidence says the disabled knob is the one
    that would help (arXiv:1709.07122; `auto` needs no advice)."""
    if sig.get("env", {}).get("RTPU_PCPM") != "0":
        return None
    queries = sig.get("queries", [])
    split, total = _phase_split(queries)
    if len(queries) < 4 or total < 1.0:
        return None
    compute = split.get("compute", 0.0) + split.get("device_wait", 0.0)
    if compute < 0.5 * total:
        return None
    kernels = sig.get("kernels", [])
    traffic = {}
    for k in kernels:
        b = (k.get("est_hbm_bytes") or k.get("bytes_accessed") or 0.0) \
            * max(1, k.get("dispatches", 0))
        bound = k.get("bound_refined") or k.get("bound") or "unknown"
        traffic[bound] = traffic.get(bound, 0.0) + b
    all_b = sum(traffic.values())
    if not all_b or traffic.get("hbm_bound", 0.0) < 0.7 * all_b:
        return None
    return _finding(
        "hbm-bound-enable-pcpm",
        "compute phase dominates and hbm-bound kernels carry "
        f"{traffic['hbm_bound'] / all_b:.0%} of device bytes, but "
        "RTPU_PCPM=0 disables the destination-binned kernels",
        "RTPU_PCPM", "unset RTPU_PCPM (auto) or set RTPU_PCPM=1",
        {"compute_fraction": round(compute / total, 3),
         "phase_seconds": {p: round(s, 4) for p, s in split.items()},
         "device_bytes_by_bound": {b: round(v, 0)
                                   for b, v in traffic.items()},
         "queries": _exemplars(queries)})


def rule_fold_stall_workers(sig: dict) -> dict | None:
    """The host fold dominates the phase split while RTPU_FOLD_WORKERS
    is pinned below the cores available — the docs/OBSERVABILITY worked
    walkthrough (mis-set RTPU_FOLD_WORKERS=1 on a 4-core box)."""
    raw = sig.get("env", {}).get("RTPU_FOLD_WORKERS")
    if raw is None:
        return None            # auto-sized: nothing to advise
    try:
        workers = int(raw)
    except ValueError:
        return None
    auto = max(2, (sig.get("cpu_count") or 2) // 2)
    if workers >= auto:
        return None
    queries = sig.get("queries", [])
    split, total = _phase_split(queries)
    fold = split.get("fold", 0.0)
    if len(queries) < 4 or total < 1.0 or fold < 0.5 * total:
        return None
    return _finding(
        "fold-stall-raise-workers",
        f"the host fold is {fold / total:.0%} of attributed time but "
        f"RTPU_FOLD_WORKERS={workers} caps the fold pool below the "
        f"{auto} workers this host would auto-size",
        "RTPU_FOLD_WORKERS",
        f"raise RTPU_FOLD_WORKERS toward {auto} (or unset for auto); "
        "RTPU_PREFETCH_DEPTH queues folds ahead of dispatch",
        {"fold_fraction": round(fold / total, 3),
         "phase_seconds": {p: round(s, 4) for p, s in split.items()},
         "fold_workers": workers, "auto_workers": auto,
         "fold_stall_seconds": sig.get("transfer", {}).get(
             "fold_stall_seconds"),
         "queries": _exemplars(queries)})


def rule_queue_burn_shed(sig: dict) -> dict | None:
    """Queue wait is material while some SLO budget is burning — the
    admission-control signal pair. Recommends shedding the top-cost
    tenant BY NAME with its ledger rows as the shed-this evidence.
    Since the serving scheduler landed (jobs/scheduler.py) this
    recommendation has an actuator: ``RTPU_ADMISSION=1`` sheds exactly
    this tenant's new requests with 429s while the budget burns."""
    bud = sig.get("budget") or {}
    if bud.get("grade") != "burning":
        return None
    queries = sig.get("queries", [])
    waits = sorted(q.get("queue_wait_seconds", 0.0) for q in queries)
    if len(waits) < 4:
        return None
    p99 = waits[min(len(waits) - 1, int(0.99 * len(waits)))]
    if p99 < 0.1:
        return None            # budget burns for another reason
    top = (sig.get("workload_top") or [{}])[0]
    if not top.get("tenant"):
        return None
    burning = [t for t in bud.get("targets", [])
               if t.get("grade") == "burning"]
    return _finding(
        "queue-burn-shed-top-tenant",
        f"queue-wait p99 {p99:.3f}s while "
        f"{[t['algorithm'] for t in burning]} burn their error budget; "
        f"tenant {top['tenant']!r} holds the top attributed cost",
        "RTPU_ADMISSION",
        f"shed tenant {top['tenant']!r}: set RTPU_ADMISSION=1 so the "
        "serving scheduler sheds its new requests with 429s "
        "automatically (jobs/scheduler.py), or kill its jobs via "
        "/KillTask until the fast burn drops below 1",
        {"queue_wait_p99_seconds": round(p99, 4),
         "burning_targets": burning,
         "top_tenant": {
             "tenant": top.get("tenant"),
             "cost_seconds": top.get("cost_seconds"),
             "queue_wait_seconds": top.get("queue_wait_seconds"),
             "queries_total": top.get("queries_total"),
             "top_queries": top.get("top_queries")},
         "queries": _exemplars(queries)},
        severity="warning")


def rule_h2d_stall_depth(sig: dict) -> dict | None:
    """Transfer stalls (staging + wire waits) rival the useful phase
    time — the H2D window is too shallow for this link. Stall and phase
    time come from the SAME recent-query window: the process-lifetime
    transfer totals would keep a day-1 stall backlog firing this rule
    forever on a long-since-healthy server."""
    queries = sig.get("queries", [])
    stall = 0.0
    for q in queries:
        stalls = (q.get("h2d") or {}).get("stall_seconds") or {}
        stall += sum(float(s or 0.0) for s in stalls.values())
    if stall < 2.0:
        return None
    split, total = _phase_split(queries)
    if len(queries) < 4 or stall < 0.3 * max(total, 1e-9):
        return None
    depth = sig.get("env", {}).get("RTPU_TRANSFER_DEPTH")
    tr = sig.get("transfer") or {}
    return _finding(
        "h2d-stall-raise-depth",
        f"{stall:.1f}s of H2D stage/wire stall against {total:.1f}s of "
        "attributed phase time over the recent-query window — the "
        "in-flight upload window is the bottleneck",
        "RTPU_TRANSFER_DEPTH",
        f"raise RTPU_TRANSFER_DEPTH (currently {depth or 'default 2'})",
        {"stall_seconds": round(stall, 4),
         "window_queries": len(queries),
         "process_stall_seconds": tr.get("stall_seconds"),
         "bytes_shipped": tr.get("bytes_shipped"),
         "phase_seconds_total": round(total, 4),
         "queries": _exemplars(queries)})


def rule_fold_cache_thrash(sig: dict) -> dict | None:
    """The cross-request fold cache is evicting while missing more than
    it hits — the bound is too small for the working set."""
    fc = sig.get("fold_cache") or {}
    hits = int(fc.get("hits") or 0)
    misses = int(fc.get("misses") or 0)
    if (int(fc.get("evictions") or 0) < 10 or hits + misses < 20
            or hits >= misses):
        return None
    return _finding(
        "fold-cache-thrash",
        f"fold cache evicted {fc['evictions']} entries with a "
        f"{hits / (hits + misses):.0%} hit rate — the working set no "
        "longer fits RTPU_FOLD_CACHE_MB",
        "RTPU_FOLD_CACHE_MB",
        "raise RTPU_FOLD_CACHE_MB (bytes in use: "
        f"{fc.get('bytes')}/{fc.get('max_bytes')})",
        {"fold_cache": {k: fc.get(k) for k in
                        ("hits", "misses", "evictions", "bytes",
                         "max_bytes", "entries")}})


def rule_watermark_stale(sig: dict) -> dict | None:
    """A live source has held the safe-time fence still past the
    staleness bar — every exact query behind the fence is waiting on it
    (the watermark-lag staleness SLO, PAPERS.md pseudo-streaming)."""
    lag = sig.get("watermark_lag_seconds")
    if lag is None or lag < stale_s():
        return None
    return _finding(
        "watermark-stale",
        f"the global safe time has not advanced for {lag:.1f}s "
        f"(bar: {stale_s():.0f}s) — a live source is stalled",
        "sources",
        "find the stalled source in the watermark snapshot and fix or "
        "finish it; exact-time queries block on this fence",
        {"watermark_lag_seconds": round(lag, 3),
         "watermark_sources": sig.get("watermark_sources"),
         "stale_bar_seconds": stale_s()},
        severity="warning")


# ---- freshness rules: evaluate over the obs/freshness plane ----

#: staged-backlog fraction of the queue bound past which the pipeline
#: writer has lost the race (the saturation oracle the ingest bench
#: reads the same way)
INGEST_BACKLOG_FRAC = 0.8
#: evidence floor before the out-of-order rule may speak
OOO_MIN_EVENTS = 256


def rule_ingest_backlog(sig: dict) -> dict | None:
    """Some staged parse→append queue is pinned near ITS bound — the
    writer has lost the race with the sources and backpressure is
    throttling ingest (the paper's §6.1 saturation oracle, now judged
    continuously instead of only in the bench). Saturation is judged
    per queue: summing backlogs against the max bound would both
    false-fire (two half-full queues) and mask (a small queue behind a
    big bound)."""
    fr = sig.get("freshness") or {}
    queues = fr.get("staged_queues")
    if queues is None:
        # older/synthetic signal shape: fall back to the totals (both
        # keys guarded — the per-queue loop below skips None backlogs)
        queues = ([{"backlog_events": fr.get("backlog_events"),
                    "queue_max_events": fr.get("queue_max_events")}]
                  if fr.get("queue_max_events") else [])
    worst = None
    for q in queues:
        b, qmax = q.get("backlog_events"), q.get("queue_max_events")
        if not qmax or b is None:
            continue
        if worst is None or b / qmax > worst[0] / worst[1]:
            worst = (b, qmax)
    if worst is None or worst[0] < INGEST_BACKLOG_FRAC * worst[1]:
        return None
    backlog, qmax = worst
    srcs = fr.get("sources") or {}
    return _finding(
        "ingest-backlog",
        f"staged ingest backlog at {backlog}/{qmax} events "
        f"({backlog / qmax:.0%} of the queue bound) — the append writer "
        "is saturated and backpressure is throttling every source",
        "RAPHTORY_TPU_INGEST_QUEUE_EVENTS",
        "the writer, not the queue, is the bottleneck: shed or slow "
        "sources, or shard ingest (ingestion/router.py); raising the "
        "queue bound only buys latency, not throughput",
        {"backlog_events": backlog, "queue_max_events": qmax,
         "updates_per_s_by_source": {n: s.get("updates_per_s")
                                     for n, s in srcs.items()},
         "queryable_lag_seconds": fr.get("queryable_lag_seconds")},
        severity="warning")


def rule_ooo_excess(sig: dict) -> dict | None:
    """A source's observed out-of-orderness EXCEEDS its declared
    ``disorder`` bound — the watermark promise ("no event <= w will ever
    be appended") is at risk: an exact view served at the fence may have
    missed late events. The commutative store applies them correctly
    once they land, but 'exact' answers served in between were not."""
    srcs = (sig.get("freshness") or {}).get("sources") or {}
    worst = None
    for name, s in srcs.items():
        if s.get("events", 0) < OOO_MIN_EVENTS:
            continue
        excess = s.get("ooo_max", 0) - max(0, s.get("disorder_bound", 0))
        if excess > 0 and (worst is None or excess > worst[1]):
            worst = (name, excess, s)
    if worst is None:
        return None
    name, excess, s = worst
    return _finding(
        "out-of-order-excess",
        f"source {name!r} emitted events up to {s['ooo_max']} event-time "
        f"units behind its high water, {excess} past its declared "
        f"disorder bound of {s['disorder_bound']} — watermarks promised "
        "completeness they did not have",
        "source.disorder",
        f"raise {name!r}'s declared disorder bound to at least "
        f"{s['ooo_max']} (the watermark then holds back far enough), or "
        "fix the upstream ordering; /freshz carries the full "
        "out-of-order distance histogram",
        {"source": name, "ooo_max": s.get("ooo_max"),
         "declared_disorder": s.get("disorder_bound"),
         "ooo_events": s.get("ooo_events"), "events": s.get("events")},
        severity="warning")


def rule_freshness_burn(sig: dict) -> dict | None:
    """Some RTPU_FRESH_TARGET staleness budget is burning — live
    results are sustainably older than the operator promised. The
    evidence names the stalled ingredient: backlog, queryable lag, or a
    stalled watermark."""
    fr = sig.get("freshness") or {}
    bud = fr.get("budget") or {}
    if bud.get("grade") != "burning":
        return None
    burning = [t for t in bud.get("targets", [])
               if t.get("grade") == "burning"]
    return _finding(
        "freshness-burn",
        f"staleness budgets burning for "
        f"{[t['algorithm'] for t in burning]}: live results are "
        "sustainably staler than RTPU_FRESH_TARGET promises",
        "RTPU_FRESH_TARGET",
        "find the slow ingredient: a stalled source (watermark "
        "snapshot), a saturated staged queue (backlog), or analytics "
        "that can't keep up with ingest (ROADMAP item 3's incremental "
        "live algorithms are the structural fix); or relax the target",
        {"burning_targets": burning,
         "staleness_p99_seconds": fr.get("staleness_p99_seconds"),
         "backlog_events": fr.get("backlog_events"),
         "queryable_lag_seconds": fr.get("queryable_lag_seconds"),
         "watermark_lag_seconds": sig.get("watermark_lag_seconds")},
        severity="warning")


# ---- device rules: evaluate over the obs/device measured plane ----

#: mutual-divergence band for the model-divergence rule: per-kernel
#: measured/predicted ratios spreading wider than this say the cost
#: model RANKS kernels wrongly. Deliberately scale-invariant — the
#: platform peaks are order-of-magnitude anchors, so an absolute
#: measured-vs-predicted gap is expected (and constant-ratio gaps keep
#: the bound classification correct); inconsistent ratios do not.
DIVERGENCE_BAND = 16.0
#: measured evidence floors before the divergence rule may speak
DIVERGENCE_MIN_SAMPLES = 4
DIVERGENCE_MIN_KERNELS = 2


def divergence_band() -> float:
    try:
        v = float(os.environ.get("RTPU_ADVISOR_DIVERGENCE", "")
                  or DIVERGENCE_BAND)
        return max(1.5, v)
    except ValueError:
        return DIVERGENCE_BAND


def rule_model_divergence(sig: dict) -> dict | None:
    """Per-kernel measured-vs-predicted ratios are mutually inconsistent
    past the band — the roofline/traffic model mis-RANKS kernels, so
    ``bound_refined`` (and any controller trusting it) should be
    distrusted until the model is recalibrated against the measured
    table. Scale-invariant on purpose: a constant absolute offset (rough
    platform anchors) never fires this."""
    rows = (sig.get("device") or {}).get("timing") or []
    rated = {}
    for r in rows:
        m = r.get("measured") or {}
        # overhead_bound rows are excluded: when dispatch overhead
        # dominates (small kernels, CPU rigs) the ratio judges the
        # overhead, not the model's ranking — including them would fire
        # this on every healthy host with mixed kernel sizes
        if (m.get("samples", 0) >= DIVERGENCE_MIN_SAMPLES
                and r.get("divergence")
                and r.get("bound_measured") != "overhead_bound"):
            rated[f"{r.get('kernel')}[{r.get('sig')}]"] = \
                float(r["divergence"])
    if len(rated) < DIVERGENCE_MIN_KERNELS:
        return None
    worst = max(rated, key=rated.get)
    best = min(rated, key=rated.get)
    spread = rated[worst] / max(rated[best], 1e-12)
    if spread < divergence_band():
        return None
    return _finding(
        "device-model-divergence",
        f"measured/predicted kernel-seconds ratios spread {spread:.1f}x "
        f"across kernels (band: {divergence_band():.0f}x) — the cost "
        "model mis-ranks kernels; bound_refined is not trustworthy",
        "RTPU_LEDGER_RIDGE",
        "distrust bound_refined until recalibrated: check the measured "
        f"table on /devicez (worst {worst}, best {best}); set "
        "RTPU_LEDGER_RIDGE from measured achieved FLOP/s / bytes/s, or "
        "fix the traffic model for the out-of-band kernel",
        {"divergence_by_kernel": {k: round(v, 3)
                                  for k, v in sorted(rated.items())},
         "spread": round(spread, 3), "band": divergence_band(),
         "worst": worst, "best": best})


def rule_device_pressure(sig: dict) -> dict | None:
    """Device memory near its limit, OR a request-path compile storm
    (new shape sigs recompiling under load faster than they amortise) —
    either way the device runtime is under pressure and a knob exists."""
    dev = sig.get("device") or {}
    mem = dev.get("memory") or {}
    if mem.get("available") and mem.get("bytes_limit"):
        frac = mem["bytes_in_use"] / mem["bytes_limit"]
        if frac >= 0.9:
            return _finding(
                "device-pressure",
                f"device memory at {frac:.0%} of its "
                f"{mem['bytes_limit']} byte limit — the next allocation "
                "spills or OOMs",
                "RTPU_TILE_BUDGET_MB",
                "lower RTPU_TILE_BUDGET_MB (shrinks the columnar edge "
                "tile), raise RTPU_PARTITIONS, or shed resident engines "
                "(see the /devicez resident registry for what is "
                "pinned)",
                {"memory": mem,
                 "resident_bytes": dev.get("resident_bytes")},
                severity="warning")
    comp = dev.get("compile") or {}
    if (comp.get("events_in_window", 0) >= comp.get(
            "threshold", _device.storm_threshold())
            and comp.get("distinct_sigs_in_window", 0)
            >= max(4, int(comp.get("threshold", 16)) // 4)):
        return _finding(
            "device-pressure",
            f"compile storm: {comp['events_in_window']} XLA compiles "
            f"({comp.get('distinct_sigs_in_window')} distinct shape "
            f"sigs) inside the last {comp.get('window_seconds')}s — "
            "request traffic is shape-diverse enough to recompile "
            "faster than programs amortise",
            "RTPU_COMPILE_CACHE_DIR",
            "set RTPU_COMPILE_CACHE_DIR (persistent compile cache), "
            "and bucket/pad request shapes upstream so distinct sigs "
            "collapse; /devicez lists the recent compile events",
            {"compile": comp},
            severity="warning")
    return None


# ---- cluster rules: evaluate over the /clusterz processes dict ----


def _cluster_rows(cluster: dict | None) -> dict:
    procs = (cluster or {}).get("processes") or {}
    return {name: p for name, p in procs.items() if p.get("reachable")}


def rule_cluster_straggler(sig: dict) -> dict | None:
    """One process's watermark lag towers over the rest of the mesh —
    the straggler holding every fence-gated sweep back. Barrier waits
    ride along as corroborating evidence (in a cross-process collective
    the OTHER processes accumulate the wait)."""
    rows = _cluster_rows(sig.get("cluster"))
    lags = {n: float(p["watermark_lag_seconds"]) for n, p in rows.items()
            if p.get("watermark_lag_seconds") is not None}
    if len(lags) < 2:
        return None
    worst = max(lags, key=lags.get)
    others = [v for n, v in lags.items() if n != worst]
    if lags[worst] < stale_s() or \
            lags[worst] < 3.0 * (max(others) + 1.0):
        return None
    waits = {n: (p.get("collectives") or {}).get("barrier_wait_seconds")
             for n, p in rows.items()}
    return _finding(
        "cluster-straggler",
        f"{worst} lags the mesh: watermark stalled for "
        f"{lags[worst]:.1f}s while the rest sit at "
        f"{max(others):.1f}s or less",
        "cluster",
        f"inspect {worst} (its /statusz watermark sources and "
        "/profilez); a mesh sweep runs at the pace of this process",
        {"process": worst,
         "process_index": rows[worst].get("process_index"),
         "watermark_lag_by_process": {n: round(v, 3)
                                      for n, v in lags.items()},
         "barrier_wait_by_process": waits},
        severity="warning")


def rule_shard_skew(sig: dict) -> dict | None:
    """A shard's row count towers over the mean — power-law skew the
    static partition cannot balance; the sparse-collective route
    (PAPERS.md Sparse Allreduce) exists for exactly this shape."""
    rows = _cluster_rows(sig.get("cluster"))
    worst = None
    for name, p in rows.items():
        skew = (p.get("collectives") or {}).get("skew") or {}
        for kind, val in skew.items():
            # shard_skew() publishes {per_shard, max, mean, skew} rows;
            # tolerate a bare ratio too (synthetic test signals)
            s = val.get("skew") if isinstance(val, dict) else val
            if s is None:
                continue
            if worst is None or float(s) > worst[2]:
                worst = (name, kind, float(s))
    if worst is None or worst[2] < 4.0:
        return None
    name, kind, val = worst
    # route evidence: if the chooser is already taking the sparse route
    # (or frontier densities say it should), say so — the remediation
    # differs between "re-partition" and "let the sparse route absorb it"
    route_counts: dict[str, int] = {}
    density: dict[str, float] = {}
    for n, p in rows.items():
        coll = p.get("collectives") or {}
        for key, cnt in ((coll.get("route_table") or {}).get("counts")
                         or {}).items():
            route_counts[key] = route_counts.get(key, 0) + int(cnt)
        for key, d in (coll.get("frontier_density") or {}).items():
            density[key] = max(density.get(key, 0.0), float(d))
    sparse_taken = any(k.endswith("/sparse") for k in route_counts)
    sparse_fits = any(d < 1.0 / 3.0 for d in density.values())
    if sparse_taken:
        fix = ("the sparse frontier route is already absorbing the skew "
               "(docs/COMM.md) — if bytes stay high, re-balance with "
               "RTPU_PARTITIONS")
    elif sparse_fits:
        fix = ("frontier density is under the sparse crossover — set "
               "RTPU_COMM_ROUTE=auto (or =sparse) so min-merge sweeps "
               "exchange compacted frontiers instead of dense state "
               "(docs/COMM.md), or re-balance with RTPU_PARTITIONS")
    else:
        fix = ("re-balance: raise RTPU_PARTITIONS; dense frontiers keep "
               "the sparse route out of crossover here (docs/COMM.md)")
    return _finding(
        "shard-skew",
        f"{name} reports {kind} partition skew {val:.1f}x (max/mean "
        "per-shard rows) — the hot shard serializes every superstep",
        "RTPU_COMM_ROUTE",
        fix,
        {"process": name, "kind": kind, "skew": round(val, 3),
         "route_counts": route_counts,
         "frontier_density": {k: round(v, 4) for k, v in density.items()},
         "skew_by_process": {n: (p.get("collectives") or {}).get("skew")
                             for n, p in rows.items()}})


#: the catalogue: (rule_id, fn, reads, one-line description) — /advisez
#: lists it and docs/OBSERVABILITY.md "Advisor" documents it verbatim
RULES = (
    ("hbm-bound-enable-pcpm", rule_hbm_bound_pcpm,
     "kernel roofline classes + phase split",
     "hbm-bound kernels dominate compute with RTPU_PCPM=0"),
    ("fold-stall-raise-workers", rule_fold_stall_workers,
     "phase split + fold-pool sizing",
     "host fold dominates while RTPU_FOLD_WORKERS is pinned low"),
    ("queue-burn-shed-top-tenant", rule_queue_burn_shed,
     "queue-wait p99 + error budgets + workload accounts",
     "queue wait burns budget; names the top-cost tenant to shed"),
    ("h2d-stall-raise-depth", rule_h2d_stall_depth,
     "per-query H2D stalls + phase split (same recent window)",
     "H2D stage/wire stalls rival useful phase time"),
    ("fold-cache-thrash", rule_fold_cache_thrash,
     "fold-cache hit/miss/eviction stats",
     "fold cache evicts more than it serves"),
    ("watermark-stale", rule_watermark_stale,
     "watermark lag + source snapshot",
     "the safe-time fence stopped advancing past the staleness bar"),
    ("ingest-backlog", rule_ingest_backlog,
     "/freshz staged backlog vs the queue bound",
     "the parse→append queue is pinned: the writer lost the race"),
    ("out-of-order-excess", rule_ooo_excess,
     "/freshz per-source out-of-orderness vs the declared disorder",
     "observed disorder exceeds the bound the watermark promise rests "
     "on"),
    ("freshness-burn", rule_freshness_burn,
     "RTPU_FRESH_TARGET staleness budgets + /freshz evidence",
     "live results sustainably staler than the operator promised"),
    ("device-model-divergence", rule_model_divergence,
     "/devicez measured kernel table (sampled timings vs model)",
     "measured/predicted ratios mutually inconsistent past the band — "
     "distrust bound_refined"),
    ("device-pressure", rule_device_pressure,
     "/devicez memory snapshot + compile-storm window",
     "device memory near its limit, or a request-path compile storm"),
    ("cluster-straggler", rule_cluster_straggler,
     "/clusterz per-process watermark lag + barrier waits",
     "one process's lag towers over the mesh"),
    ("shard-skew", rule_shard_skew,
     "/clusterz per-process partition skew",
     "a hot shard serializes the collective supersteps"),
)


def evaluate_rules(signals: dict) -> list[dict]:
    """Run every rule over ``signals``; a crashing rule becomes zero
    findings (the advisor must never take a tick down), surfaced in the
    signals' ``rule_errors`` for the /advisez payload."""
    findings = []
    for rule_id, fn, _, _ in RULES:
        try:
            f = fn(signals)
        except Exception as e:   # noqa: BLE001 — advice must not crash
            signals.setdefault("rule_errors", []).append(
                f"{rule_id}: {type(e).__name__}: {e}"[:200])
            continue
        if f is not None:
            findings.append(f)
    return findings


def gather_signals(manager=None, cluster: dict | None = None) -> dict:
    """Assemble the signals dict from the live surfaces — every read
    goes through the owning surface's own lock; nothing here holds the
    advisor's. ``cluster`` is an already-fetched /clusterz document
    (the caller does the network I/O — never under a lock)."""
    sig: dict = {
        "queries": recent_query_rows(QUERY_WINDOW),
        "kernels": _ledger.REGISTRY.snapshot(),
        "budget": _budget.BUDGET.evaluate(),
        "workload_top": _workload.WORKLOAD.top_by_cost(3),
        "cpu_count": os.cpu_count(),
        "env": {k: os.environ.get(k) for k in
                ("RTPU_PCPM", "RTPU_FOLD_WORKERS", "RTPU_PREFETCH_DEPTH",
                 "RTPU_TRANSFER_DEPTH", "RTPU_FOLD_CACHE_MB")},
        "cluster": cluster,
    }
    try:
        # the measured device plane (obs/device.py): sampled kernel
        # timings joined with estimates, memory snapshot, compile storm
        sig["device"] = _device.advisor_signals()
        sig["device"]["resident_bytes"] = \
            _device.RESIDENT.snapshot()["total_bytes"]
    except Exception:
        sig["device"] = {}
    try:
        from ..utils.transfer import shared_engine

        sig["transfer"] = shared_engine().stats.totals()
    except Exception:
        sig["transfer"] = {}
    try:
        from ..core.sweep import fold_cache

        cache = fold_cache()
        sig["fold_cache"] = cache.stats() if cache is not None else {}
    except Exception:
        sig["fold_cache"] = {}
    try:
        # the freshness plane (obs/freshness.py): per-source stream
        # telemetry, staged backlog, staleness budget — what the
        # ingest-backlog / out-of-order-excess / freshness-burn rules
        # read
        sig["freshness"] = _freshness.FRESH.advisor_signals()
    except Exception:
        sig["freshness"] = {}
    graph = getattr(manager, "graph", None) if manager is not None else None
    if graph is not None:
        try:
            # lag_state separates idle (registered, no traffic — 0.0,
            # never an alarm) from a genuinely stalled active fence
            state, lag = graph.watermarks.lag_state()
            sig["watermark_lag_seconds"] = lag
            sig["watermark_lag_state"] = state
            sig["watermark_sources"] = {
                k: int(v) for k, v in graph.watermarks.snapshot().items()}
        except Exception:
            pass
    return sig


class Advisor:
    """Process-wide periodic rule evaluator. Last-tick findings and a
    bounded history under one lock; gathering, rule evaluation, metric
    mirroring and trace instants all happen OUTSIDE it (RT009)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._findings: list[dict] = []
        self._rule_errors: list[str] = []
        self._history: deque = deque(maxlen=HISTORY)
        self._last_rule_ids: set = set()
        #: unix time of the last pass that saw /clusterz data — gates
        #: how long local ticks keep carrying its cluster findings
        self._cluster_unix = 0.0
        self._manager_ref = None
        self._thread: threading.Thread | None = None
        # per-generation stop event (obs/slo.SeriesRing pattern): a stop
        # racing a concurrent start must only affect its own generation
        self._stop = threading.Event()
        self.ticks = 0
        self.last_tick_unix = 0.0
        self._san_tracker = _san_track("advisor_findings")

    def attach_manager(self, manager) -> None:
        """Weakly attach the serving AnalysisManager — the watermark-lag
        and queue signals come from its graph; the advisor must not pin
        a dead manager (the registry is process-wide)."""
        import weakref

        with self._lock:
            self._manager_ref = weakref.ref(manager)

    def _manager(self):
        with self._lock:
            ref = self._manager_ref
        return ref() if ref is not None else None

    # ---- evaluation ----

    def tick(self, cluster: dict | None = None) -> list[dict]:
        """One evaluation pass: gather → rules → publish. Returns the
        findings. Safe from any thread; never raises."""
        signals = gather_signals(self._manager(), cluster=cluster)
        findings = evaluate_rules(signals)
        now = time.time()
        # a federated pass only counts as mesh EVIDENCE when the scrape
        # actually reached ≥ 2 processes — a transient all-peers-down
        # scrape renders reachable:false everywhere, which must not
        # clear a carried straggler finding (the cluster rules judged
        # nothing) or the finding flaps across every peer outage
        evidential = (cluster is not None
                      and len(_cluster_rows(cluster)) >= 2)
        with self._lock:
            _san_note(self._san_tracker, True)
            if evidential:
                self._cluster_unix = now
            elif now - self._cluster_unix <= CLUSTER_RETAIN_S:
                # no mesh evidence this pass: carry the last evidential
                # pass's cluster findings (bounded by age) — only a pass
                # that saw the mesh may clear or refresh them
                present = {f["rule_id"] for f in findings}
                findings = findings + [f for f in self._findings
                                       if f["rule_id"] in CLUSTER_RULES
                                       and f["rule_id"] not in present]
            new_ids = {f["rule_id"] for f in findings}
            prev_ids = self._last_rule_ids
            fresh = [f for f in findings if f["rule_id"] not in prev_ids]
            self._last_rule_ids = new_ids
            self._findings = findings
            # a crashed rule must look DIFFERENT from a quiet one: the
            # errors ride on /advisez and the /statusz block
            self._rule_errors = signals.get("rule_errors", [])
            self._history.extend(fresh)
            self.ticks += 1
            self.last_tick_unix = now
        m = _metrics()
        if m is not None:
            m.advisor_ticks.inc()
            counts: dict[str, int] = {}
            for f in findings:
                counts[f["rule_id"]] = counts.get(f["rule_id"], 0) + 1
            for rule_id, _, _, _ in RULES:   # zero cleared rules too
                m.advisor_findings.labels(rule_id).set(
                    counts.get(rule_id, 0))
        for f in fresh:                      # instants outside the lock
            TRACER.instant("advisor.finding", rule_id=f["rule_id"],
                           knob=f["knob"], severity=f["severity"],
                           summary=f["summary"])
            # durable journal: FRESH findings only (a standing finding
            # re-journaled every tick would be noise, not evidence)
            if _journal.enabled():
                _journal.emit("advice", f)
        return findings

    # ---- periodic thread ----

    def _loop(self, stop: threading.Event) -> None:
        while not stop.wait(interval_s()):
            if enabled():
                self.tick()

    def start(self) -> "Advisor":
        """Start the periodic evaluator thread (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, args=(stop,), name="advisor",
                daemon=True)
            self._thread.start()
        return self

    def maybe_start(self) -> "Advisor":
        return self.start() if enabled() else self

    def stop(self) -> None:
        with self._lock:
            t, self._thread = self._thread, None
            self._stop.set()
        if t is not None:
            t.join(timeout=5.0)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # ---- export ----

    def findings(self) -> list[dict]:
        with self._lock:
            _san_note(self._san_tracker, False)
            return [dict(f) for f in self._findings]

    def status_block(self) -> dict:
        """The compact ``advisor`` block /statusz embeds (what /clusterz
        federates): counts + rule ids only, never the evidence bodies."""
        with self._lock:
            _san_note(self._san_tracker, False)
            return {"enabled": enabled(), "running": self.running,
                    "ticks": self.ticks,
                    "last_tick_unix": round(self.last_tick_unix, 3),
                    "findings": len(self._findings),
                    "rule_ids": sorted({f["rule_id"]
                                        for f in self._findings}),
                    "rule_errors": list(self._rule_errors)}

    def advisez(self, cluster: dict | None = None) -> dict:
        """The full ``/advisez`` document. When ``cluster`` (a fetched
        /clusterz doc) is supplied the tick evaluates the mesh rules
        too — one process advising the whole mesh."""
        findings = self.tick(cluster=cluster)
        with self._lock:
            history = [dict(f) for f in self._history]
            rule_errors = list(self._rule_errors)
            ticks = self.ticks
        out = {
            "enabled": enabled(), "running": self.running,
            "interval_seconds": interval_s(), "ticks": ticks,
            "findings": findings,
            "rule_errors": rule_errors,
            "history": history,
            "rules": [{"rule_id": rid, "reads": reads, "fires_when": desc}
                      for rid, _, reads, desc in RULES],
            "read_only": ("findings recommend; nothing here mutates a "
                          "knob — the adaptive runtime (ROADMAP 4) "
                          "closes the loop"),
        }
        if cluster is not None:
            out["cluster"] = {
                "processes_reachable": cluster.get("processes_reachable"),
                "peers_configured": cluster.get("peers_configured"),
            }
        return out

    def clear(self) -> None:
        with self._lock:
            self._findings = []
            self._rule_errors = []
            self._history.clear()
            self._last_rule_ids = set()
            self._cluster_unix = 0.0
            self.ticks = 0
            self.last_tick_unix = 0.0
        with _QUERIES_LOCK:
            _QUERIES.clear()


#: the process singleton /advisez and the RestServer tick through
ADVISOR = Advisor()
