"""Prometheus metrics registry + scrape server.

Signal parity with the reference's Kamon wiring (SURVEY §5.1): spout
send-rate (``SpoutTrait.scala:136-141``), router throughput
(``RouterManager.scala:118-122``), storage sizes and update rates
(``WriterLogger.scala:21-30,62-84``), archivist cycle timings + heap gauge
(``Archivist.scala:86-97,132``), plus the BSP/job signals the reference
exposes only as log lines (viewTime per job). Scrape endpoint defaults to
the reference's :11600.

All metrics live in one module-level ``Metrics`` bundle on a dedicated
``CollectorRegistry`` so repeated imports in tests never hit prometheus's
duplicate-timeseries guard.
"""

from __future__ import annotations

import resource
import sys
import threading

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    start_http_server,
)

DEFAULT_PORT = 11600  # reference's embedded Prometheus scrape port


class Metrics:
    def __init__(self) -> None:
        self.registry = CollectorRegistry()
        r = self.registry
        # ingestion (spout/router/writer signals)
        self.events_ingested = Counter(
            "raphtory_events_ingested_total",
            "Graph update events appended to the log", ["source"], registry=r)
        self.parse_errors = Counter(
            "raphtory_parse_errors_total",
            "Fatal source errors (a source thread died)", ["source"],
            registry=r)
        self.records_dropped = Counter(
            "raphtory_records_dropped_total",
            "Records a parser produced no updates for (malformed or "
            "filtered)", ["source"], registry=r)
        self.watermark = Gauge(
            "raphtory_watermark_safe_time",
            "Safe event time promised by all live sources", registry=r)
        self.ingest_backlog = Gauge(
            "raphtory_ingest_backlog_events",
            "Events parsed but not yet appended to the log (bounded-"
            "mailbox depth; the WriterLogger queue-size analogue)",
            registry=r)
        # freshness plane (obs/freshness.py): per-source stream
        # telemetry + ingest-to-queryable + live-result staleness.
        # Source label cardinality is bounded by the deployment's source
        # set (same contract as events_ingested); algorithm by the
        # registry + the freshness MAX_ALGOS cap.
        self.ingest_batches = Counter(
            "raphtory_ingest_batches_total",
            "Sink batches that arrived from a source", ["source"],
            registry=r)
        self.ingest_batch_events = Histogram(
            "raphtory_ingest_batch_events",
            "Events per sink batch (the vectorisation amortisation "
            "factor of the ingest hot path)",
            buckets=(1, 8, 64, 512, 4096, 32768, 262144, float("inf")),
            registry=r)
        self.ingest_ooo_events = Counter(
            "raphtory_ingest_out_of_order_events_total",
            "Events that arrived with event time behind their source's "
            "high-water mark (safe under the commutative store; the "
            "distance distribution lives on /freshz)", ["source"],
            registry=r)
        self.ingest_tombstones = Counter(
            "raphtory_ingest_tombstone_events_total",
            "Vertex/edge DELETE events ingested (the tombstone half of "
            "the op-type mix)", ["source"], registry=r)
        self.freshness_queryable = Histogram(
            "raphtory_freshness_queryable_seconds",
            "Ingest-to-queryable latency: sink-batch arrival until the "
            "global safe time covered the batch's max event time "
            "(trace-ID exemplars on /freshz)", ["source"],
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                     30.0, 60.0, 300.0, float("inf")), registry=r)
        self.freshness_staleness = Histogram(
            "raphtory_freshness_staleness_seconds",
            "Live-query result staleness: wall seconds since the "
            "result's watermark stopped being the ingest head",
            ["algorithm"],
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                     30.0, 60.0, 300.0, float("inf")), registry=r)
        self.freshness_burn_rate = Gauge(
            "raphtory_freshness_burn_rate",
            "Staleness error-budget burn rate per RTPU_FRESH_TARGET "
            "and window (>1 in both windows = burning; grades "
            "/healthz)", ["algorithm", "window"], registry=r)
        self.freshness_pending = Gauge(
            "raphtory_freshness_pending_batches",
            "Sink batches appended but not yet covered by the global "
            "safe time (the not-yet-queryable backlog)", registry=r)
        self.freshness_pending.set_function(_freshness_pending)
        # storage (WriterLogger gauges)
        self.log_events = Gauge(
            "raphtory_log_events", "Rows in the event log", registry=r)
        self.view_vertices = Gauge(
            "raphtory_view_vertices",
            "Vertices alive in the most recent view", registry=r)
        self.view_edges = Gauge(
            "raphtory_view_edges",
            "Edges alive in the most recent view", registry=r)
        self.snapshot_build_seconds = Histogram(
            "raphtory_snapshot_build_seconds",
            "Event log → device-ready view fold time", registry=r)
        # analysis (AnalysisTask/ReaderWorker signals)
        self.jobs_started = Counter(
            "raphtory_jobs_started_total", "Analysis jobs submitted",
            ["kind"], registry=r)
        self.jobs_completed = Counter(
            "raphtory_jobs_completed_total", "Analysis jobs finished",
            ["status"], registry=r)
        self.views_computed = Counter(
            "raphtory_views_computed_total",
            "Windowed views evaluated by the BSP engine", registry=r)
        self.view_seconds = Histogram(
            "raphtory_view_seconds",
            "Per-view end-to-end time (the reference's viewTime)",
            registry=r)
        self.supersteps = Counter(
            "raphtory_supersteps_total",
            "BSP supersteps executed on device", registry=r)
        # live epoch engine (jobs/live.LiveEpochState): bounded labels —
        # algorithm is capped by the freshness registry's MAX_ALGOS and
        # mode is a closed five-value set
        self.live_epochs = Counter(
            "raphtory_live_epochs_total",
            "Live-subscription epochs served, by algorithm and epoch "
            "mode (incremental|rebase|resweep|skipped|resync)",
            ["algorithm", "mode"], registry=r)
        # transfer pipeline (utils/transfer.TransferEngine) — the H2D link
        # is the term that bounds a real sweep on a tunnelled accelerator,
        # so the pipeline's stalls are first-class signals
        self.h2d_bytes = Counter(
            "raphtory_h2d_bytes_total",
            "Host→device bytes shipped through the transfer engine",
            registry=r)
        self.h2d_slices = Counter(
            "raphtory_h2d_slices_total",
            "Chunked upload slices issued", registry=r)
        self.h2d_retries = Counter(
            "raphtory_h2d_retries_total",
            "Per-slice transport retries (UNAVAILABLE-class errors)",
            registry=r)
        self.h2d_stall_seconds = Counter(
            "raphtory_h2d_stall_seconds_total",
            "Seconds a transfer-pipeline stage spent stalled (stage=host "
            "staging copy, wire=blocked on an in-flight put, fold=sweep "
            "waiting on the hop-lookahead host fold)", ["stage"],
            registry=r)
        self.h2d_inflight_depth = Gauge(
            "raphtory_h2d_inflight_depth",
            "High-water in-flight device_put window depth", registry=r)
        self.fold_seconds = Histogram(
            "raphtory_fold_seconds",
            "Host fold wall seconds per chunk-group fold (mode=serial is "
            "the shared-builder pipeline lane, mode=parallel a forked "
            "per-chunk fold on the sized RTPU_FOLD_WORKERS pool)",
            ["mode"], registry=r)
        self.fold_cache_hits = Counter(
            "raphtory_fold_cache_hits_total",
            "Cross-request fold-cache hits (payloads + checkpoint seeds)",
            registry=r)
        self.fold_cache_misses = Counter(
            "raphtory_fold_cache_misses_total",
            "Cross-request fold-cache misses", registry=r)
        self.fold_cache_evictions = Counter(
            "raphtory_fold_cache_evictions_total",
            "Fold-cache LRU evictions under the RTPU_FOLD_CACHE_MB bound",
            registry=r)
        self.fold_cache_bytes = Gauge(
            "raphtory_fold_cache_bytes",
            "Bytes currently accounted to the fold cache", registry=r)
        # collective telemetry (parallel/sharded.py, parallel/columns.py):
        # what the cross-shard exchange MOVED per route — the evidence the
        # sparse third collective route (ROADMAP item 3, "Sparse
        # Allreduce" / "Node Aware SpMV") will be tuned against
        self.collective_seconds = Counter(
            "raphtory_collective_seconds_total",
            "Wall seconds inside the collective window (dispatch to "
            "local program completion) by comm route and edge direction",
            ["route", "direction"], registry=r)
        self.collective_bytes = Counter(
            "raphtory_collective_bytes_total",
            "Estimated cross-shard bytes moved by superstep exchanges "
            "(halo slot pages or all_gather replication, summed over "
            "devices and supersteps)", ["route", "direction"], registry=r)
        self.collective_rows = Counter(
            "raphtory_collective_rows_total",
            "Cross-shard state rows moved by superstep exchanges",
            ["route", "direction"], registry=r)
        self.collective_barrier_wait = Counter(
            "raphtory_collective_barrier_wait_seconds_total",
            "Host seconds between local program completion and the "
            "cross-process result allgather completing — the per-process "
            "straggler-wait signal", ["route"], registry=r)
        self.route_decisions = Counter(
            "raphtory_comm_route_decisions_total",
            "Comm-route chooser verdicts per mesh dispatch "
            "(parallel/sharded.py: halo | all_gather | sparse) — a route "
            "flip under load shows as the sparse series taking over",
            ["algorithm", "route"], registry=r)
        self.partition_skew = Gauge(
            "raphtory_partition_skew",
            "Max/mean per-shard row-count ratio of the latest partition "
            "build (kind=edges_dst|edges_src|halo_dst|halo_src) — 1.0 is "
            "perfectly balanced, power-law graphs drift high",
            ["kind"], registry=r)
        self.shard_rows = Histogram(
            "raphtory_shard_rows",
            "Per-shard row counts observed at partition build time "
            "(one observation per shard per build)",
            ["kind"],
            buckets=(1e2, 1e3, 1e4, 1e5, 1e6, 1e7, 1e8, float("inf")),
            registry=r)
        # cluster control plane (cluster/watchdog.py)
        self.cluster_members = Gauge(
            "raphtory_cluster_members",
            "Live watchdog members by role (joined, beating, not downed)",
            ["role"], registry=r)
        self.cluster_stale = Gauge(
            "raphtory_cluster_stale_members",
            "Members past the staleness bar but not yet auto-downed",
            registry=r)
        # watermark lag (ingestion/watermark.py wires the callable — this
        # module must not import it: watermark imports METRICS from here)
        self.watermark_lag = Gauge(
            "raphtory_watermark_lag_seconds",
            "Seconds since this process's global safe time last advanced "
            "(0 while the fence is moving; grows when a source stalls)",
            registry=r)
        self.sweep_phase_seconds = Histogram(
            "raphtory_sweep_phase_seconds",
            "Per-sweep wall seconds by pipeline phase (fold=host delta "
            "fold incl. worker time, stage=host staging copies, ship=wire/"
            "in-flight waits, compute=dispatch-loop residual incl. device "
            "compute) — the phase breakdown the span tracer also attaches "
            "to every sweep span", ["phase"], registry=r)
        # per-query resource ledger (obs/ledger.py): what a query COST,
        # by algorithm — the accounting admission control and the PCPM
        # kernel work size themselves from
        # SLO surface (obs/slo.py): per-request end-to-end latency by
        # algorithm and phase, bucketed on the SAME grid as the stdlib
        # exemplar histograms so a Prometheus p99 and an /slz exemplar
        # point at the same bucket; plus the queue-wait distribution the
        # admission-control bench will be judged with (the ledger has
        # measured queue_wait since PR 6 but only as a per-query scalar)
        from .slo import slo_buckets as _slo_buckets

        self.request_seconds = Histogram(
            "raphtory_request_seconds",
            "Per-request latency by ledger phase (phase=e2e is wall "
            "submit->done; tail buckets keep trace-ID exemplars at /slz)",
            ["algorithm", "phase"],
            buckets=(*_slo_buckets(), float("inf")), registry=r)
        self.job_queue_wait_seconds = Histogram(
            "raphtory_job_queue_wait_seconds",
            "Seconds between job submission and its thread running "
            "(thread-spawn latency today; real admission queueing when "
            "the serving scheduler lands)",
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0,
                     float("inf")), registry=r)
        self.query_cost_seconds = Histogram(
            "raphtory_query_cost_seconds",
            "Per-query wall seconds by ledger phase (fold/stage/ship/"
            "compute from the sweep engines, device_wait/emit/other from "
            "the jobs layer, queue_wait before the job thread ran)",
            ["algorithm", "phase"], registry=r)
        self.query_cost_queries = Counter(
            "raphtory_query_cost_queries_total",
            "Queries whose ledger was closed", ["algorithm", "bound"],
            registry=r)
        self.query_cost_est_flops = Counter(
            "raphtory_query_cost_est_device_flops_total",
            "Estimated device FLOPs attributed to queries (XLA "
            "cost_analysis per compiled kernel x dispatch count)",
            ["algorithm"], registry=r)
        self.query_cost_est_hbm_bytes = Counter(
            "raphtory_query_cost_est_hbm_bytes_total",
            "Estimated device bytes accessed attributed to queries (XLA "
            "cost_analysis bytes-accessed x dispatch count)",
            ["algorithm"], registry=r)
        self.query_cost_h2d_bytes = Counter(
            "raphtory_query_cost_h2d_bytes_total",
            "Host->device bytes attributed to queries (TransferEngine "
            "deltas per sweep)", ["algorithm"], registry=r)
        self.query_cost_dcn_bytes = Counter(
            "raphtory_query_cost_dcn_bytes_total",
            "Estimated cross-shard collective bytes attributed to "
            "queries (parallel/sharded.py exchange accounting) — the "
            "DCN/ICI column next to est HBM bytes in the ledger",
            ["algorithm"], registry=r)
        # per-tenant workload accounts (obs/workload.py): WHO spent the
        # budget. Label cardinality is PROVABLY bounded — tenant names
        # pass normalize_tenant (malformed -> "invalid") and the
        # RTPU_TENANT_CAP account cap (overflow -> "other") before ever
        # reaching .labels()
        self.tenant_queries = Counter(
            "raphtory_tenant_queries_total",
            "Completed jobs attributed to a tenant account",
            ["tenant", "status"], registry=r)
        self.tenant_cost_seconds = Counter(
            "raphtory_tenant_cost_seconds_total",
            "Attributed cost seconds by tenant and ledger phase "
            "(queue_wait included as its own phase)",
            ["tenant", "phase"], registry=r)
        self.tenant_est_hbm_bytes = Counter(
            "raphtory_tenant_est_hbm_bytes_total",
            "Estimated device HBM bytes attributed to a tenant "
            "(locality-aware per-dispatch traffic estimate)",
            ["tenant"], registry=r)
        self.tenant_dcn_bytes = Counter(
            "raphtory_tenant_dcn_bytes_total",
            "Estimated cross-shard collective bytes attributed to a "
            "tenant", ["tenant"], registry=r)
        # SLO error budgets (obs/budget.py): operator RTPU_SLO_TARGET
        # targets judged as multi-window burn rates; label cardinality
        # bounded by the parsed-target cap
        self.slo_burn_rate = Gauge(
            "raphtory_slo_burn_rate",
            "Error-budget burn rate per target and window (1.0 = "
            "spending exactly the allowed budget; >1 in both windows = "
            "burning)", ["algorithm", "window"], registry=r)
        self.slo_budget_remaining = Gauge(
            "raphtory_slo_error_budget_remaining",
            "Fraction of the error budget left over this process's "
            "lifetime (1.0 = untouched, 0 = exhausted, negative = "
            "overspent)", ["algorithm"], registry=r)
        # serving scheduler (jobs/scheduler.py): cross-request
        # coalescing + ledger-priced admission control + deadlines.
        # Label cardinality is bounded: family comes from the fixed
        # columnar-engine set, reason from the fixed shed-rule set.
        self.scheduler_batches = Counter(
            "raphtory_scheduler_batches_total",
            "Coalesced cross-request batches dispatched by the serving "
            "scheduler, by algorithm family", ["family"], registry=r)
        self.scheduler_coalesced_jobs = Histogram(
            "raphtory_scheduler_coalesced_jobs",
            "Jobs per coalesced batch dispatch (the amortisation "
            "factor)", buckets=(1, 2, 4, 8, 16, 32, 64, 128,
                                float("inf")), registry=r)
        self.scheduler_shed = Counter(
            "raphtory_scheduler_shed_total",
            "Requests shed by admission control (HTTP 429), by reason "
            "(queue_full, tenant_share, shed_top_tenant, over_budget, "
            "deadline_infeasible)", ["reason"], registry=r)
        self.scheduler_deadline_expired = Counter(
            "raphtory_scheduler_deadline_expired_total",
            "Jobs whose deadline_ms expired before dispatch (failed "
            "fast; never reached the device)", registry=r)
        self.scheduler_queue_depth = Gauge(
            "raphtory_scheduler_queue_depth",
            "Jobs currently waiting in serving-scheduler collect "
            "windows, summed over live schedulers", registry=r)
        self.scheduler_queue_depth.set_function(_scheduler_queue_depth)
        self.scheduler_backlog_seconds = Gauge(
            "raphtory_scheduler_backlog_seconds",
            "Ledger-priced cost seconds admitted but not yet completed "
            "(the admission-control pressure signal)", registry=r)
        self.scheduler_backlog_seconds.set_function(_scheduler_backlog)
        # resilience plane (resilience/): retry decisions, breaker
        # states, degraded serves — see docs/RESILIENCE.md
        self.retry_attempts = Counter(
            "raphtory_retry_attempts_total",
            "Retry-policy decisions, by failpoint site and outcome "
            "(retry, fatal, exhausted, deadline). Nothing increments on "
            "the zero-failure hot path", ["site", "outcome"], registry=r)
        self.breaker_state = Gauge(
            "raphtory_breaker_state",
            "Per-peer circuit-breaker state: 0 closed, 1 half-open, "
            "2 open", ["peer"], registry=r)
        self.degraded_results = Counter(
            "raphtory_degraded_results_total",
            "Queries answered with PARTIAL results under the degraded-"
            "serving contract (degraded:true + coveredTime), by reason "
            "(deadline, retry_budget)", ["reason"], registry=r)
        # advisor plane (obs/advisor.py): strictly read-only findings
        self.advisor_findings = Gauge(
            "raphtory_advisor_findings",
            "Findings emitted by the last advisor tick, by rule",
            ["rule"], registry=r)
        self.advisor_ticks = Counter(
            "raphtory_advisor_ticks_total",
            "Advisor rule-evaluation passes", registry=r)
        # device runtime plane (obs/device.py): the MEASURED half of the
        # ledger — sampled timed-dispatch latencies, observed XLA
        # compiles (the compile-storm evidence), and device memory
        self.device_kernel_seconds = Histogram(
            "raphtory_device_kernel_seconds",
            "Measured wall seconds of sampled timed dispatches "
            "(RTPU_DEVICE_TIMING; includes dispatch overhead and the "
            "sync's pipeline drain)", ["kernel"],
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0,
                     5.0, 30.0, float("inf")), registry=r)
        self.compiles = Counter(
            "raphtory_compiles_total",
            "XLA compiles observed at the kernel registry's "
            "lower().compile() sites (one per new (kernel, shape-sig))",
            ["kernel"], registry=r)
        self.compile_seconds = Counter(
            "raphtory_compile_seconds_total",
            "Seconds inside observed XLA compiles, by kernel",
            ["kernel"], registry=r)
        self.device_bytes_in_use = Gauge(
            "raphtory_device_bytes_in_use",
            "Device bytes in use (memory_stats of device 0; 0 when the "
            "backend exposes no memory counters — /devicez reports the "
            "unavailable degrade explicitly)", registry=r)
        self.device_bytes_in_use.set_function(_device_bytes_in_use)
        # memory governor (Archivist signals)
        self.compactions = Counter(
            "raphtory_compactions_total",
            "History compaction cycles", ["kind"], registry=r)
        self.compaction_seconds = Histogram(
            "raphtory_compaction_seconds",
            "Compression/archive cycle time", registry=r)
        self.heap_bytes = Gauge(
            "raphtory_host_rss_bytes",
            "Host resident set size (the reference's heap gauge)",
            registry=r)
        self.heap_bytes.set_function(_rss_bytes)


def _scheduler_queue_depth() -> float:
    """Scrape-time gauge callback over the live serving schedulers —
    must never raise; lazy import keeps metrics importable without the
    jobs layer."""
    try:
        from ..jobs.scheduler import total_queue_depth

        return total_queue_depth()
    except Exception:
        return 0.0


def _scheduler_backlog() -> float:
    try:
        from ..jobs.scheduler import total_backlog_seconds

        return total_backlog_seconds()
    except Exception:
        return 0.0


def _device_bytes_in_use() -> float:
    """Scrape-time device-memory gauge callback — must never raise (a
    prometheus scrape is no place for a backend error), so unavailable
    degrades to 0.0; lazy import keeps metrics importable without the
    device plane."""
    try:
        from .device import gauge_bytes_in_use

        return gauge_bytes_in_use()
    except Exception:
        return 0.0


def _freshness_pending() -> float:
    """Scrape-time not-yet-queryable batch count — never raises; lazy
    import keeps metrics importable without the freshness plane."""
    try:
        from .freshness import FRESH

        return float(FRESH.pending_batches())
    except Exception:
        return 0.0


def _rss_bytes() -> float:
    """Current RSS (so compaction wins are visible), not the lifetime peak."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return float(pages * resource.getpagesize())
    except (OSError, ValueError, IndexError):
        # fallback: peak RSS; ru_maxrss is KiB on Linux, bytes on macOS
        peak = float(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        return peak if sys.platform == "darwin" else peak * 1024.0


METRICS = Metrics()

#: actual bound port of the last-started MetricsServer (0 = none) — what
#: /statusz surfaces so /clusterz peers can scrape without hand-wiring
_BOUND_PORT = [0]
_BOUND_PORT_LOCK = threading.Lock()


def bound_port() -> int:
    with _BOUND_PORT_LOCK:
        return _BOUND_PORT[0]


class MetricsServer:
    """Embedded scrape endpoint (reference: Kamon Prometheus on :11600)."""

    def __init__(self, port: int = DEFAULT_PORT, addr: str = "0.0.0.0",
                 metrics: Metrics = METRICS):
        from ..utils.config import strided_port

        # auto-offset by jax.process_index() x RTPU_PORT_STRIDE so a
        # multi-process localhost cluster never collides on :11600 —
        # process 0 (and every single-process deployment) binds the
        # configured port verbatim; port 0 stays ephemeral
        self.port = strided_port(port)
        self.addr = addr
        self.metrics = metrics
        self._server = None
        self._thread: threading.Thread | None = None

    def start(self) -> "MetricsServer":
        self._server, self._thread = start_http_server(
            self.port, self.addr, registry=self.metrics.registry)
        # surface the ACTUAL bound port (ephemeral port-0 binds resolve
        # here) — what /statusz reports for /clusterz peer discovery
        self.port = self._server.server_address[1]
        with _BOUND_PORT_LOCK:
            _BOUND_PORT[0] = self.port
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            with _BOUND_PORT_LOCK:
                if _BOUND_PORT[0] == self.port:
                    _BOUND_PORT[0] = 0
        if self._thread is not None:
            # join the scrape-server thread so repeated start/stop in
            # tests can't leak threads; a bounded wait keeps a wedged
            # handler from hanging shutdown forever
            self._thread.join(timeout=5.0)
            self._thread = None
