"""/clusterz — federated cluster observability.

Every process of a deployment serves the same single-process surfaces
(``/statusz``, ``/tracez``, ``/slz``); until now an operator diagnosing a
2-process straggler had to hand-curl N ports and join the answers in
their head. ``/clusterz`` does the join server-side: ANY process scrapes
its peers' ``/statusz`` (and, per trace id, ``/tracez``) and renders one
merged cluster view — membership, per-process queue depth and watermark
lag, per-route collective seconds/bytes/rows, per-shard halo/degree skew,
per-process barrier wait, cross-process traces reassembled by id, plus
the judgment plane (PR 11): mesh-wide per-tenant workload totals and the
union of firing advisor rules with per-process attribution, and the
freshness plane (ISSUE 15): a merged min-watermark + per-process
watermark spread — the lagging-ingest-shard straggler signal.

Design rules (the RT009/RT011 lint territory this module sits in):

* **Scrapes happen outside every lock.** The peer list is resolved and
  the HTTP fan-out completes before the snapshot cache is touched; the
  cache lock only ever guards dict ops. A slow peer can cost the caller
  its bounded timeout, never block another thread on a mutex.
* **A dead peer is DATA, not an error.** Scrape failures render as
  ``reachable: false`` with the error string; ``/clusterz`` itself never
  500s because a member died — that is precisely when it is needed.
* **Bounded everything.** Peer scrapes carry ``RTPU_CLUSTERZ_TIMEOUT``
  (default 2 s) socket timeouts; the snapshot cache holds at most
  ``_CACHE_MAX`` peers (oldest evicted) with a short TTL so a 1 Hz
  dashboard poll doesn't multiply scrape traffic across the mesh.

Peer discovery: ``RTPU_CLUSTER_PEERS`` (comma-separated ``host:port``
or URLs, or ``@/path/file`` one-per-line) when set — real multi-host
deployments name their peers; otherwise the bootstrap topology is enough:
process ``i`` listens on ``rest_port + i x RTPU_PORT_STRIDE`` (the
localhost port-striding scheme, utils/config.strided_port).

Every peer scrape carries the caller's ``X-RTPU-Trace`` context, so the
scrape itself reconstructs as one trace across the processes it touched.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.parse
import urllib.request
from concurrent.futures import ThreadPoolExecutor

from ..resilience import faults as _faults
from ..resilience.breaker import BREAKERS
from ..utils.config import process_index, strided_port
from .trace import TRACER, TraceContext


def _breakers():
    """The process-wide breaker registry (one name per peer URL)."""
    return BREAKERS

DEFAULT_TIMEOUT_S = 2.0
_CACHE_MAX = 64          # bounded peer-snapshot cache (RT011)
_CACHE_TTL_S = 2.0       # fresh-enough window for repeat polls

#: statuses that occupy the job table (everything not yet terminal)
_ACTIVE_STATUSES = ("pending", "running")


def clusterz_timeout() -> float:
    """``RTPU_CLUSTERZ_TIMEOUT`` — per-peer scrape socket timeout."""
    try:
        return max(0.1, float(
            os.environ.get("RTPU_CLUSTERZ_TIMEOUT", "") or DEFAULT_TIMEOUT_S))
    except ValueError:
        return DEFAULT_TIMEOUT_S


# ------------------------------------------------------------ discovery


def _static_peer_spec() -> tuple[str, str | None]:
    """``RTPU_CLUSTER_PEERS`` resolved to a comma-separated spec, plus an
    error string when an ``@/path/file`` form could not be read. The
    error is DATA for ``/clusterz`` (``peers_error``) — a typo'd peer
    file must not silently degrade to the derived localhost topology
    with no hint why the configured mesh is dark."""
    static = os.environ.get("RTPU_CLUSTER_PEERS", "").strip()
    if static.startswith("@"):
        path = static[1:]
        try:
            with open(path) as f:
                static = ",".join(
                    ln.strip() for ln in f
                    if ln.strip() and not ln.lstrip().startswith("#"))
        except OSError as e:
            return "", f"unreadable RTPU_CLUSTER_PEERS file {path}: {e}"
    return static, None


def resolve_peers(n_processes: int | None = None,
                  rest_port: int | None = None,
                  host: str | None = None) -> tuple:
    """Per-process REST base URLs, in process order.

    ``RTPU_CLUSTER_PEERS`` wins when set. Otherwise derive from the
    port-striding scheme: peer ``i`` on ``rest_port + i * stride`` at
    ``RTPU_PEER_HOST`` (default 127.0.0.1). ``n_processes`` defaults to
    ``jax.process_count()`` when jax is already imported (never imported
    from here — this module stays stdlib-only), else 1."""
    static, _ = _static_peer_spec()
    if static:
        out = []
        for p in static.split(","):
            p = p.strip()
            if not p:
                continue
            if not p.startswith(("http://", "https://")):
                p = f"http://{p}"
            out.append(p.rstrip("/"))
        return tuple(out)
    if n_processes is None:
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                n_processes = int(jax.process_count())
            except Exception:
                n_processes = 1
        else:
            n_processes = 1
    if rest_port is None:
        from ..utils.config import Settings

        rest_port = Settings().rest_port
    host = host or os.environ.get("RTPU_PEER_HOST", "127.0.0.1")
    return tuple(
        f"http://{host}:{strided_port(rest_port, i)}"
        for i in range(max(1, int(n_processes))))


# -------------------------------------------------------------- scraping


def _fetch_json(url: str, timeout: float) -> dict:
    """One bounded-timeout GET returning parsed JSON. The caller's trace
    context rides the X-RTPU-Trace header so the serve side joins the
    scrape's trace. Raises on any transport/parse trouble — the caller
    turns that into an ``unreachable`` row, never a 500."""
    _faults.fire("peer.scrape")
    req = urllib.request.Request(url)
    ctx = TRACER.capture()
    if ctx is not None:
        req.add_header(TraceContext.HEADER, ctx.to_wire())
    with urllib.request.urlopen(req, timeout=timeout) as r:  # noqa: S310
        return json.loads(r.read().decode())


class PeerScraper:
    """Fan-out scraper with a bounded, TTL'd last-snapshot cache.

    The cache exists for poll-frequency callers (a dashboard refreshing
    /clusterz at 1 Hz must not scrape the whole mesh every time) and is
    bounded both ways: at most ``_CACHE_MAX`` peer entries (oldest
    evicted — a churning RTPU_CLUSTER_PEERS can't grow it without bound)
    and ``_CACHE_TTL_S`` seconds of staleness before a refetch. All
    network I/O happens OUTSIDE the cache lock."""

    def __init__(self, timeout_s: float | None = None,
                 ttl_s: float = _CACHE_TTL_S):
        self._timeout_s = timeout_s
        self._ttl_s = ttl_s
        self._lock = threading.Lock()
        self._cache: dict[str, tuple[float, dict]] = {}

    def _cached(self, urls: list[str]) -> dict[str, dict]:
        now = time.monotonic()
        with self._lock:
            return {u: snap for u, (ts, snap) in self._cache.items()
                    if u in urls and now - ts <= self._ttl_s}

    def last_seen_s(self, url: str) -> float | None:
        """Seconds since ``url`` last answered a cacheable scrape (past
        the TTL too) — the staleness a DOWN peer's row renders while the
        survivor keeps serving."""
        with self._lock:
            ent = self._cache.get(url)
        return None if ent is None else time.monotonic() - ent[0]

    def _store(self, results: dict[str, dict]) -> None:
        now = time.monotonic()
        with self._lock:
            for u, snap in results.items():
                self._cache[u] = (now, snap)
            while len(self._cache) > _CACHE_MAX:   # bounded: evict oldest
                oldest = min(self._cache, key=lambda u: self._cache[u][0])
                del self._cache[oldest]

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()

    def scrape(self, urls: list[str], path: str = "/statusz",
               refresh: bool = False) -> dict[str, dict]:
        """Fetch ``url + path`` from every peer concurrently (bounded
        timeout each). Returns url → snapshot; failures yield
        ``{"reachable": False, "error": ...}``. ``refresh=True`` (and any
        non-/statusz path) bypasses the cache."""
        timeout = (self._timeout_s if self._timeout_s is not None
                   else clusterz_timeout())
        cacheable = path == "/statusz" and not refresh
        out: dict[str, dict] = {}
        todo = list(urls)
        if cacheable:
            hit = self._cached(todo)
            out.update(hit)
            todo = [u for u in todo if u not in hit]
        if todo:
            fetched: dict[str, dict] = {}
            # per-peer circuit breakers: a DEAD peer costs `threshold`
            # timeouts once, then one half-open probe per window — every
            # gated pass renders the breaker as the row's evidence
            # instead of paying the socket timeout again
            wired = []
            for u in todo:
                br = _breakers().get(u)
                if br.allow():
                    wired.append(u)
                else:
                    snap = {"reachable": False, "down": True,
                            "error": "breaker open: peer skipped this "
                                     "pass (no timeout paid)",
                            "breaker": br.snapshot()}
                    seen = self.last_seen_s(u)
                    if seen is not None:
                        snap["last_seen_seconds_ago"] = round(seen, 3)
                    fetched[u] = snap
            if wired:
                with TRACER.span("rest.scrape", peers=len(wired),
                                 path=path,
                                 process=TRACER.process_index):
                    # network fan-out: no lock held anywhere in this block
                    with ThreadPoolExecutor(
                            max_workers=min(8, len(wired))) as pool:
                        futs = {u: pool.submit(_fetch_json, u + path,
                                               timeout)
                                for u in wired}
                        for u, fut in futs.items():
                            try:
                                snap = fut.result()
                                snap.setdefault("reachable", True)
                                fetched[u] = snap
                                _breakers().get(u).record(True)
                            except Exception as e:   # dead peer == data
                                err = f"{type(e).__name__}: {e}"[:200]
                                br = _breakers().get(u)
                                br.record(False, error=err)
                                snap = {"reachable": False, "error": err,
                                        "breaker": br.snapshot()}
                                seen = self.last_seen_s(u)
                                if seen is not None:
                                    snap["last_seen_seconds_ago"] = (
                                        round(seen, 3))
                                fetched[u] = snap
            out.update(fetched)
            if cacheable:
                self._store({u: s for u, s in fetched.items()
                             if s.get("reachable")})
        return out


#: process-wide scraper (the bounded cache is shared across requests)
SCRAPER = PeerScraper()


# ------------------------------------------------------------- federation


def _peer_summary(status: dict) -> dict:
    """The compact per-process row of the merged view, extracted from one
    peer's /statusz snapshot (tolerant: older peers may lack blocks)."""
    if not status.get("reachable", True):
        row = {"reachable": False, "error": status.get("error", "")}
        # breaker evidence survives the summary: the merged view is where
        # operators look first, so auto-down must be visible THERE
        for k in ("down", "breaker", "last_seen_seconds_ago"):
            if k in status:
                row[k] = status[k]
        return row
    cluster = status.get("cluster", {}) or {}
    jobs = status.get("jobs", {}) or {}
    coll = status.get("collectives", {}) or {}
    routes = coll.get("routes", {}) or {}
    wm = status.get("watermark", {}) or {}
    return {
        "reachable": True,
        "process_index": cluster.get("process_index"),
        "ports": cluster.get("ports", {}),
        "watchdog": cluster.get("watchdog"),
        "queue_depth": sum(1 for s in jobs.values()
                           if s in _ACTIVE_STATUSES),
        "jobs_total": len(jobs),
        "watermark_lag_seconds": wm.get("lag_seconds"),
        "safe_time": wm.get("safe_time"),
        "log_events": status.get("log_events"),
        "collectives": {
            "routes": routes,
            "skew": coll.get("skew"),
            "skew_refreshes": coll.get("skew_refreshes"),
            # the route chooser's verdict counts + measured frontier
            # densities (PR 20) — the advisor's shard-skew rule reads
            # these to tell "sparse route already absorbing it" from
            # "operator should flip RTPU_COMM_ROUTE"
            "route_table": coll.get("route_table"),
            "frontier_density": coll.get("frontier_density"),
            "barrier_wait_seconds": round(sum(
                r.get("barrier_wait_seconds", 0.0)
                for r in routes.values()), 6),
        },
        # the judgment plane (PR 11): compact per-tenant totals, the
        # error-budget grade, and the advisor's last-tick rule ids —
        # already bounded at the source (/statusz embeds the same)
        "workload": status.get("workload"),
        "budget": status.get("budget"),
        "advisor": status.get("advisor"),
        # the measured device plane (PR 12): timing totals, memory
        # snapshot (or degrade), resident bytes, compile-storm signal
        "device": status.get("device"),
        # the freshness plane (obs/freshness.py): updates/s, backlog,
        # queryable lag, staleness grade — already compact at the source
        "freshness": status.get("freshness"),
        # the durable journal (obs/journal.py): where this member's
        # replayable evidence lives, how much of it, and whether the
        # writer is keeping up — the postmortem plane's discovery data
        "journal": status.get("journal"),
        # the mesh-divergence sanitizer (analysis/sanitizer.py): this
        # member's dispatch-fingerprint ring + counters — the raw
        # material of the cluster-wide prefix cross-check
        "mesh_sanitizer": status.get("mesh_sanitizer"),
    }


def _merge_members(processes: dict) -> dict:
    """Union of every reachable peer's watchdog membership, keyed by
    role — each process's WatchDog only knows locally-joined members, so
    the cluster view is the union with per-process attribution."""
    merged: dict[str, dict] = {}
    for name, p in processes.items():
        wd = p.get("watchdog") if p.get("reachable") else None
        if not wd:
            continue
        for role, ids in (wd.get("members") or {}).items():
            r = merged.setdefault(role, {"count": 0, "by_process": {}})
            r["count"] += len(ids)
            r["by_process"][name] = ids
    return merged


def _merge_workload(processes: dict) -> dict:
    """Mesh-wide per-tenant totals: every reachable peer's compact
    workload block summed by tenant with per-process attribution — an
    operator asks "what is tenant X costing the CLUSTER", not one
    process. Bounded: each peer ships at most its top-8 tenants."""
    tenants: dict[str, dict] = {}
    for name, p in processes.items():
        wl = p.get("workload") if p.get("reachable") else None
        if not wl:
            continue
        for tenant, row in (wl.get("tenants") or {}).items():
            t = tenants.setdefault(tenant, {
                "queries": 0, "cost_seconds": 0.0,
                "queue_wait_seconds": 0.0, "by_process": {}})
            t["queries"] += row.get("queries", 0)
            t["cost_seconds"] = round(
                t["cost_seconds"] + row.get("cost_seconds", 0.0), 6)
            t["queue_wait_seconds"] = round(
                t["queue_wait_seconds"]
                + row.get("queue_wait_seconds", 0.0), 6)
            t["by_process"][name] = row
    top = sorted(tenants.items(), key=lambda kv: -kv[1]["cost_seconds"])
    return {"n_tenants": len(tenants), "tenants": dict(top[:8])}


def _merge_device(processes: dict) -> dict:
    """Every reachable peer's device block: mesh-wide resident bytes,
    per-process memory occupancy, and which processes are inside a
    compile storm — the measured plane's cluster view."""
    resident_total = 0
    memory: dict[str, dict] = {}
    storms: list[str] = []
    measured = 0
    for name, p in processes.items():
        dev = p.get("device") if p.get("reachable") else None
        if not dev:
            continue
        resident_total += int(dev.get("resident_bytes") or 0)
        mem = dev.get("memory") or {}
        memory[name] = (mem if mem.get("available")
                        else {"available": False})
        comp = dev.get("compile") or {}
        if comp.get("storm"):
            storms.append(name)
        measured += int((dev.get("timing") or {})
                        .get("kernels_measured") or 0)
    return {"resident_bytes_total": resident_total,
            "kernels_measured_total": measured,
            "memory_by_process": memory,
            "compile_storms": sorted(storms)}


def _merge_freshness(processes: dict) -> dict:
    """The mesh's freshness view: merged min-watermark (the fence the
    CLUSTER can serve exactly at — one lagging ingest shard drags it),
    per-process safe times and watermark lags, and the watermark SPREAD
    (max lag − min lag): a lagging ingest shard is a straggler the
    barrier-wait signals can't see, because it stalls the fence, not a
    collective."""
    safe: dict[str, int] = {}
    lags: dict[str, float] = {}
    ups = 0.0
    backlog = 0
    grades: dict[str, str] = {}
    for name, p in processes.items():
        if not p.get("reachable"):
            continue
        # the ±2^62 fence sentinels (all-done / idle-registered) are
        # not times: a serving-only or replay-finished process must not
        # put 4611686018427387904 into the merged min (the freshness
        # plane nulls the same sentinels on /statusz)
        if p.get("safe_time") is not None \
                and abs(int(p["safe_time"])) < 2**62:
            safe[name] = int(p["safe_time"])
        if p.get("watermark_lag_seconds") is not None:
            lags[name] = float(p["watermark_lag_seconds"])
        fr = p.get("freshness") or {}
        ups += float(fr.get("updates_per_s") or 0.0)
        backlog += int(fr.get("backlog_events") or 0)
        if fr.get("grade"):
            grades[name] = fr["grade"]
    out: dict = {
        "min_safe_time": min(safe.values()) if safe else None,
        "safe_time_by_process": safe,
        "watermark_lag_by_process": {n: round(v, 3)
                                     for n, v in lags.items()},
        "watermark_spread_seconds": (round(max(lags.values())
                                           - min(lags.values()), 3)
                                     if len(lags) >= 2 else 0.0),
        "updates_per_s_total": round(ups, 1),
        "backlog_events_total": backlog,
        "grade_by_process": grades,
    }
    if safe:
        worst = min(safe, key=safe.get)
        out["min_safe_process"] = worst
    return out


def _merge_journal(processes: dict) -> dict:
    """The postmortem plane's discovery view: which members journal,
    where, how many bytes of evidence each holds, and mesh-wide drop /
    flush-lag health — so ``rtpu-postmortem`` (and the operator driving
    it) learns from ONE scrape where every member's replayable history
    lives, including a member that is about to die."""
    by_process: dict[str, dict] = {}
    bytes_total = 0
    drops_total = 0
    worst_lag = 0.0
    enabled = 0
    for name, p in processes.items():
        j = p.get("journal") if p.get("reachable") else None
        if not j:
            continue
        if not j.get("enabled"):
            by_process[name] = {"enabled": False}
            continue
        enabled += 1
        lag = float(j.get("flush_lag_seconds") or 0.0)
        by_process[name] = {
            "enabled": True,
            "dir": j.get("dir"),
            "segments": j.get("segments"),
            "bytes": j.get("total_bytes"),
            "drops": j.get("drops"),
            "flush_lag_seconds": lag,
        }
        bytes_total += int(j.get("total_bytes") or 0)
        drops_total += int(j.get("drops") or 0)
        worst_lag = max(worst_lag, lag)
    return {"processes_enabled": enabled,
            "bytes_total": bytes_total,
            "drops_total": drops_total,
            "worst_flush_lag_seconds": round(worst_lag, 3),
            "by_process": by_process}


def _merge_routes(processes: dict) -> dict:
    """Cluster-wide per-route exchange totals + chooser verdict counts
    summed over reachable peers — the at-a-glance answer to "what moved
    over the wire, by route" (the smoke asserts sparse bytes HERE)."""
    totals: dict[str, dict] = {}
    decisions: dict[str, int] = {}
    for p in processes.values():
        coll = p.get("collectives") if p.get("reachable") else None
        if not coll:
            continue
        for route, r in (coll.get("routes") or {}).items():
            t = totals.setdefault(route, {"dispatches": 0, "supersteps": 0,
                                          "rows": 0, "bytes": 0})
            for k in t:
                t[k] += int(r.get(k, 0))
        for key, c in ((coll.get("route_table") or {}).get("counts")
                       or {}).items():
            decisions[key] = decisions.get(key, 0) + int(c)
    return {"totals": totals, "decision_counts": decisions}


def _merge_mesh(processes: dict) -> dict:
    """The SPMD-divergence cross-check: every sanitized peer's dispatch-
    fingerprint ring compared pairwise against the lowest-indexed one
    (``analysis.sanitizer.mesh_prefix_divergence``). In a correct run
    every process issues the SAME sequence of mesh dispatches, so the
    first sequence number whose fingerprints disagree names the exact
    collective where the programs diverged — the root cause behind a
    barrier-wait hang that the straggler signals can only see as "slow".
    Dispatch counters ride along: a peer merely BEHIND (same prefix,
    fewer dispatches) renders as skew, not divergence."""
    rings: dict[str, list] = {}
    dispatches: dict[str, int] = {}
    findings_total = 0
    enabled = 0
    for name, p in processes.items():
        ms = p.get("mesh_sanitizer") if p.get("reachable") else None
        if not ms:
            continue
        if not ms.get("enabled"):
            continue
        enabled += 1
        rings[name] = ms.get("ring") or []
        dispatches[name] = int(ms.get("dispatches") or 0)
        findings_total += int(ms.get("findings") or 0)
    divergence = None
    if len(rings) >= 2:
        from ..analysis.sanitizer import mesh_prefix_divergence

        divergence = mesh_prefix_divergence(rings)
    counts = set(dispatches.values())
    return {
        "processes_enabled": enabled,
        "dispatches_by_process": dispatches,
        "dispatch_skew": (max(counts) - min(counts)) if counts else 0,
        "findings_total": findings_total,
        "divergence": divergence,
    }


def _merge_advisor(processes: dict) -> dict:
    """Every reachable peer's advisor block: total findings + the union
    of firing rule ids with per-process attribution."""
    rules: dict[str, list] = {}
    total = 0
    for name, p in processes.items():
        adv = p.get("advisor") if p.get("reachable") else None
        if not adv:
            continue
        total += adv.get("findings", 0)
        for rid in adv.get("rule_ids", []):
            rules.setdefault(rid, []).append(name)
    return {"findings": total,
            "rules": {rid: sorted(names)
                      for rid, names in sorted(rules.items())}}


def clusterz(manager=None, handler=None, trace_id: str | None = None,
             refresh: bool = False, peers: list[str] | None = None) -> dict:
    """The merged cluster view any process serves at ``/clusterz``.

    The local process renders in-process (no HTTP hop to itself); every
    other peer is scraped with bounded timeouts. ``trace_id`` adds a
    cross-process trace reassembly block: every peer's
    ``/tracez?trace_id=`` spans, grouped by process (span timestamps are
    per-process perf_counter epochs — NOT comparable across processes;
    the grouping preserves that honestly)."""
    my_idx = process_index()
    static_spec, peers_error = _static_peer_spec()
    if peers is None:
        base = (getattr(handler, "rest_base_port", None)
                if handler else None)
        peers = list(resolve_peers(rest_port=base))
    # identify self: derived (strided-localhost) peers match on index or
    # local bound port; static lists need the HOST too — every host of a
    # real mesh binds the same port, so port alone would classify EVERY
    # peer as self and federation would never scrape anyone. A static
    # entry naming this host by a non-loopback address is scraped over
    # HTTP like any peer (wasteful, never wrong).
    my_port = getattr(handler, "rest_port", 0) if handler else 0

    def _is_self(i: int, url: str) -> bool:
        u = urllib.parse.urlsplit(url)
        if static_spec or os.environ.get("RTPU_CLUSTER_PEERS"):
            return bool(my_port) and u.port == my_port and \
                u.hostname in ("127.0.0.1", "localhost", "::1")
        return (bool(my_port) and u.port == my_port) or i == my_idx

    remote = [u for i, u in enumerate(peers) if not _is_self(i, u)]
    scraped = SCRAPER.scrape(remote, refresh=refresh)

    processes: dict[str, dict] = {}
    if manager is not None:
        from ..jobs.rest import _statusz

        local = _statusz(manager, handler)
        local["reachable"] = True
        processes[f"process_{my_idx}"] = _peer_summary(local)
        processes[f"process_{my_idx}"]["self"] = True
    for u in remote:
        snap = scraped.get(u, {"reachable": False, "error": "not scraped"})
        row = _peer_summary(snap)
        row["url"] = u
        idx = row.get("process_index")
        key = f"process_{idx}" if idx is not None else u
        processes[key] = row

    reachable = sum(1 for p in processes.values() if p.get("reachable"))
    out: dict = {
        "process_index": my_idx,
        "peers_configured": len(peers),
        "processes_reachable": reachable,
        "processes": processes,
        "members": _merge_members(processes),
        "workload": _merge_workload(processes),
        "advisor": _merge_advisor(processes),
        "device": _merge_device(processes),
        "freshness": _merge_freshness(processes),
        "journal": _merge_journal(processes),
        "mesh": _merge_mesh(processes),
        "routes": _merge_routes(processes),
        "stragglers": {
            name: p["collectives"]["barrier_wait_seconds"]
            for name, p in processes.items()
            if p.get("reachable") and p.get("collectives")},
    }
    if peers_error:
        out["peers_error"] = peers_error
    if trace_id:
        by_process: dict[str, list] = {}
        if manager is not None:
            by_process[f"process_{my_idx}"] = TRACER.for_trace(trace_id)
        # ONE concurrent fan-out like the /statusz scrape above — a
        # serial per-peer loop would stack dead peers' timeouts
        q = urllib.parse.quote(trace_id, safe="")
        scraped_t = SCRAPER.scrape(remote, path=f"/tracez?trace_id={q}",
                                   refresh=True)
        for u in remote:
            t = scraped_t.get(u, {})
            key = next((k for k, p in processes.items()
                        if p.get("url") == u), u)
            by_process[key] = (t.get("spans", [])
                              if t.get("reachable", True) else [])
        out["trace"] = {
            "trace_id": trace_id,
            "span_count": sum(len(v) for v in by_process.values()),
            "processes_with_spans": sorted(
                k for k, v in by_process.items() if v),
            "by_process": by_process,
        }
    return out
