"""SLO latency histograms with trace exemplars + a bounded series ring.

Two gaps this module closes for the serving push (ROADMAP item 1):

* **"p99 is bad — WHICH request?"** Aggregate histograms prove a tail
  exists but can't name a culprit. Each per-(algorithm, phase) latency
  histogram here keeps one **trace-ID exemplar per bucket** — the last
  request that landed there — so the p99 bucket resolves to an actual
  end-to-end trace at ``/tracez?trace_id=…`` (obs/trace.py). This is the
  Canopy workflow: sampled per-request traces joined to the aggregate
  that flagged them.
* **"/statusz is a point-in-time snapshot."** Saturation is a shape over
  time (queue depth climbing while throughput flattens), invisible at
  scrape instants. The ``SeriesRing`` samples a small signal set (queue
  depth, in-flight jobs, fold-cache bytes, H2D stall seconds) every
  interval into a bounded ring, surfaced at ``/slz`` as JSON plus text
  sparklines.

Everything is stdlib-only; observations mirror into the Prometheus
``raphtory_request_seconds{algorithm,phase}`` histogram when
``obs.metrics`` is importable.

Knobs
-----
* ``RTPU_SLO`` — per-request SLO observation (default on; the
  ``telemetry_overhead`` bench's off arm).
* ``RTPU_SLO_BUCKETS`` — comma-separated upper bounds in seconds.
* ``RTPU_SERIES_RING`` — series-ring capacity in samples (default 512).
* ``RTPU_SERIES_DUMP`` — file path; implies the ring sampler on, rows
  written there at interpreter exit (the CI failure-artifact hook).
"""

from __future__ import annotations

import bisect
import json
import os
import threading
import time
import weakref
from collections import deque

from . import journal as _journal

#: Canopy-style default grid: sub-10ms cache hits through multi-minute
#: cold scale sweeps, denser where SLOs actually get set
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 120.0)
DEFAULT_RING = 512
#: (algorithm, phase) key cap — the REST surface must not be able to grow
#: the histogram table without bound (rtpulint RT011); the registry names
#: a few dozen programs, so 256 keys is generous
MAX_KEYS = 256
_SPARK = "▁▂▃▄▅▆▇█"


def enabled() -> bool:
    """Re-read per observation so the A/B bench (and operators) can flip
    it without a process restart — one getenv per completed request."""
    return os.environ.get("RTPU_SLO", "1") not in ("", "0", "false")


def slo_buckets() -> tuple:
    """Histogram upper bounds (seconds), ascending. ``RTPU_SLO_BUCKETS``
    is a comma-separated override; unparseable values fall back to the
    default grid (telemetry must never take a process down)."""
    raw = os.environ.get("RTPU_SLO_BUCKETS", "")
    if raw:
        try:
            bounds = tuple(sorted(float(x) for x in raw.split(",") if x))
            if bounds and all(b > 0 for b in bounds):
                return bounds
        except ValueError:
            pass
    return DEFAULT_BUCKETS


class _Hist:
    """One (algorithm, phase) histogram: per-bucket counts plus one
    trace-ID exemplar per bucket (the LAST request that landed there —
    recency beats reservoir sampling for debugging: the exemplar must
    still be in the flight-recorder ring to resolve)."""

    __slots__ = ("bounds", "counts", "count", "sum_seconds", "exemplars")

    def __init__(self, bounds: tuple):
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)   # +1: overflow bucket
        self.count = 0
        self.sum_seconds = 0.0
        self.exemplars: list = [None] * (len(bounds) + 1)

    def observe(self, seconds: float, trace_id: str | None,
                unix: float) -> None:
        i = bisect.bisect_left(self.bounds, seconds)
        self.counts[i] += 1
        self.count += 1
        self.sum_seconds += seconds
        if trace_id:
            self.exemplars[i] = {"trace_id": trace_id,
                                 "seconds": round(seconds, 6),
                                 "unix": round(unix, 3)}

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile (the standard
        Prometheus-style estimate; the overflow bucket reports the last
        finite bound). 0.0 when empty. Shares ``quantile_bucket`` so the
        reported p99 and the p99 exemplar can never name different
        buckets."""
        if not self.count:
            return 0.0
        return self.bounds[min(self.quantile_bucket(q),
                               len(self.bounds) - 1)]

    def quantile_bucket(self, q: float) -> int:
        if not self.count:
            return 0
        need = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= need:
                return i
        return len(self.counts) - 1

    def exemplar_near(self, q: float):
        """The exemplar of the q-quantile's bucket, walking DOWN to the
        nearest populated one when that bucket's observations all lacked
        trace ids (tracing off for those requests)."""
        for i in range(self.quantile_bucket(q), -1, -1):
            if self.exemplars[i] is not None:
                return self.exemplars[i]
        return None

    def as_dict(self) -> dict:
        return {
            "count": self.count,
            "sum_seconds": round(self.sum_seconds, 6),
            "buckets": list(self.bounds),
            "counts": list(self.counts),
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
            "exemplars": list(self.exemplars),
            "p99_exemplar": self.exemplar_near(0.99),
        }


def _metrics():
    """obs.metrics bundle, or None when prometheus isn't importable."""
    try:
        from .metrics import METRICS

        return METRICS
    except Exception:
        return None


class SLORegistry:
    """Process-wide per-(algorithm, phase) latency histograms. All
    mutation under one lock (observations come from every job thread);
    bucket bounds are pinned at first observation so an env flip mid-run
    can't tear a histogram."""

    def __init__(self):
        self._lock = threading.Lock()
        self._hists: dict[tuple, _Hist] = {}
        self.dropped_keys = 0

    def observe(self, algorithm: str, phase: str, seconds: float,
                trace_id: str | None = None) -> None:
        if not enabled():
            return
        seconds = float(seconds)
        key = (str(algorithm), str(phase))
        now = time.time()
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                if len(self._hists) >= MAX_KEYS:
                    self.dropped_keys += 1
                    return
                h = self._hists[key] = _Hist(slo_buckets())
            h.observe(seconds, trace_id, now)
        m = _metrics()
        if m is not None:
            m.request_seconds.labels(algorithm, phase).observe(seconds)

    def exemplar(self, algorithm: str, phase: str = "e2e",
                 q: float = 0.99):
        with self._lock:
            h = self._hists.get((str(algorithm), str(phase)))
            return h.exemplar_near(q) if h is not None else None

    def totals_below(self, algorithm: str, phase: str,
                     threshold_s: float) -> tuple[int, int]:
        """``(total, good)`` observation counts for ``algorithm``/
        ``phase`` where *good* counts observations in buckets whose
        upper bound is ≤ ``threshold_s`` — the error-budget numerator
        (obs/budget.py). Algorithm matching is case-insensitive (targets
        are operator-typed env strings; ledger algorithm labels are
        class names). A threshold between bucket bounds counts its
        bucket as BAD — conservative, and exact when targets align with
        the (configurable) ``RTPU_SLO_BUCKETS`` grid."""
        alg = str(algorithm).lower()
        ph = str(phase)
        total = good = 0
        with self._lock:
            for (a, p), h in self._hists.items():
                if p != ph or a.lower() != alg:
                    continue
                total += h.count
                for i, bound in enumerate(h.bounds):
                    if bound <= threshold_s:
                        good += h.counts[i]
        return total, good

    def as_dict(self) -> dict:
        with self._lock:
            hists = {f"{alg}/{ph}": h.as_dict()
                     for (alg, ph), h in sorted(self._hists.items())}
            dropped = self.dropped_keys
        return {"enabled": enabled(), "histograms": hists,
                "dropped_keys": dropped}

    def clear(self) -> None:
        with self._lock:
            self._hists.clear()
            self.dropped_keys = 0


SLO = SLORegistry()


def _fold_cache_bytes() -> float:
    from ..core.sweep import fold_cache

    cache = fold_cache()
    return float(cache.stats()["bytes"]) if cache is not None else 0.0


def _h2d_totals() -> dict:
    from ..utils.transfer import shared_engine

    return shared_engine().stats.totals()


def _device_bytes_in_use() -> float:
    """Device-memory occupancy collector (obs/device.py): raises on
    backends without memory counters so the sample records None — the
    ring's failing-collector contract, the thread never dies."""
    from .device import series_bytes_in_use

    return series_bytes_in_use()


def _device_resident_bytes() -> float:
    from .device import RESIDENT

    return float(RESIDENT.snapshot()["total_bytes"])


def _ingest_events_total() -> float:
    """Freshness-plane collector (obs/freshness.py): cumulative ingested
    events — the ring's ``_total`` differencing renders updates/s."""
    from .freshness import FRESH

    return FRESH.total_events()


def _ingest_backlog_events() -> float:
    from .freshness import FRESH

    return FRESH.backlog_events()


def _queryable_lag_seconds() -> float:
    from .freshness import FRESH

    return FRESH.queryable_lag_seconds()


def sparkline(values: list[float]) -> str:
    """Text sparkline over ``values`` (min..max scaled to 8 levels);
    constant series render flat-low."""
    vals = [float(v) for v in values]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK[0] * len(vals)
    return "".join(_SPARK[int((v - lo) / (hi - lo) * (len(_SPARK) - 1))]
                   for v in vals)


class SeriesRing:
    """Bounded ring of periodic samples over registered collectors —
    saturation as a SHAPE over time, not a scrape instant.

    Collectors are zero-arg callables returning a float; a failing
    collector contributes None for that sample (telemetry never takes
    the server down). Cumulative signals use a ``_total`` suffix — the
    sparkline/rate surfaces difference them per interval."""

    def __init__(self, ring: int | None = None, interval: float = 1.0):
        if ring is None:
            try:
                ring = int(os.environ.get("RTPU_SERIES_RING", DEFAULT_RING))
            except ValueError:
                ring = DEFAULT_RING
        self.interval = float(interval)
        self._rows: deque = deque(maxlen=max(16, int(ring)))
        self._lock = threading.Lock()   # collectors map + thread lifecycle
        self._collectors: dict[str, object] = {}
        self._thread: threading.Thread | None = None
        # per-GENERATION stop event, replaced on every start — see
        # obs/sampler.py: a stop racing a concurrent start must only
        # affect the generation it swapped out
        self._stop = threading.Event()
        self.samples = 0
        # process-wide signals every deployment has; job-table signals
        # join via attach_manager
        self.register("fold_cache_bytes", _fold_cache_bytes)
        self.register("h2d_stall_seconds_total",
                      lambda: _h2d_totals()["stall_seconds"])
        self.register("h2d_bytes_total",
                      lambda: float(_h2d_totals()["bytes_shipped"]))
        # device runtime plane (obs/device.py): live memory occupancy
        # (None on backends without memory_stats — this CPU rig) and
        # the resident-buffer registry's total
        self.register("device_bytes_in_use", _device_bytes_in_use)
        self.register("device_resident_bytes", _device_resident_bytes)
        # freshness plane (obs/freshness.py): ingested events (the
        # ``_total`` differencing renders updates/s), the staged
        # parse→append backlog, and the age of the oldest batch the
        # safe-time fence has not yet covered
        self.register("ingest_events_total", _ingest_events_total)
        self.register("ingest_backlog_events", _ingest_backlog_events)
        self.register("queryable_lag_seconds", _queryable_lag_seconds)

    # ---- collectors ----

    def register(self, name: str, fn) -> None:
        with self._lock:
            self._collectors[str(name)] = fn

    def unregister(self, name: str) -> None:
        """Drop a collector (unknown names are a no-op) — how the
        error-budget registry retires a retargeted algorithm's
        collectors instead of leaving dead histogram walks sampling at
        1 Hz forever (obs/budget.py)."""
        with self._lock:
            self._collectors.pop(str(name), None)

    def attach_manager(self, manager) -> None:
        """Register job-table collectors for ``manager`` (weakly — the
        ring is process-wide and must not pin a dead manager): in-flight
        jobs and queue depth. Today queue depth counts submitted-but-not-
        yet-running jobs (thread-spawn latency); the admission-control
        scheduler will put real queueing behind the same signal."""
        ref = weakref.ref(manager)

        def _count(statuses):
            mgr = ref()
            if mgr is None:
                return 0.0
            return float(sum(1 for s in mgr.jobs().values()
                             if s in statuses))

        self.register("jobs_in_flight", lambda: _count(("running",)))
        self.register("jobs_queued", lambda: _count(("pending",)))
        # serving-scheduler signals (jobs/scheduler.py): collect-window
        # queue depth + ledger-priced admitted backlog — the saturation
        # shape a coalescing storm is diagnosed with at /slz
        sched = getattr(manager, "scheduler", None)
        if sched is not None:
            sref = weakref.ref(sched)

            def _sched_depth():
                s = sref()
                return float(s.queue_depth()) if s is not None else 0.0

            def _sched_backlog():
                s = sref()
                return (float(s.backlog_seconds())
                        if s is not None else 0.0)

            self.register("scheduler_queue_depth", _sched_depth)
            self.register("scheduler_backlog_seconds", _sched_backlog)

    # ---- sampling ----

    def sample_once(self) -> dict:
        with self._lock:
            collectors = list(self._collectors.items())
        row: dict = {"unix": round(time.time(), 3)}
        for name, fn in collectors:   # outside the lock: a collector may
            try:                      # take its own (manager/cache) locks
                row[name] = float(fn())
            except Exception:
                row[name] = None
        self._rows.append(row)
        self.samples += 1
        # durable mirror (obs/journal.py): the 1 Hz saturation series is
        # exactly the shape a postmortem wants around a death
        if _journal.enabled():
            _journal.emit("series", row)
        return row

    def _loop(self, stop: threading.Event) -> None:
        while not stop.wait(self.interval):
            self.sample_once()

    def start(self, interval: float | None = None) -> "SeriesRing":
        """Start the background sampler (idempotent)."""
        with self._lock:
            if interval is not None:
                self.interval = float(interval)
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, args=(stop,),
                name="series-sampler", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._lock:
            t, self._thread = self._thread, None
            self._stop.set()   # this generation's event, under the lock
        if t is not None:
            t.join(timeout=5.0)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    # ---- export ----

    def rows(self) -> list[dict]:
        return list(self._rows)

    def _series(self, rows: list[dict], name: str) -> list[float]:
        vals = [r.get(name) for r in rows]
        if name.endswith("_total"):
            # cumulative → per-interval deltas; a boundary touching a
            # failed sample (None) is DROPPED, never merged — filtering
            # Nones first would difference across the gap and render two
            # intervals' growth as one 2x "spike" in the sparkline
            return [b - a for a, b in zip(vals, vals[1:])
                    if a is not None and b is not None]
        return [v for v in vals if v is not None]

    def as_dict(self, last: int = 120) -> dict:
        rows = self.rows()
        names = sorted({k for r in rows for k in r} - {"unix"})
        window = rows[-max(1, int(last)):]
        return {
            "running": self.running,
            "interval_seconds": self.interval,
            "ring": self._rows.maxlen,
            "samples": self.samples,
            "signals": names,
            "rows": window,
            "sparklines": {n: sparkline(self._series(window, n))
                           for n in names},
        }

    def clear(self) -> None:
        self._rows.clear()
        self.samples = 0


SERIES = SeriesRing()


def slz_payload(series_last: int = 120) -> dict:
    """The ``/slz`` document: SLO histograms + exemplars + the series
    ring — everything needed to go from "p99 moved" to a trace id."""
    return {"slo": SLO.as_dict(), "series": SERIES.as_dict(series_last)}


_series_dump = os.environ.get("RTPU_SERIES_DUMP")
if _series_dump:
    from . import exitdump as _exitdump

    SERIES.start()

    def _dump_series(path=_series_dump):
        with open(path, "w") as f:
            json.dump({"interval_seconds": SERIES.interval,
                       "samples": SERIES.samples,
                       "rows": SERIES.rows()}, f)

    _exitdump.register("series", _dump_series)
