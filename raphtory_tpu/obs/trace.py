"""Span tracing + flight recorder — one timeline from ingest event to XLA op.

The reference had no distributed tracing at all (SURVEY §5.1 "No spans"):
Kamon counters plus log lines were the only answer to "where did this
sweep's time go". With the pipelined transfer engine overlapping fold /
stage / ship / compute across threads, aggregate histograms can no longer
attribute a regression to a stage — per-phase timing is the first-class
signal of the BSP pseudo-streaming literature (arXiv:1608.07200) and of
partition-centric phase breakdowns (arXiv:1709.07122).

Three pieces, all host-side and dependency-free (stdlib only, so the
transfer layer can use it in stripped environments):

* **Spans** — ``TRACER.span(name, **attrs)`` context managers carrying
  structured attributes (job_id, hop, superstep, bytes, stage). Spans
  nest per thread (a thread-local stack links parent ids), and each span
  optionally enters a ``jax.profiler.TraceAnnotation`` of the same name,
  so host phases line up with XLA ops in an xprof capture.
* **Trace contexts** — every root span allocates a ``trace_id``, and
  children inherit it. One REQUEST crosses threads (REST handler → job
  thread → fold-pool workers → transfer staging), so the per-thread
  nesting alone would shatter it into unlinked fragments; the explicit
  handoff API stitches them: ``capture()`` the context on the submitting
  thread, ``adopt(ctx)`` (or wrap the callable with ``carry(fn)``) on
  the receiving one. Spans opened under an adoption parent to the
  captured span and share its trace_id — ``for_trace(trace_id)`` (the
  REST ``/tracez?trace_id=`` surface) then reconstructs the request
  end-to-end, and the Chrome export draws cross-thread flow arrows
  between a span and its other-thread parent. This is the Canopy model
  of per-request trace assembly (one trace id, events from many
  execution units, joined after the fact).
* **Flight recorder** — a bounded ring (``collections.deque(maxlen=…)``)
  of COMPLETED spans. Always cheap: when tracing is off, ``span()``
  returns a shared no-op and records nothing; when on, a span costs two
  ``perf_counter_ns`` calls plus one dict append. The ring survives
  crashes of everything except the process — dump it on failure and the
  last N spans tell you what the system was doing.
* **Chrome trace-event exporter** — ``chrome_trace()`` / ``dump()``
  produce Perfetto / ``chrome://tracing`` compatible JSON: one ``X``
  (complete) event per span, one track per thread (``M`` thread-name
  metadata events), instants (``ph: "i"``) for watermark advances and
  stalls.

Knobs
-----
* ``RTPU_TRACE`` — enable tracing at import (default off). Runtime
  toggles: ``TRACER.enable()`` / ``TRACER.disable()`` or the REST
  ``/tracez?enable=1`` endpoint.
* ``RTPU_TRACE_RING`` — flight-recorder capacity in spans (default 4096).
* ``RTPU_TRACE_DUMP`` — a file path; implies tracing on, and the ring is
  written there at interpreter exit (the CI failure-artifact hook).
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import tempfile
import threading
import time

from . import journal as _journal

DEFAULT_RING = 4096


class _NullSpan:
    """Shared do-nothing span — what ``span()`` returns when tracing is
    off, so disabled tracing costs one attribute check per call site."""

    __slots__ = ()

    #: NULL_SPAN.trace is None — callers that record "the trace id of the
    #: span I just ran under" (jobs/manager) read it without a getattr
    trace = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


NULL_SPAN = _NullSpan()


class TraceContext:
    """A (trace_id, span_id) pair captured on one thread and adopted on
    another — the request identity that crosses every pool handoff.
    ``origin`` is the process index the context was captured on (0 for
    single-process runs): a context that crossed a REST hop keeps naming
    the process that started the request. Immutable value object; build
    via ``Tracer.capture()`` or parse one off the wire with
    ``from_wire``."""

    __slots__ = ("trace_id", "span_id", "origin")

    #: HTTP header every REST hop / peer scrape carries the wire form in
    HEADER = "X-RTPU-Trace"

    def __init__(self, trace_id: str, span_id: int, origin: int = 0):
        self.trace_id = trace_id
        self.span_id = span_id
        self.origin = origin

    def __repr__(self):
        return (f"TraceContext({self.trace_id!r}, {self.span_id}, "
                f"origin={self.origin})")

    def __eq__(self, other):
        return (isinstance(other, TraceContext)
                and other.trace_id == self.trace_id
                and other.span_id == self.span_id)

    def __hash__(self):
        # defining __eq__ alone would set __hash__ = None — a "value
        # object" that can't key a set/dict is a trap for callers
        # deduplicating captured contexts
        return hash((self.trace_id, self.span_id))

    # ---- wire form (the X-RTPU-Trace header payload) ----

    def to_wire(self) -> str:
        """Compact header payload: ``trace_id;span_id;origin``. Trace ids
        are already process-unique strings (pid + urandom prefix), so the
        receiving process joins the trace by value — no id translation."""
        return f"{self.trace_id};{self.span_id:x};{self.origin}"

    @classmethod
    def from_wire(cls, raw: str | None) -> "TraceContext | None":
        """Parse a wire form back into a context. Tolerant: anything
        malformed (truncated header, non-hex span id, empty string)
        returns None — an observability header must never be able to
        fail a request."""
        if not raw:
            return None
        parts = str(raw).strip().split(";")
        if len(parts) != 3 or not parts[0]:
            return None
        try:
            return cls(parts[0], int(parts[1], 16), int(parts[2]))
        except ValueError:
            return None


class _Adoption:
    """Context manager returned by ``Tracer.adopt``: installs ``ctx`` as
    the thread's ambient trace context and restores the previous one on
    exit — exception-safe (restore happens in ``__exit__`` regardless),
    and re-entrant (adoptions nest, each restoring its own prior)."""

    __slots__ = ("_tracer", "_ctx", "_prev", "_prev_active", "_tid")

    def __init__(self, tracer: "Tracer", ctx: "TraceContext | None"):
        self._tracer = tracer
        self._ctx = ctx
        self._prev = None
        self._prev_active = None
        self._tid = 0

    def __enter__(self):
        if self._ctx is None:
            return self
        tr = self._tracer
        local = tr._local
        self._prev = getattr(local, "adopted", None)
        local.adopted = self._ctx
        # expose the adopted context to the sampling profiler even while
        # no span is open on this thread (the sample between two spans of
        # one request still belongs to that request)
        t = threading.current_thread()
        self._tid = t.ident or 0
        if not tr._stack():
            self._prev_active = tr._active.get(self._tid)
            tr._active[self._tid] = (self._ctx.trace_id, self._ctx.span_id,
                                     "(adopted)")
        return self

    def __exit__(self, *exc):
        if self._ctx is None:
            return False
        tr = self._tracer
        tr._local.adopted = self._prev
        if not tr._stack():
            if self._prev_active is not None:
                tr._active[self._tid] = self._prev_active
            else:
                tr._active.pop(self._tid, None)
        return False

#: lazily-resolved jax.profiler.TraceAnnotation (False = unavailable) —
#: jax must never be a hard dependency of this module
_ANNOTATION = None


def _annotation_cls():
    global _ANNOTATION
    if _ANNOTATION is None:
        try:
            import jax

            _ANNOTATION = jax.profiler.TraceAnnotation
        except Exception:
            _ANNOTATION = False
    return _ANNOTATION


class Span:
    """One in-flight span. Enter/exit on the SAME thread (the per-thread
    parent stack assumes it); attributes are plain JSON-able values."""

    __slots__ = ("name", "attrs", "sid", "parent", "trace", "_tracer",
                 "_tid", "_t0", "_ann")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self._tracer = tracer
        self.sid = next(tracer._ids)
        self.parent = 0
        self.trace = ""
        self._tid = 0
        self._t0 = 0
        self._ann = None

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        tr = self._tracer
        t = threading.current_thread()
        self._tid = t.ident or 0
        if tr._threads.get(self._tid) != t.name:
            # not just first-seen: thread idents are RECYCLED by the OS,
            # and pools rename threads — a stale entry would label this
            # thread's track with a dead thread's name in every export
            tr._note_thread(self._tid, t.name)
        stack = tr._stack()
        if stack:
            top = stack[-1]
            self.parent = top.sid
            self.trace = top.trace
        else:
            ctx = getattr(tr._local, "adopted", None)
            if ctx is not None:
                # a pool handoff: parent to the captured span on the
                # submitting thread, join its trace
                self.parent = ctx.span_id
                self.trace = ctx.trace_id
            else:
                self.trace = tr._new_trace_id()
        stack.append(self)
        # cross-thread registry for the sampling profiler: plain dict
        # store (GIL-atomic), pruned with the thread-name map
        tr._active[self._tid] = (self.trace, self.sid, self.name)
        cls = _annotation_cls() if tr.annotate else False
        if cls:
            try:
                self._ann = cls(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, et, ev, tb):
        dur_ns = time.perf_counter_ns() - self._t0
        tr = self._tracer
        if self._ann is not None:
            try:
                self._ann.__exit__(et, ev, tb)
            except Exception:
                pass
        stack = tr._stack()
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:   # mismatched exits must not corrupt nesting
            stack.remove(self)
        if stack:
            top = stack[-1]
            tr._active[self._tid] = (top.trace, top.sid, top.name)
        else:
            ctx = getattr(tr._local, "adopted", None)
            if ctx is not None:
                tr._active[self._tid] = (ctx.trace_id, ctx.span_id,
                                         "(adopted)")
            else:
                tr._active.pop(self._tid, None)
        if et is not None:
            self.attrs["error"] = f"{et.__name__}: {ev}"
        tr._record({
            "ph": "X", "name": self.name,
            "ts": (self._t0 - tr._epoch_ns) / 1e3,     # µs, tracer epoch
            "dur": dur_ns / 1e3,
            "pid": tr._pid, "tid": self._tid,
            "sid": self.sid, "parent": self.parent,
            "trace": self.trace,
            "args": self.attrs,
        })
        return False


class Tracer:
    """Thread-safe span tracer + bounded flight recorder.

    The module-level ``TRACER`` is the process singleton every
    instrumented layer uses; tests build private instances.
    """

    def __init__(self, enabled: bool | None = None, ring: int | None = None,
                 annotate: bool = True):
        env = os.environ
        if enabled is None:
            enabled = (env.get("RTPU_TRACE", "0") not in ("", "0", "false")
                       or bool(env.get("RTPU_TRACE_DUMP")))
        if ring is None:
            try:
                ring = int(env.get("RTPU_TRACE_RING", DEFAULT_RING))
            except ValueError:
                ring = DEFAULT_RING
        self.enabled = bool(enabled)
        self.annotate = bool(annotate)
        self._ring: collections.deque = collections.deque(
            maxlen=max(16, int(ring)))
        self._ids = itertools.count(1)
        self._lock = threading.Lock()   # guards _recorded + ring append
        self._recorded = 0
        self._local = threading.local()
        self._epoch_ns = time.perf_counter_ns()
        self._epoch_unix = time.time()
        self._pid = os.getpid()
        self._threads: dict[int, str] = {}
        # tid → (trace_id, span_id, span_name) of the innermost open span
        # (or adopted context) per thread — the cross-thread read surface
        # the sampling profiler tags its samples from. Plain dict with
        # GIL-atomic per-key stores; pruned alongside _threads.
        self._active: dict[int, tuple] = {}
        # trace ids: process-unique prefix + counter — cheap (no urandom
        # per request) yet collision-free across processes in one capture
        self._trace_prefix = f"{os.getpid():x}-{os.urandom(3).hex()}"
        self._trace_ids = itertools.count(1)
        # cluster identity: which PROCESS of a multi-host deployment this
        # tracer records for. Seeded from RTPU_PROCESS_INDEX (plain
        # multi-process deployments without jax.distributed), refined by
        # cluster/bootstrap.py once jax.process_index() is known — this
        # module must stay stdlib-importable, so jax is never asked here.
        try:
            self.process_index = max(
                0, int(os.environ.get("RTPU_PROCESS_INDEX", "0") or 0))
        except ValueError:
            self.process_index = 0
        # extra dump payloads (the sampling profiler registers one):
        # name → zero-arg callable returning a JSON-able block or None
        self._aux: dict[str, object] = {}
        self._dump_dir: str | None = None   # lazy private dir for dump()

    # ---- recording ----

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _new_trace_id(self) -> str:
        return f"{self._trace_prefix}-{next(self._trace_ids):x}"

    def _prune_threads(self, referenced: set | None = None) -> None:
        """Drop name entries for threads the ring no longer references
        (dead job threads) — called from exports, and from registration
        once the map outgrows the ring it annotates. The ring and the
        name map are snapshotted via atomic C-level copies before
        iterating: concurrent span exits keep appending, and iterating
        the live deque/dict would raise mid-export. The active-span
        registry prunes on the same trigger (a dead thread can no longer
        be sampled, so its entry is pure leak)."""
        if referenced is None:
            referenced = {e["tid"] for e in list(self._ring)}
        live = {t.ident for t in threading.enumerate()}
        self._threads = {tid: name
                         for tid, name in dict(self._threads).items()
                         if tid in referenced or tid in live}
        for tid in list(self._active):
            if tid not in live:
                self._active.pop(tid, None)

    def _note_thread(self, tid: int, name: str) -> None:
        self._threads[tid] = name
        if len(self._threads) > max(256, self.ring_size):
            self._prune_threads()

    def _record(self, event: dict) -> None:
        # the bounded-deque append itself is GIL-atomic, but the recorded
        # counter must stay exact under concurrent writers (the eviction
        # count in /statusz derives from it) — one uncontended lock
        # acquire per COMPLETED span is noise next to building the event
        with self._lock:
            self._recorded += 1
            self._ring.append(event)
        # durable mirror (obs/journal.py): ring events additionally land
        # in the on-disk journal so a SIGKILLed process's final sweep
        # survives it. One environ lookup when journaling is off; the
        # emit itself is a bounded non-blocking queue append.
        if _journal.enabled():
            _journal.emit_event(event)

    def span(self, name: str, **attrs):
        """Context-manager span; no-op (and ~free) when tracing is off."""
        if not self.enabled:
            return NULL_SPAN
        return Span(self, name, attrs)

    def _ambient(self) -> tuple:
        """(trace_id, parent span id) of the calling thread's innermost
        open span, falling back to its adopted context — what instants
        and completes tag themselves with ("" / 0 when neither)."""
        st = self._stack()
        if st:
            return st[-1].trace, st[-1].sid
        ctx = getattr(self._local, "adopted", None)
        if ctx is not None:
            return ctx.trace_id, ctx.span_id
        return "", 0

    def instant(self, name: str, **attrs) -> None:
        """Zero-duration marker (watermark advances, state flips)."""
        if not self.enabled:
            return
        t = threading.current_thread()
        tid = t.ident or 0
        if self._threads.get(tid) != t.name:
            self._note_thread(tid, t.name)
        trace, _ = self._ambient()
        self._record({
            "ph": "i", "s": "t", "name": name,
            "ts": (time.perf_counter_ns() - self._epoch_ns) / 1e3,
            "pid": self._pid, "tid": tid, "trace": trace, "args": attrs,
        })

    def complete(self, name: str, dur_s: float, **attrs) -> None:
        """Record a span that already happened (e.g. a measured stall whose
        wait ran inside another primitive) as an X event ending now."""
        if not self.enabled:
            return
        t = threading.current_thread()
        tid = t.ident or 0
        if self._threads.get(tid) != t.name:
            self._note_thread(tid, t.name)
        now = time.perf_counter_ns()
        dur_ns = max(0.0, float(dur_s)) * 1e9
        trace, parent = self._ambient()
        self._record({
            "ph": "X", "name": name,
            "ts": (now - dur_ns - self._epoch_ns) / 1e3,
            "dur": dur_ns / 1e3,
            "pid": self._pid, "tid": tid, "sid": next(self._ids),
            "parent": parent, "trace": trace, "args": attrs,
        })

    # ---- cross-thread trace context ----

    def capture(self) -> TraceContext | None:
        """The calling thread's trace context (innermost open span, else
        its adopted context) — hand it to the thread that continues this
        request. None when tracing is off or nothing is open: adopt(None)
        and carry() degrade to no-ops, so capture-at-submit is always
        safe to write unconditionally."""
        if not self.enabled:
            return None
        st = self._stack()
        if st:
            return TraceContext(st[-1].trace, st[-1].sid,
                                self.process_index)
        return getattr(self._local, "adopted", None)

    def set_process_index(self, index: int) -> None:
        """Record which process of a multi-host deployment this tracer
        belongs to — called by ``cluster/bootstrap.bootstrap()`` once
        ``jax.process_index()`` is known. Captured contexts carry it as
        their origin, and ``block_steps`` tags barrier spans with it."""
        self.process_index = max(0, int(index))

    def adopt(self, ctx: TraceContext | None) -> _Adoption:
        """Install ``ctx`` as this thread's ambient trace context for the
        duration of the returned context manager. Spans opened inside
        (with no other enclosing span) parent to the captured span and
        share its trace. Exception-safe and re-entrant; ``adopt(None)``
        is a no-op."""
        return _Adoption(self, ctx)

    def carry(self, fn):
        """Wrap a zero-or-more-arg callable so it runs under the CALLING
        thread's current trace context — the one-line pool handoff:
        ``pool.submit(tracer.carry(task))``. When tracing is off or no
        context is open the callable is returned unwrapped (zero cost)."""
        ctx = self.capture()
        if ctx is None:
            return fn

        def run(*a, **kw):
            with self.adopt(ctx):
                return fn(*a, **kw)
        return run

    def active_for(self, tid: int) -> tuple | None:
        """(trace_id, span_id, span_name) of the innermost open span (or
        adopted context) on thread ``tid`` — the sampling profiler's tag
        lookup. None when that thread has nothing open."""
        return self._active.get(tid)

    # ---- lifecycle ----

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._recorded = 0

    # ---- introspection / export ----

    @property
    def ring_size(self) -> int:
        return self._ring.maxlen or 0

    @property
    def recorded(self) -> int:
        """Events seen since start/clear (≥ len(ring) once it wraps)."""
        return self._recorded

    @property
    def dropped(self) -> int:
        """Events evicted from the ring by newer ones."""
        return max(0, self._recorded - len(self._ring))

    def recent(self, n: int = 200) -> list[dict]:
        """The newest ``n`` completed events, oldest first (a snapshot —
        safe against concurrent writers)."""
        n = int(n)
        if n <= 0:
            return []
        snap = list(self._ring)
        return snap[-n:]

    def for_trace(self, trace_id: str) -> list[dict]:
        """Every buffered event of one trace, oldest first — the
        ``/tracez?trace_id=`` request-reconstruction surface (and what an
        SLO exemplar resolves to). Spans evicted from the bounded ring
        are gone; the ``recorded``/``dropped`` counters say whether the
        window still covers the request."""
        return [e for e in list(self._ring) if e.get("trace") == trace_id]

    def register_aux(self, name: str, fn) -> None:
        """Attach a zero-arg provider whose return value rides in every
        Chrome export's ``otherData`` under ``name`` (None = omit) — how
        the sampling profiler folds its collapsed stacks into the
        flight-recorder dump without spamming the span ring."""
        self._aux[str(name)] = fn

    @staticmethod
    def _flow_events(events: list[dict]) -> list[dict]:
        """Chrome flow-arrow pairs (ph ``s``/``f``) for every span whose
        parent completed on ANOTHER thread — the visible cross-thread
        handoffs (REST → job → fold workers) in Perfetto. Only pairs
        where both ends are in the snapshot can be drawn; a parent still
        open at export time simply has no arrow yet."""
        by_sid = {e["sid"]: e for e in events
                  if e.get("ph") == "X" and "sid" in e}
        flows = []
        for e in events:
            if e.get("ph") != "X" or not e.get("parent"):
                continue
            p = by_sid.get(e["parent"])
            if p is None or p["tid"] == e["tid"]:
                continue
            ts = min(p["ts"], e["ts"])
            flows.append({"ph": "s", "cat": "handoff", "name": "handoff",
                          "id": e["sid"], "pid": e["pid"],
                          "tid": p["tid"], "ts": ts})
            flows.append({"ph": "f", "bp": "e", "cat": "handoff",
                          "name": "handoff", "id": e["sid"],
                          "pid": e["pid"], "tid": e["tid"], "ts": e["ts"]})
        return flows

    def chrome_trace(self) -> dict:
        """Perfetto / chrome://tracing compatible trace-event JSON dict:
        the ring's events plus thread-name metadata (one track per
        thread). Only threads the CURRENT ring references get a metadata
        row — a long-lived server churns through one thread per job, and
        emitting (or retaining, see ``_prune_threads``) every thread ever
        seen would grow without bound."""
        events = list(self._ring)   # atomic snapshot — writers keep going
        referenced = {e["tid"] for e in events}
        self._prune_threads(referenced)
        meta = [{
            "ph": "M", "name": "thread_name", "pid": self._pid, "tid": tid,
            "args": {"name": name},
        } for tid, name in sorted(dict(self._threads).items())
            if tid in referenced]
        other = {
            "epoch_unix": self._epoch_unix,
            "recorded": self._recorded,
            "dropped": self.dropped,
        }
        for name, fn in dict(self._aux).items():
            try:
                block = fn()
            except Exception:   # an aux provider must never break a dump
                block = None
            if block is not None:
                other[name] = block
        return {
            "traceEvents": meta + self._flow_events(events) + events,
            "displayTimeUnit": "ms",
            "otherData": other,
        }

    def dump(self, path: str | None = None) -> str:
        """Write the Chrome trace JSON to ``path`` and return the path.
        The default is one STABLE per-process file, overwritten on each
        call — a monitor polling ``/tracez?dump=1`` must refresh a
        snapshot, not accumulate thousands of files — inside a private
        mkdtemp (mode 0700) directory: a predictable world-writable /tmp
        name would let another local user pre-plant a symlink and turn
        the remotely-triggerable dump into a file-clobber primitive."""
        if path is None:
            if self._dump_dir is None:
                self._dump_dir = tempfile.mkdtemp(prefix="rtpu_trace_")
            path = os.path.join(self._dump_dir, "trace.json")
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f, default=str)
        return path

    def status(self) -> dict:
        return {
            "enabled": self.enabled,
            "ring_size": self.ring_size,
            "recorded": self._recorded,
            "buffered": len(self._ring),
            "dropped": self.dropped,
        }


#: process-wide tracer every instrumented layer records into
TRACER = Tracer()


def span(name: str, **attrs):
    """Module-level convenience for ``TRACER.span``."""
    return TRACER.span(name, **attrs)


def block_steps(fn):
    """Run ``fn() -> (value, steps)`` — a device barrier where a compiled
    program's results land — under ONE ``superstep.block`` span carrying
    the superstep count. The single definition of the barrier span shared
    by the engine layer (``bsp.run``) and every jobs-layer emit path."""
    with TRACER.span("superstep.block",
                     process=TRACER.process_index) as sp:
        value, steps = fn()
        steps = int(steps)
        sp.set(steps=steps)
    return value, steps


_dump_path = os.environ.get("RTPU_TRACE_DUMP")
if _dump_path:
    # the shared exit-artifact registry (obs/exitdump.py): one atexit
    # hook + one guarded SIGTERM handler for EVERY RTPU_*_DUMP writer
    from . import exitdump as _exitdump

    def _dump_at_exit(path=_dump_path):
        if len(TRACER._ring):
            TRACER.dump(path)

    _exitdump.register("trace", _dump_at_exit)
