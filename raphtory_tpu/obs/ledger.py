"""Per-query resource ledger + XLA kernel cost registry.

The tracing layer (``obs/trace.py``) answers *when* time goes and the
fold metrics answer *one* subsystem; nothing could answer "what did this
query cost, which resource is it bound on, and did HEAD regress?" — the
accounting the serving scheduler (admission control sized by measured
cost) and the PCPM kernel work (per-kernel HBM-bytes evidence that the
hop kernels are gather-bound, arXiv:1709.07122) both block on. Three
pieces:

* **Ledger** — a per-query accumulator every job carries: phase seconds
  (fold/stage/ship/compute from the sweep engines' phase breakdowns,
  plus device_wait / emit / other measured by the jobs layer), fold
  seconds by mode and fold-cache hits, H2D bytes + stall seconds
  (TransferEngine deltas), per-kernel device dispatch counts with
  estimated FLOPs / bytes-accessed, queue wait, and peak host RSS.
  Jobs accept ``explain=1`` and return it with the result; the phase
  seconds (queue wait included) sum to the job's wall time by
  construction (``other`` is the explicit residual).
* **KernelRegistry** — process-wide: every compiled kernel the engines
  dispatch is registered by ``instrument()``, and ONCE per (kernel,
  argument-shape signature) the XLA ``cost_analysis()`` (FLOPs, bytes
  accessed) and ``memory_analysis()`` (temp/argument/output bytes) are
  harvested at compile time through the AOT ``lower().compile()`` path —
  which shares the in-memory XLA compilation cache with the normal
  dispatch path, so the harvest costs executable-load time, not a second
  compile. Each kernel is classified roofline-style from its arithmetic
  intensity (FLOPs per byte accessed) against the backend's ridge point.
* **Capability probes** — ``cost_analysis``/``memory_analysis`` may
  return None or raise on some backends/jaxlib versions; the probe runs
  once, harvesting never propagates an exception, and the ledger
  degrades to host-side accounting (kernels report ``bound="unknown"``)
  rather than ever failing a sweep.

Roofline classification rule (documented in docs/OBSERVABILITY.md):
``intensity = flops / bytes_accessed``; a kernel is ``hbm_bound`` when
intensity is below the backend ridge (peak FLOP/s ÷ peak memory
bandwidth), else ``compute_bound``. The query-level ``bound`` is
``host_bound`` / ``h2d_bound`` when the fold / ship phase dominates wall
time, else the dominant kernel's roofline bound.

Knobs
-----
* ``RTPU_LEDGER`` — per-query cost accounting (default on; ``0``
  disables collection, the bench A/B arm).
* ``RTPU_LEDGER_XLA`` — compile-time XLA cost/memory harvest (default
  on; ``0`` forces host-side-only accounting).
* ``RTPU_LEDGER_RIDGE`` — override the roofline ridge point
  (flops/byte) when the built-in per-backend operating points are wrong
  for the hardware.
"""

from __future__ import annotations

import collections
import contextlib
import os
import resource
import sys
import threading
import time

from ..analysis.sanitizer import (note_shared as _san_note,
                                  track_shared as _san_track)
from . import device as _device
from .trace import TRACER

#: (peak FLOP/s, peak memory bandwidth B/s) operating points per backend —
#: order-of-magnitude roofline anchors, not measured calibration (the
#: TPU row matches bench.py's v5e-class constants). Override the derived
#: ridge with RTPU_LEDGER_RIDGE.
_PEAKS = {
    "tpu": (197e12, 819e9),     # v5e-class bf16 peak / HBM bandwidth
    "gpu": (1e14, 2e12),
    "cpu": (1e11, 2e10),        # few-core container class
}
_DEFAULT_PLATFORM = "cpu"


def _enabled() -> bool:
    """Collection gate, re-read per call so the bench A/B (and operators)
    can flip ``RTPU_LEDGER`` without a restart."""
    return os.environ.get("RTPU_LEDGER", "1") not in ("", "0", "false")


def collection_enabled() -> bool:
    """Public alias of the ``RTPU_LEDGER`` gate — the jobs layer checks
    it before publishing (metrics, /costz ring, instants), so disabling
    collection silences every ledger surface."""
    return _enabled()


def _xla_enabled() -> bool:
    return os.environ.get("RTPU_LEDGER_XLA", "1") not in ("", "0", "false")


def _rss_peak_bytes() -> int:
    """Lifetime peak RSS (ru_maxrss is KiB on Linux, bytes on macOS) —
    stdlib-only so the ledger imports in stripped environments."""
    try:
        peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(peak if sys.platform == "darwin" else peak * 1024)
    except Exception:
        return 0


# --------------------------------------------------------------- XLA caps

_CAPS: dict = {}
_CAPS_LOCK = threading.Lock()


def _cost_dict(compiled):
    """Tolerant ``cost_analysis()`` extraction: older jaxlibs return a
    one-element list of dicts, newer ones a dict; either may be None."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return ca if isinstance(ca, dict) else None


def xla_analysis_caps() -> dict:
    """Probe-once capability check for compile-time cost/memory harvest.
    On backends/jaxlib versions where the analyses raise or return None
    the ledger degrades to host-side accounting — a sweep must never fail
    because its accounting layer couldn't introspect the executable."""
    with _CAPS_LOCK:
        if _CAPS:
            return dict(_CAPS)
    caps = {"cost": False, "memory": False,
            "platform": _DEFAULT_PLATFORM, "probed": True}
    if _xla_enabled():
        try:
            import jax

            caps["platform"] = jax.devices()[0].platform
            fn = jax.jit(lambda x: x * 2.0 + 1.0)
            comp = fn.lower(
                jax.ShapeDtypeStruct((8,), "float32")).compile()
            ca = _cost_dict(comp)
            caps["cost"] = ca is not None and "flops" in ca
            ma = comp.memory_analysis()
            caps["memory"] = (ma is not None
                              and hasattr(ma, "temp_size_in_bytes"))
        except Exception as e:   # probe failure == capability absent
            caps["error"] = f"{type(e).__name__}: {e}"[:200]
    else:
        caps["disabled"] = True
    with _CAPS_LOCK:
        _CAPS.clear()
        _CAPS.update(caps)
    return dict(caps)


def reset_xla_caps() -> None:
    """Forget the probe result (tests flip RTPU_LEDGER_XLA and re-probe)."""
    with _CAPS_LOCK:
        _CAPS.clear()


def ridge_flops_per_byte(platform: str | None = None) -> float:
    """Roofline ridge point for ``platform`` (default: the probed one)."""
    v = os.environ.get("RTPU_LEDGER_RIDGE")
    if v is not None:
        try:
            return max(1e-6, float(v))
        except ValueError:
            pass
    if platform is None:
        platform = xla_analysis_caps().get("platform", _DEFAULT_PLATFORM)
    flops, bw = _PEAKS.get(platform, _PEAKS[_DEFAULT_PLATFORM])
    return flops / bw


def classify_roofline(flops, bytes_accessed,
                      platform: str | None = None) -> str:
    """``hbm_bound`` | ``compute_bound`` | ``unknown`` from harvested
    cost-analysis numbers — the ONE place the classification rule lives
    (docs/OBSERVABILITY.md documents it verbatim)."""
    if not flops or not bytes_accessed:
        return "unknown"
    intensity = float(flops) / float(bytes_accessed)
    return ("compute_bound"
            if intensity >= ridge_flops_per_byte(platform) else "hbm_bound")


# --------------------------------------------------------- kernel registry


def _sig_of(args) -> tuple:
    """Cheap argument-shape signature: shape+dtype for array-likes (never
    materialises device data), type name for python scalars."""
    sig = []
    for a in args:
        shape = getattr(a, "shape", None)
        dt = getattr(a, "dtype", None)
        if shape is not None and dt is not None:
            sig.append(f"{dt}{list(shape)}")
        else:
            sig.append(f"py:{type(a).__name__}")
    return tuple(sig)


class KernelRegistry:
    """Process-wide registry of every compiled kernel the engines
    dispatch: one record per (kernel name, argument-shape signature),
    carrying harvested XLA cost/memory analysis, the roofline
    classification, and lifetime dispatch counts."""

    def __init__(self):
        self._lock = threading.Lock()
        self._kernels: dict[tuple, dict] = {}
        #: keys whose record was created but not yet harvested — the
        #: dispatch wrapper's harvest trigger. Lives HERE (not in a
        #: per-wrapper seen-set) so a cap-evicted key re-harvests when
        #: traffic brings it back: the estimates died with the record
        self._pending_harvest: set[tuple] = set()
        #: entries dropped by the RTPU_KERNEL_REGISTRY_CAP bound —
        #: shape-diverse request traffic must not grow the registry
        #: without bound (rtpulint RT011)
        self.evictions = 0
        # lockset-sanitizer registration (None unless RTPU_SANITIZE):
        # every registry access reports its held lockset — an unguarded
        # path shows up as a shared-state-race finding
        self._san_tracker = _san_track("kernel_registry")

    def _note_shared(self, write: bool) -> None:
        _san_note(self._san_tracker, write)

    @staticmethod
    def _new_record(name: str, sig: tuple) -> dict:
        return {
            "kernel": name, "sig": "×".join(sig),
            "dispatches": 0, "mode": "host", "bound": "unknown",
            "flops": None, "bytes_accessed": None,
            "temp_bytes": None, "argument_bytes": None,
            "output_bytes": None, "intensity": None,
            "est_hbm_bytes": None, "bound_refined": None,
        }

    def _create_locked(self, key: tuple) -> tuple[dict, list]:
        """Insert a fresh record for ``key`` (caller holds the lock and
        verified absence) and run the LRU cap eviction (every touch
        re-inserts at the back, so the front is the COLDEST key, not the
        first-registered — a hot kernel's estimates survive). Returns
        (record, evicted keys); the caller runs the device-plane timing
        hook on the evicted keys AFTER releasing the lock."""
        rec = self._kernels[key] = self._new_record(*key)
        evicted = _device.evict_past_cap(
            self._kernels, _device.registry_cap(), key)
        self.evictions += len(evicted)
        for old in evicted:
            self._pending_harvest.discard(old)
        return rec, evicted

    def _ensure(self, name: str, sig: tuple) -> dict:
        key = (name, sig)
        evicted: list[tuple] = []
        with self._lock:
            self._note_shared(write=True)
            rec = self._kernels.get(key)
            if rec is None:
                rec, evicted = self._create_locked(key)
                self._pending_harvest.add(key)
            else:
                self._kernels[key] = self._kernels.pop(key)  # LRU touch
        for old in evicted:
            _device.TIMING.evict(old)
        return rec

    def touch(self, name: str, sig: tuple) -> tuple[dict, bool]:
        """The dispatch wrapper's pre-call, ONE lock acquisition:
        get-or-create the record, LRU-touch it, and report whether it
        still needs its harvest (consumed here — exactly once per LIVE
        record). Registry-owned freshness (not a per-wrapper seen-set):
        a key whose record was cap-evicted re-harvests when traffic
        brings it back, instead of serving host-mode Nones forever."""
        key = (name, sig)
        evicted: list[tuple] = []
        fresh = False
        with self._lock:
            self._note_shared(write=True)
            rec = self._kernels.get(key)
            if rec is None:
                rec, evicted = self._create_locked(key)
                fresh = True   # created-and-consumed in one step
            else:
                self._kernels[key] = self._kernels.pop(key)  # LRU touch
                if key in self._pending_harvest:   # _ensure-created rec
                    self._pending_harvest.discard(key)
                    fresh = True
        for old in evicted:
            _device.TIMING.evict(old)
        return rec, fresh

    def needs_harvest(self, name: str, sig: tuple) -> bool:
        """``touch``'s freshness flag alone (tests + direct callers)."""
        return self.touch(name, sig)[1]

    def record_dispatch(self, rec: dict) -> None:
        """Count one dispatch on an already-touched record — the
        wrapper's post-call, one lock acquisition (``touch`` did the
        lookup; re-resolving the key would double the hot-path cost)."""
        with self._lock:
            self._note_shared(write=True)
            rec["dispatches"] += 1

    def harvest(self, name: str, sig: tuple, fn, args,
                traffic: dict | None = None) -> dict:
        """Harvest ``cost_analysis``/``memory_analysis`` for one compiled
        (kernel, shapes) through the AOT path — BEFORE the dispatch call,
        so donated buffers are still alive for tracing. Never raises:
        any failure leaves the record in host-side mode.

        ``traffic`` is an optional ENGINE-SIDE DRAM traffic model
        (``ops/partition.edge_traffic_model``): XLA's ``bytes_accessed``
        sums logical operand bytes and is blind to access LOCALITY, so a
        partition-binned kernel that turns random cacheline traffic into
        cache-resident streams harvests the same (or higher) logical
        bytes. The model supplies ``est_hbm_bytes`` — what the kernel is
        expected to move through DRAM — and the record carries BOTH, plus
        a ``bound_refined`` classification over the modelled bytes
        (docs/OBSERVABILITY.md "Cost ledger")."""
        rec = self._ensure(name, sig)
        if traffic:
            with self._lock:
                rec["traffic_model"] = dict(traffic)
                rec["est_hbm_bytes"] = int(
                    traffic.get("est_hbm_bytes") or 0) or None
        caps = xla_analysis_caps()
        if not (caps["cost"] or caps["memory"]):
            return rec
        try:
            t0 = time.perf_counter()
            # the ONE compile site of the registry (shares the in-memory
            # XLA cache with the dispatch path) — spanned + recorded so
            # compile counts/seconds/shape-sigs are observable and a
            # request-path recompile burst is a detectable storm
            # (obs/device.py compile plane)
            with TRACER.span("xla.compile", kernel=name,
                             sig="×".join(sig)):
                compiled = fn.lower(*args).compile()
            harvest_s = time.perf_counter() - t0
            _device.note_compile(name, "×".join(sig), harvest_s)
            updates: dict = {"mode": "xla",
                             "harvest_seconds": round(harvest_s, 4)}
            if caps["cost"]:
                ca = _cost_dict(compiled)
                if ca is not None:
                    updates["flops"] = float(ca.get("flops") or 0.0)
                    updates["bytes_accessed"] = float(
                        ca.get("bytes accessed") or 0.0)
            if caps["memory"]:
                ma = compiled.memory_analysis()
                if ma is not None:
                    updates["temp_bytes"] = int(ma.temp_size_in_bytes)
                    updates["argument_bytes"] = int(
                        ma.argument_size_in_bytes)
                    updates["output_bytes"] = int(ma.output_size_in_bytes)
            flops = updates.get("flops")
            nbytes = updates.get("bytes_accessed")
            if flops and nbytes:
                updates["intensity"] = round(flops / nbytes, 4)
            updates["bound"] = classify_roofline(flops, nbytes,
                                                 caps.get("platform"))
            hbm = (rec.get("est_hbm_bytes") if traffic
                   else (int(nbytes) if nbytes else None))
            if not traffic:
                updates["est_hbm_bytes"] = hbm
            updates["bound_refined"] = classify_roofline(
                flops, hbm, caps.get("platform"))
            if flops and hbm:
                updates["intensity_refined"] = round(flops / hbm, 4)
            with self._lock:
                rec.update(updates)
            TRACER.instant("ledger.kernel", kernel=name,
                           bound=rec["bound"], flops=rec["flops"],
                           bytes_accessed=rec["bytes_accessed"])
        except Exception as e:   # harvest must never fail a sweep
            with self._lock:
                rec["harvest_error"] = f"{type(e).__name__}: {e}"[:200]
        return rec

    def snapshot(self) -> list[dict]:
        with self._lock:
            self._note_shared(write=False)
            return [dict(r) for r in self._kernels.values()]

    @staticmethod
    def bound_counts(records: list[dict]) -> dict:
        """Kernel count per roofline bound over an ALREADY-TAKEN
        ``snapshot()`` — so /statusz and /costz copy the table once."""
        out: dict[str, int] = {}
        for rec in records:
            out[rec["bound"]] = out.get(rec["bound"], 0) + 1
        return out

    def clear(self) -> None:
        with self._lock:
            self._kernels.clear()
            self._pending_harvest.clear()


#: the process singleton every instrumented engine records into
REGISTRY = KernelRegistry()


class InstrumentedKernel:
    """Wrapper the engine compiled-program caches return: dispatch goes
    straight through to the jitted callable (donation, async dispatch and
    the C++ fast path untouched), while the wrapper counts the dispatch
    into the registry and the active query ledger, and harvests XLA
    analysis once per LIVE (kernel, argument-shape-signature) registry
    record (a cap-evicted signature re-harvests on return). With
    ``RTPU_LEDGER=0`` the wrapper is a single env-read passthrough."""

    __slots__ = ("name", "fn", "traffic")

    def __init__(self, name: str, fn, traffic: dict | None = None):
        self.name = name
        self.fn = fn
        self.traffic = traffic

    def __call__(self, *args):
        if not _enabled():
            return self.fn(*args)
        sig = _sig_of(args)
        # freshness is REGISTRY-owned (not a per-wrapper seen-set): a
        # cap-evicted (kernel, sig) whose traffic returns re-harvests
        # instead of serving host-mode Nones forever, and the wrapper
        # carries no per-shape state of its own (RT011). One lock
        # acquisition pre-call (touch), one post-call (record_dispatch).
        rec, fresh = REGISTRY.touch(self.name, sig)
        if fresh:
            # BEFORE the dispatch: donated buffers must still be alive
            # when lower() traces; the AOT compile lands in (or seeds)
            # the same in-memory XLA cache the call below hits
            REGISTRY.harvest(self.name, sig, self.fn, args,
                             traffic=self.traffic)
        # sampled timed dispatch (obs/device.py): a sampled call blocks
        # until the result is ready and records wall device seconds —
        # sampling because an always-on sync would destroy the transfer
        # pipelining; cold (first-ever) samples are recorded apart
        timed, cold = _device.TIMING.should_sample(self.name, sig)
        if timed:
            t0 = time.perf_counter()
        out = self.fn(*args)
        measured = False
        seconds = 0.0
        if timed and _device.block_ready(out):
            # a FAILED sync is a lost sample, never an observation: the
            # unsynced duration is enqueue time and would poison the
            # percentiles the divergence/bound_measured math reads
            seconds = time.perf_counter() - t0
            measured = True
            _device.TIMING.observe(self.name, sig, seconds, cold=cold)
        REGISTRY.record_dispatch(rec)
        led = current()
        if led is not None:
            led.count_dispatch(self.name, rec)
            if measured and not cold:
                led.count_measured(self.name, seconds)
                # the synced instant is also the cheapest honest moment
                # to read the device-memory counter into the query
                snap = _device.memory_snapshot()
                if snap.get("available"):
                    led.note_device_memory(snap["bytes_in_use"])
        return out

    # the REST compile-cache introspection walks factories; keep the
    # wrapped callable reachable for debugging
    def __repr__(self):
        return f"InstrumentedKernel({self.name!r})"


def instrument(name: str, fn,
               traffic: dict | None = None) -> InstrumentedKernel:
    """Wrap a jitted callable for the kernel registry — what every
    compiled-program cache in ``engine/`` returns. ``traffic`` is an
    optional engine-side DRAM traffic model recorded next to the XLA
    harvest (see :meth:`KernelRegistry.harvest`)."""
    return InstrumentedKernel(name, fn, traffic)


# ---------------------------------------------------------------- ledger


class Ledger:
    """Per-query resource accumulator — thread-safe (fold workers and the
    dispatch thread may record concurrently). ``merge()`` folds another
    ledger's accounting in — the sub-ledger path: every completed job's
    ledger merges into its tenant's long-lived account
    (``obs/workload.py``), and the serving-scheduler tentpole's
    cross-tenant batches will merge per-unit sub-ledgers the same way."""

    def __init__(self, query_id: str = "", algorithm: str = ""):
        self._lock = threading.Lock()
        self.query_id = query_id
        self.algorithm = algorithm
        #: normalized tenant identity (obs/workload.py) — set by the jobs
        #: layer at submit; "" for ledgers created outside the jobs path
        self.tenant = ""
        #: trace id of the owning request's span tree ("" untraced) —
        #: set by the jobs layer so /costz ledgers join /tracez traces
        self.trace_id = ""
        self.created_unix = time.time()
        self.queue_wait_seconds = 0.0
        self.wall_seconds = 0.0
        self.status = "running"
        self.phase_seconds: dict[str, float] = {}
        self.fold_mode_seconds: dict[str, float] = {}
        self.fold_cache_hits = 0
        self.fold_cache_misses = 0
        self.h2d_bytes = 0
        self.h2d_stall_seconds: dict[str, float] = {}
        # cross-shard collective traffic by comm route (halo /
        # all_gather / replicate) — the refined DCN/ICI bytes column
        # next to est HBM bytes (parallel/sharded.py exchange accounting)
        self.dcn: dict[str, dict] = {}
        self.kernels: dict[str, dict] = {}
        self.sweeps = 0
        self.views = 0
        self.supersteps = 0
        self.hops = 0
        self.peak_rss_bytes = 0
        #: max device bytes-in-use observed at sampled timed dispatches
        #: (+ one read at finish) — 0 on backends without memory_stats
        self.peak_device_bytes = 0
        #: set by the serving scheduler when this query's views rode a
        #: COALESCED cross-request dispatch (jobs/scheduler.py): batch
        #: id, member count, this query's column share — the explain
        #: surface's proof of which batch served it
        self.coalesced: dict | None = None

    # ---- recording ----

    def add_phase(self, phase: str, seconds: float) -> None:
        with self._lock:
            self.phase_seconds[phase] = (
                self.phase_seconds.get(phase, 0.0) + float(seconds))

    def fold_cache_event(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.fold_cache_hits += 1
            else:
                self.fold_cache_misses += 1

    def add_sweep(self, phases: dict, ship_delta: dict, ship_bytes: int,
                  n_hops: int, fold_modes: dict | None = None) -> None:
        """One sweep's phase breakdown (``sweep_phase_summary`` output) +
        transfer-engine deltas — called by both sweep engines on the
        dispatch thread."""
        with self._lock:
            for ph, sec in phases.items():
                self.phase_seconds[ph] = (
                    self.phase_seconds.get(ph, 0.0) + float(sec))
            self.h2d_bytes += int(ship_delta.get("bytes_shipped", 0) or 0)
            for stage in ("stage", "wire"):
                sec = float(ship_delta.get(f"{stage}_stall_seconds", 0.0)
                            or 0.0)
                if sec:
                    self.h2d_stall_seconds[stage] = (
                        self.h2d_stall_seconds.get(stage, 0.0) + sec)
            self.sweeps += 1
            self.hops += int(n_hops)
            if fold_modes:
                for mode, sec in fold_modes.items():
                    self.fold_mode_seconds[mode] = (
                        self.fold_mode_seconds.get(mode, 0.0) + float(sec))

    def add_dcn(self, route: str, *, rows: int, bytes_: int) -> None:
        """One sharded dispatch's cross-shard exchange accounting
        (``parallel/sharded.py``): estimated rows/bytes the collective
        moved on ``route`` (halo / all_gather / replicate). Lands in the
        ``dcn`` block of the ledger dict and the per-algorithm
        ``raphtory_query_cost_dcn_bytes_total`` counter at publish."""
        with self._lock:
            d = self.dcn.get(route)
            if d is None:
                d = self.dcn[route] = {"dispatches": 0, "rows": 0,
                                       "bytes": 0}
            d["dispatches"] += 1
            d["rows"] += max(0, int(rows))
            d["bytes"] += max(0, int(bytes_))

    def count_dispatch(self, name: str, rec: dict) -> None:
        with self._lock:
            k = self.kernels.get(name)
            if k is None:
                k = self.kernels[name] = {
                    "dispatches": 0, "est_flops": 0.0,
                    "est_bytes_accessed": 0.0, "est_hbm_bytes": 0.0,
                    "bound": "unknown"}
            k["dispatches"] += 1
            k["est_flops"] += float(rec.get("flops") or 0.0)
            k["est_bytes_accessed"] += float(
                rec.get("bytes_accessed") or 0.0)
            # the locality-aware per-dispatch traffic estimate (falls
            # back to the logical XLA bytes when no model is attached)
            k["est_hbm_bytes"] += float(
                rec.get("est_hbm_bytes")
                or rec.get("bytes_accessed") or 0.0)
            k["bound"] = rec.get("bound", "unknown")
            if rec.get("bound_refined"):
                k["bound_refined"] = rec["bound_refined"]

    def count_measured(self, name: str, seconds: float) -> None:
        """One sampled timed dispatch's measured wall device seconds
        (obs/device.py) — joins the kernel's estimate columns so
        ``explain:1`` carries measured next to estimated."""
        with self._lock:
            k = self.kernels.get(name)
            if k is None:
                k = self.kernels[name] = {
                    "dispatches": 0, "est_flops": 0.0,
                    "est_bytes_accessed": 0.0, "est_hbm_bytes": 0.0,
                    "bound": "unknown"}
            k["measured_seconds"] = round(
                k.get("measured_seconds", 0.0) + float(seconds), 6)
            k["timed_dispatches"] = k.get("timed_dispatches", 0) + 1

    def note_device_memory(self, bytes_in_use: int) -> None:
        with self._lock:
            self.peak_device_bytes = max(self.peak_device_bytes,
                                         int(bytes_in_use))

    def count_views(self, n: int = 1) -> None:
        with self._lock:
            self.views += int(n)

    def count_supersteps(self, n: int) -> None:
        with self._lock:
            self.supersteps += max(0, int(n))

    def merge(self, other: "Ledger") -> "Ledger":
        """Fold ``other``'s accounting into this ledger (parallel fold
        workers / sub-unit ledgers). Scalar maxima (peak RSS) take the
        max; everything else sums."""
        with other._lock:
            snap = other._unlocked_dict()
        with self._lock:
            for ph, sec in snap["phase_seconds"].items():
                self.phase_seconds[ph] = (
                    self.phase_seconds.get(ph, 0.0) + sec)
            for mode, sec in snap["fold"]["seconds_by_mode"].items():
                self.fold_mode_seconds[mode] = (
                    self.fold_mode_seconds.get(mode, 0.0) + sec)
            self.fold_cache_hits += snap["fold"]["cache_hits"]
            self.fold_cache_misses += snap["fold"]["cache_misses"]
            self.h2d_bytes += snap["h2d"]["bytes"]
            for stage, sec in snap["h2d"]["stall_seconds"].items():
                self.h2d_stall_seconds[stage] = (
                    self.h2d_stall_seconds.get(stage, 0.0) + sec)
            for route, d in snap["dcn"]["routes"].items():
                mine = self.dcn.get(route)
                if mine is None:
                    self.dcn[route] = dict(d)
                else:
                    for k in ("dispatches", "rows", "bytes"):
                        mine[k] += d[k]
            for name, k in snap["device"]["kernels"].items():
                mine = self.kernels.get(name)
                if mine is None:
                    self.kernels[name] = dict(k)
                else:
                    mine["dispatches"] += k["dispatches"]
                    mine["est_flops"] += k["est_flops"]
                    mine["est_bytes_accessed"] += k["est_bytes_accessed"]
                    mine["est_hbm_bytes"] = (
                        mine.get("est_hbm_bytes", 0.0)
                        + k.get("est_hbm_bytes", 0.0))
                    if k.get("timed_dispatches"):
                        mine["measured_seconds"] = round(
                            mine.get("measured_seconds", 0.0)
                            + k.get("measured_seconds", 0.0), 6)
                        mine["timed_dispatches"] = (
                            mine.get("timed_dispatches", 0)
                            + k["timed_dispatches"])
            self.sweeps += snap["sweeps"]
            self.views += snap["views"]
            self.supersteps += snap["supersteps"]
            self.hops += snap["hops"]
            self.peak_rss_bytes = max(self.peak_rss_bytes,
                                      snap["host"]["peak_rss_bytes"])
            self.peak_device_bytes = max(
                self.peak_device_bytes,
                snap["device"].get("peak_device_bytes", 0))
        return self

    def absorb_share(self, batch_snap: dict, frac: float,
                     coalesced: dict | None = None) -> None:
        """Fold THIS query's share of a coalesced batch dispatch's
        accounting in (``batch_snap`` = the batch ledger's ``as_dict()``,
        ``frac`` = this query's columns / the batch's total columns —
        the scheduler's attribution rule). Divisible resources (phase
        seconds, H2D bytes, estimated FLOPs/bytes) scale by ``frac`` so
        the members' ledgers SUM to the batch's cost; per-rider counts
        (kernel dispatches, sweeps) land whole — every member's views
        did ride that one dispatch. The batch's ``other`` residual is
        skipped: each member computes its own residual at finish()."""
        frac = float(frac)
        with self._lock:
            for ph, sec in batch_snap["phase_seconds"].items():
                if ph == "other":
                    continue
                self.phase_seconds[ph] = (
                    self.phase_seconds.get(ph, 0.0) + sec * frac)
            for mode, sec in batch_snap["fold"]["seconds_by_mode"].items():
                self.fold_mode_seconds[mode] = (
                    self.fold_mode_seconds.get(mode, 0.0) + sec * frac)
            # the batch's ONE fold outcome is every member's outcome: a
            # hit means this query skipped folding too
            self.fold_cache_hits += batch_snap["fold"]["cache_hits"]
            self.fold_cache_misses += batch_snap["fold"]["cache_misses"]
            self.h2d_bytes += int(batch_snap["h2d"]["bytes"] * frac)
            for stage, sec in batch_snap["h2d"]["stall_seconds"].items():
                self.h2d_stall_seconds[stage] = (
                    self.h2d_stall_seconds.get(stage, 0.0) + sec * frac)
            for name, k in batch_snap["device"]["kernels"].items():
                mine = self.kernels.get(name)
                if mine is None:
                    mine = self.kernels[name] = {
                        "dispatches": 0, "est_flops": 0.0,
                        "est_bytes_accessed": 0.0, "est_hbm_bytes": 0.0,
                        "bound": "unknown"}
                mine["dispatches"] += k["dispatches"]
                mine["est_flops"] += k["est_flops"] * frac
                mine["est_bytes_accessed"] += (
                    k["est_bytes_accessed"] * frac)
                mine["est_hbm_bytes"] = (
                    mine.get("est_hbm_bytes", 0.0)
                    + k.get("est_hbm_bytes", 0.0) * frac)
                mine["bound"] = k.get("bound", "unknown")
                if k.get("bound_refined"):
                    mine["bound_refined"] = k["bound_refined"]
            self.sweeps += 1
            if coalesced is not None:
                self.coalesced = dict(coalesced)

    def finish(self, wall_seconds: float, status: str = "done") -> None:
        """Close the ledger: record wall time, peak RSS, and the explicit
        ``other`` residual phase so queue wait + phase seconds sum to the
        wall time exactly — the invariant /costz consumers rely on."""
        # one more device-memory read at close (outside the lock: it may
        # touch the backend) so short queries that never hit a sampled
        # dispatch still carry a peak-bytes observation where available
        dev_mem = _device.memory_snapshot()
        with self._lock:
            self.wall_seconds = float(wall_seconds)
            self.status = status
            self.peak_rss_bytes = max(self.peak_rss_bytes,
                                      _rss_peak_bytes())
            if dev_mem.get("available"):
                self.peak_device_bytes = max(self.peak_device_bytes,
                                             dev_mem["bytes_in_use"])
            known = sum(self.phase_seconds.values())
            self.phase_seconds["other"] = max(
                0.0, self.wall_seconds - self.queue_wait_seconds - known)

    # ---- classification / export ----

    def bound(self) -> str:
        """Query-level resource verdict: host_bound when the fold phase
        dominates, h2d_bound when staging/shipping does, else the
        dominant kernel's roofline bound (docs/OBSERVABILITY.md)."""
        with self._lock:
            ph = dict(self.phase_seconds)
            kernels = {n: dict(k) for n, k in self.kernels.items()}
        host = ph.get("fold", 0.0)
        h2d = ph.get("stage", 0.0) + ph.get("ship", 0.0)
        dev = (ph.get("compute", 0.0) + ph.get("device_wait", 0.0))
        top = max((host, h2d, dev))
        if top <= 0.0:
            return "unknown"
        if top == host:
            return "host_bound"
        if top == h2d:
            return "h2d_bound"
        if kernels:
            dom = max(kernels.values(),
                      key=lambda k: k["est_bytes_accessed"])
            if dom["bound"] != "unknown":
                return dom["bound"]
        return "unknown"

    def _unlocked_dict(self) -> dict:
        return {
            "query_id": self.query_id,
            "algorithm": self.algorithm,
            "tenant": self.tenant,
            "trace_id": self.trace_id,
            "status": self.status,
            "queue_wait_seconds": round(self.queue_wait_seconds, 6),
            "wall_seconds": round(self.wall_seconds, 6),
            "phase_seconds": {ph: round(s, 6)
                              for ph, s in self.phase_seconds.items()},
            "fold": {
                "seconds_by_mode": {m: round(s, 6) for m, s in
                                    self.fold_mode_seconds.items()},
                "cache_hits": self.fold_cache_hits,
                "cache_misses": self.fold_cache_misses,
            },
            "h2d": {"bytes": int(self.h2d_bytes),
                    "stall_seconds": {s: round(v, 6) for s, v in
                                      self.h2d_stall_seconds.items()}},
            "dcn": {
                "bytes": sum(d["bytes"] for d in self.dcn.values()),
                "rows": sum(d["rows"] for d in self.dcn.values()),
                "routes": {r: dict(d) for r, d in self.dcn.items()},
            },
            "device": {
                "dispatches": sum(k["dispatches"]
                                  for k in self.kernels.values()),
                "est_flops": sum(k["est_flops"]
                                 for k in self.kernels.values()),
                "est_bytes_accessed": sum(k["est_bytes_accessed"]
                                          for k in self.kernels.values()),
                # the measured half (obs/device.py): wall seconds of the
                # sampled timed dispatches + peak observed device bytes
                "measured_seconds": round(
                    sum(k.get("measured_seconds", 0.0)
                        for k in self.kernels.values()), 6),
                "timed_dispatches": sum(k.get("timed_dispatches", 0)
                                        for k in self.kernels.values()),
                "peak_device_bytes": int(self.peak_device_bytes),
                "kernels": {n: dict(k) for n, k in self.kernels.items()},
            },
            "host": {"peak_rss_bytes": int(self.peak_rss_bytes)},
            "sweeps": self.sweeps,
            "views": self.views,
            "supersteps": self.supersteps,
            "hops": self.hops,
            **({"coalesced": dict(self.coalesced)}
               if self.coalesced is not None else {}),
        }

    def as_dict(self) -> dict:
        out_bound = self.bound()
        with _CAPS_LOCK:
            caps = dict(_CAPS) if _CAPS else {"probed": False}
        with self._lock:
            out = self._unlocked_dict()
        out["bound"] = out_bound
        out["xla_analysis"] = ("harvested"
                               if caps.get("cost") or caps.get("memory")
                               else "host_only")
        return out


# ------------------------------------------------------ activation context

_ACTIVE = threading.local()


@contextlib.contextmanager
def activate(ledger: Ledger):
    """Bind ``ledger`` as THIS thread's active query ledger — engine
    layers attribute dispatches/phases to ``current()``. Thread-local by
    design: two concurrent jobs on different threads never share one."""
    prev = getattr(_ACTIVE, "ledger", None)
    _ACTIVE.ledger = ledger
    try:
        yield ledger
    finally:
        _ACTIVE.ledger = prev


def current() -> Ledger | None:
    """The active query ledger of THIS thread (None when collection is
    off or no query is in flight) — every engine-side hook goes through
    here, so a disabled ledger costs one env read + one getattr."""
    if not _enabled():
        return None
    return getattr(_ACTIVE, "ledger", None)


# -------------------------------------------------- completed-query ring

_RECENT: collections.deque = collections.deque(maxlen=64)
_RECENT_LOCK = threading.Lock()
_COMPLETED = [0]


def note_completed(ledger: Ledger) -> None:
    """Record a finished query's ledger into the bounded ring /costz
    serves, and drop a flight-recorder instant so the cost lands on the
    trace timeline next to the spans it explains."""
    snap = ledger.as_dict()
    with _RECENT_LOCK:
        _RECENT.append(snap)
        _COMPLETED[0] += 1
    TRACER.instant(
        "ledger.query", query_id=snap["query_id"],
        algorithm=snap["algorithm"], bound=snap["bound"],
        wall_seconds=snap["wall_seconds"],
        est_flops=snap["device"]["est_flops"],
        est_bytes_accessed=snap["device"]["est_bytes_accessed"],
        h2d_bytes=snap["h2d"]["bytes"])


def recent_queries(n: int = 16) -> list[dict]:
    with _RECENT_LOCK:
        snap = list(_RECENT)
    return snap[-max(0, int(n)):]


# ------------------------------------------------------------- surfaces


def status_block() -> dict:
    """The compact ``ledger`` block /statusz embeds."""
    with _CAPS_LOCK:
        caps = dict(_CAPS) if _CAPS else {"probed": False}
    kernels = REGISTRY.snapshot()
    return {
        "enabled": _enabled(),
        "xla": caps,
        "kernels": len(kernels),
        "kernels_by_bound": KernelRegistry.bound_counts(kernels),
        "kernel_registry_cap": _device.registry_cap(),
        "kernel_registry_evictions": REGISTRY.evictions,
        "queries_completed": _COMPLETED[0],
    }


def costz() -> dict:
    """The full /costz payload: probed capabilities, the roofline ridge,
    every registered kernel with its harvested analysis + classification,
    and the recent completed-query ledgers."""
    caps = xla_analysis_caps()
    kernels = sorted(REGISTRY.snapshot(),
                     key=lambda r: -(r["bytes_accessed"] or 0.0)
                     * r["dispatches"])
    return {
        "enabled": _enabled(),
        "xla": caps,
        "ridge_flops_per_byte": round(
            ridge_flops_per_byte(caps.get("platform")), 3),
        "classification_rule": (
            "intensity = flops / bytes_accessed; hbm_bound if intensity "
            "< ridge else compute_bound; unknown without harvested "
            "analysis. bound_refined repeats the rule over est_hbm_bytes "
            "— the engine-side partition-aware DRAM traffic model "
            "(ops/partition.edge_traffic_model) where one is attached, "
            "since XLA's bytes_accessed is blind to access locality"),
        "kernels": kernels,
        "kernels_by_bound": KernelRegistry.bound_counts(kernels),
        "kernels_by_bound_refined": {
            b: n for b, n in KernelRegistry.bound_counts(
                [{"bound": r.get("bound_refined") or "unknown"}
                 for r in kernels]).items()},
        "recent_queries": recent_queries(),
    }
