"""SLO error budgets — operator targets judged as multi-window burn rates.

PR 9 built the measurement (per-algorithm latency histograms with trace
exemplars, ``obs/slo.py``); this module adds the JUDGMENT: operators
declare targets in ``RTPU_SLO_TARGET`` (e.g. ``pagerank=p99:2.5s`` — at
most 1% of PageRank requests may take longer than 2.5 s) and the
registry evaluates each as an error budget:

* **Cumulative burn** — over this process's lifetime histograms:
  ``bad_fraction / allowed_fraction`` where ``allowed = 1 - quantile``.
  ``budget_remaining = 1 - burn`` (negative = overspent).
* **Windowed burn** — the alerting-grade signal. Two collectors per
  target (``slo_obs_<alg>_total`` / ``slo_bad_<alg>_total``) join the
  ``/slz`` series ring; differencing the ring over a FAST window
  (``RTPU_BUDGET_FAST_S``, default 60 s) and a SLOW window
  (``RTPU_BUDGET_SLOW_S``, default 600 s) gives the classic
  multi-window burn-rate pair: the fast window catches a cliff, the
  slow window keeps one bad minute from paging.

Grades (what ``/healthz`` serves — load balancers act on the HTTP code,
no JSON parsing needed, behind ``RTPU_HEALTH_STRICT=1``):

* ``ok`` — every target burns < 1 in both windows.
* ``degraded`` — some target burns ≥ 1 in ONE window (a blip, or a
  burn that has not yet sustained).
* ``burning`` — some target burns ≥ 1 in BOTH windows: sustained
  overspend that will exhaust the budget. HTTP 503 under strict mode.

With the series ring not running (library use, tests) both windows fall
back to the cumulative burn — a breached target then grades straight to
``burning``, which is the honest reading of "all the evidence we have
says overspent". Everything here follows the telemetry prime directive:
a malformed target, an empty histogram, or a dead ring NEVER raises —
parse errors are data (``errors`` in every payload).

Knobs
-----
* ``RTPU_SLO_TARGET`` — comma-separated ``<algorithm>=p<Q>:<latency>``
  targets (``2.5s``, ``250ms``, or bare seconds; ``pagerank=p99:2.5s``).
* ``RTPU_BUDGET_FAST_S`` / ``RTPU_BUDGET_SLOW_S`` — burn windows.
* ``RTPU_HEALTH_STRICT`` — ``1`` makes ``/healthz`` answer 503 while
  some budget is burning (default: always 200, grade in the body).
"""

from __future__ import annotations

import os
import threading
import time

from ..analysis.sanitizer import (note_shared as _san_note,
                                  track_shared as _san_track)
from .slo import _metrics
from .trace import TRACER

DEFAULT_FAST_S = 60.0
DEFAULT_SLOW_S = 600.0
#: live-evaluation cache TTL: /healthz probes, /statusz scrapes (one
#: per peer per /clusterz pass) and advisor ticks all call evaluate();
#: within a second they share one computation instead of each copying
#: the series ring and walking every histogram. The ring itself only
#: samples at 1 Hz, so a fresher answer does not exist anyway.
EVAL_CACHE_S = 1.0
#: parsed-target cap — the per-target Prometheus labels must stay
#: bounded even against a pathological RTPU_SLO_TARGET string
MAX_TARGETS = 16
_GRADE_ORDER = {"ok": 0, "degraded": 1, "burning": 2}


def _window_env(name: str, default: float) -> float:
    try:
        v = float(os.environ.get(name, "") or default)
        return v if v > 0 else default
    except ValueError:
        return default


def fast_window_s() -> float:
    return _window_env("RTPU_BUDGET_FAST_S", DEFAULT_FAST_S)


def slow_window_s() -> float:
    return _window_env("RTPU_BUDGET_SLOW_S", DEFAULT_SLOW_S)


def health_strict() -> bool:
    return os.environ.get("RTPU_HEALTH_STRICT", "0") not in ("", "0",
                                                             "false")


class Target:
    """One parsed SLO target: ``algorithm=pQ:threshold``."""

    __slots__ = ("algorithm", "quantile", "threshold_s", "raw")

    def __init__(self, algorithm: str, quantile: float, threshold_s: float,
                 raw: str):
        self.algorithm = algorithm
        self.quantile = quantile
        self.threshold_s = threshold_s
        self.raw = raw

    @property
    def allowed(self) -> float:
        """Allowed bad fraction — a p99 target tolerates 1% breaches."""
        return max(1e-9, 1.0 - self.quantile)

    def as_dict(self) -> dict:
        return {"algorithm": self.algorithm, "quantile": self.quantile,
                "threshold_s": self.threshold_s, "raw": self.raw,
                "allowed_bad_fraction": round(self.allowed, 9)}


def _parse_latency(s: str) -> float:
    s = s.strip().lower()
    if s.endswith("ms"):
        return float(s[:-2]) / 1000.0
    if s.endswith("s"):
        return float(s[:-1])
    return float(s)


def parse_targets(raw: str | None = None) -> tuple[list, list]:
    """``(targets, errors)`` from a ``RTPU_SLO_TARGET``-shaped string.
    NEVER raises — an operator typo in an env var must not take the
    health surface down; each bad entry becomes an error string."""
    if raw is None:
        raw = os.environ.get("RTPU_SLO_TARGET", "")
    targets: list[Target] = []
    errors: list[str] = []
    seen: set[str] = set()
    for entry in str(raw).split(","):
        entry = entry.strip()
        if not entry:
            continue
        if len(targets) >= MAX_TARGETS:
            errors.append(f"{entry!r}: past the {MAX_TARGETS}-target cap")
            continue
        try:
            alg, spec = entry.split("=", 1)
            qs, thr = spec.split(":", 1)
            qs = qs.strip().lower()
            if not qs.startswith("p"):
                raise ValueError(f"quantile {qs!r} must look like p99")
            q = float(qs[1:]) / 100.0
            if not 0.0 < q < 1.0:
                raise ValueError(f"quantile {qs!r} outside (p0, p100)")
            threshold = _parse_latency(thr)
            if threshold <= 0:
                raise ValueError(f"threshold {thr!r} must be positive")
            alg = alg.strip().lower()
            if not alg:
                raise ValueError("empty algorithm")
            if alg in seen:
                raise ValueError(f"duplicate target for {alg!r}")
            seen.add(alg)
            targets.append(Target(alg, q, threshold, entry))
        except (ValueError, IndexError) as e:
            errors.append(f"{entry!r}: {e}")
    return targets, errors


def window_burn(rows: list, algorithm: str, now: float, window_s: float,
                allowed: float, prefix: str = "slo") -> float | None:
    """Burn rate over series-ring ``rows`` inside ``[now - window_s,
    now]``: (breaches / observations in the window) / allowed. ``None``
    when the window holds fewer than two usable samples (nothing to
    difference — the ring may be off or younger than the window);
    ``0.0`` when the window saw no traffic (no requests burn nothing).
    Pure over its inputs so the burn math tests under injected clocks.
    ``prefix`` selects the collector family: ``slo`` (latency budgets,
    this module) or ``fresh`` (staleness budgets, obs/freshness.py)."""
    obs_name = f"{prefix}_obs_{algorithm}_total"
    bad_name = f"{prefix}_bad_{algorithm}_total"
    inside = [r for r in rows
              if r.get("unix", 0.0) >= now - window_s
              and r.get(obs_name) is not None
              and r.get(bad_name) is not None]
    if len(inside) < 2:
        return None
    d_obs = inside[-1][obs_name] - inside[0][obs_name]
    d_bad = inside[-1][bad_name] - inside[0][bad_name]
    if d_obs <= 0:
        return 0.0
    return max(0.0, d_bad / d_obs) / allowed


def judge_target(t: Target, rows: list, now: float, fast_s: float,
                 slow_s: float, totals_below, prefix: str = "slo"
                 ) -> tuple[dict, str, float, float]:
    """One target's full burn judgment — the grading core BOTH budget
    planes share (latency here, staleness in obs/freshness.py), so the
    burn math and the 2-of-2 grade ladder can never diverge between
    them. ``totals_below(algorithm, threshold_s) -> (total, good)`` is
    the plane's histogram walk; returns ``(row, grade, eff_fast,
    eff_slow)`` where the eff burns fall back to the cumulative burn
    when a window has no usable ring samples (dead/young ring — the
    honest reading of "all the evidence we have")."""
    total, good = totals_below(t.algorithm, t.threshold_s)
    bad = total - good
    cum = ((bad / total) / t.allowed) if total else 0.0
    fast = window_burn(rows, t.algorithm, now, fast_s, t.allowed,
                       prefix=prefix)
    slow = window_burn(rows, t.algorithm, now, slow_s, t.allowed,
                       prefix=prefix)
    eff_fast = cum if fast is None else fast
    eff_slow = cum if slow is None else slow
    if eff_fast >= 1.0 and eff_slow >= 1.0:
        grade = "burning"
    elif eff_fast >= 1.0 or eff_slow >= 1.0:
        grade = "degraded"
    else:
        grade = "ok"
    row = dict(t.as_dict())
    row.update({
        "observations": total, "breaches": bad,
        "cumulative_burn": round(cum, 4),
        "budget_remaining": round(1.0 - cum, 4),
        "fast_burn": None if fast is None else round(fast, 4),
        "slow_burn": None if slow is None else round(slow, 4),
        "windows_seconds": [fast_s, slow_s],
        "grade": grade,
    })
    return row, grade, eff_fast, eff_slow


def _retire(alg: str) -> None:
    """Drop a no-longer-targeted algorithm's ring collectors and
    Prometheus burn gauges (label removal is best-effort: the series
    may never have exported)."""
    from .slo import SERIES

    SERIES.unregister(f"slo_obs_{alg}_total")
    SERIES.unregister(f"slo_bad_{alg}_total")
    m = _metrics()
    if m is None:
        return
    for window in ("fast", "slow"):
        try:
            m.slo_burn_rate.remove(alg, window)
        except Exception:
            pass
    try:
        m.slo_budget_remaining.remove(alg)
    except Exception:
        pass


class BudgetRegistry:
    """Process-wide error-budget evaluator over the SLO histograms +
    series ring. Mutation (grade memory for transition instants,
    collector registration marks) under one lock; all histogram/ring
    reads happen OUTSIDE it (each surface has its own lock)."""

    def __init__(self):
        self._lock = threading.Lock()
        # algorithm -> registered threshold_s: the ring closures capture
        # the threshold, so a CHANGED threshold must re-register too
        self._registered: dict[str, float] = {}
        self._last_grades: dict[str, str] = {}
        # (env_key, monotonic, result) of the last LIVE evaluation —
        # injected now/rows (tests) always bypass; an env change
        # (operator retargets, strict flip) misses by key
        self._cache: tuple | None = None
        self.evaluations = 0
        self._san_tracker = _san_track("budget_registry")

    # ---- collectors ----

    def _ensure_collectors(self, targets: list) -> None:
        """Register the per-target (observations, breaches) cumulative
        collectors into the /slz series ring, once per algorithm — the
        ring's ``_total`` differencing turns them into windowed rates.
        Algorithms no longer targeted (operator retarget) get their
        collectors and gauges RETIRED, not left walking histograms at
        1 Hz forever with frozen burn gauges misleading dashboards."""
        from .slo import SERIES, SLO

        current = {t.algorithm for t in targets}
        fresh, stale = [], []
        with self._lock:
            _san_note(self._san_tracker, True)
            for t in targets:
                # new algorithm OR retargeted threshold: the closures
                # judge breaches against the captured threshold, so a
                # tightened target must replace them or the windowed
                # burns keep reading the OLD target until restart (the
                # first window spanning the swap differences totals from
                # two thresholds — one transient sample, clamped ≥ 0)
                if self._registered.get(t.algorithm) != t.threshold_s:
                    self._registered[t.algorithm] = t.threshold_s
                    fresh.append(t)
            for alg in set(self._registered) - current:
                del self._registered[alg]
                self._last_grades.pop(alg, None)
                stale.append(alg)
        for t in fresh:     # ring registration takes the RING's lock —
            alg, thr = t.algorithm, t.threshold_s   # outside ours

            def _obs(alg=alg, thr=thr):
                return float(SLO.totals_below(alg, "e2e", thr)[0])

            def _bad(alg=alg, thr=thr):
                total, good = SLO.totals_below(alg, "e2e", thr)
                return float(total - good)

            SERIES.register(f"slo_obs_{alg}_total", _obs)
            SERIES.register(f"slo_bad_{alg}_total", _bad)
        for alg in stale:
            _retire(alg)

    # ---- evaluation ----

    def evaluate(self, now: float | None = None,
                 rows: list | None = None) -> dict:
        """The full budget judgment: per-target cumulative + windowed
        burns, per-target and overall grades. ``now``/``rows`` are
        injectable for the burn-math tests; production callers pass
        nothing and get the live ring — those LIVE evaluations are
        cached for ``EVAL_CACHE_S`` (keyed on the knob env, so operator
        retargets take effect immediately): health probes, peer scrapes
        and advisor ticks share one pass per second."""
        from .slo import SERIES, SLO

        live = now is None and rows is None
        env_key = tuple(os.environ.get(k) for k in
                        ("RTPU_SLO_TARGET", "RTPU_HEALTH_STRICT",
                         "RTPU_BUDGET_FAST_S", "RTPU_BUDGET_SLOW_S"))
        if live:
            with self._lock:
                cached = self._cache
            if cached is not None and cached[0] == env_key and \
                    time.monotonic() - cached[1] < EVAL_CACHE_S:
                return cached[2]
        targets, errors = parse_targets()
        self._ensure_collectors(targets)
        if rows is None:
            rows = SERIES.rows()
        if now is None:
            now = time.time()
        fast_s, slow_s = fast_window_s(), slow_window_s()
        out_targets = []
        transitions = []
        grade = "ok"
        m = _metrics()
        for t in targets:
            row, t_grade, eff_fast, eff_slow = judge_target(
                t, rows, now, fast_s, slow_s,
                lambda alg, thr: SLO.totals_below(alg, "e2e", thr))
            if _GRADE_ORDER[t_grade] > _GRADE_ORDER[grade]:
                grade = t_grade
            out_targets.append(row)
            if m is not None:
                m.slo_burn_rate.labels(t.algorithm, "fast").set(eff_fast)
                m.slo_burn_rate.labels(t.algorithm, "slow").set(eff_slow)
                m.slo_budget_remaining.labels(t.algorithm).set(
                    row["budget_remaining"])
            with self._lock:
                prev = self._last_grades.get(t.algorithm, "ok")
                self._last_grades[t.algorithm] = t_grade
            if _GRADE_ORDER[t_grade] > _GRADE_ORDER[prev]:
                transitions.append((t.algorithm, prev, t_grade, row))
        with self._lock:
            _san_note(self._san_tracker, True)
            self.evaluations += 1
        for alg, prev, cur, row in transitions:   # instants outside locks
            TRACER.instant("budget.burn", algorithm=alg, grade=cur,
                           previous=prev, fast_burn=row["fast_burn"],
                           slow_burn=row["slow_burn"],
                           cumulative_burn=row["cumulative_burn"])
        result = {"targets": out_targets, "errors": errors,
                  "grade": grade, "strict": health_strict(),
                  "windows_seconds": {"fast": fast_s, "slow": slow_s}}
        if live:
            with self._lock:
                self._cache = (env_key, time.monotonic(), result)
        return result

    def grade(self) -> str:
        return self.evaluate()["grade"]

    def status_block(self) -> dict:
        """The compact ``budget`` block /statusz embeds (and /clusterz
        federates): grade + one row per target, no ring rows."""
        ev = self.evaluate()
        return {"grade": ev["grade"], "errors": ev["errors"],
                "targets": {t["algorithm"]: {
                    "grade": t["grade"],
                    "budget_remaining": t["budget_remaining"],
                    "fast_burn": t["fast_burn"],
                    "slow_burn": t["slow_burn"],
                } for t in ev["targets"]}}

    def clear(self) -> None:
        with self._lock:
            registered = list(self._registered)
            self._last_grades.clear()
            self._registered.clear()
            self._cache = None
            self.evaluations = 0
        for alg in registered:   # ring + gauge teardown outside our lock
            _retire(alg)


#: the process singleton /healthz and the advisor evaluate through
BUDGET = BudgetRegistry()


def healthz() -> tuple[int, dict]:
    """``(http_status, payload)`` for ``GET /healthz``: the liveness
    answer graded from the error-budget state — latency budgets (this
    module) joined with the staleness budgets (obs/freshness.py,
    ``RTPU_FRESH_TARGET``); the worse grade wins. 503 ONLY when the
    joined grade is burning AND ``RTPU_HEALTH_STRICT=1`` — the default
    keeps the pre-budget contract (always 200, grade in the body) so
    existing probes never flap on an operator's first target."""
    ev = BUDGET.evaluate()
    grade = ev["grade"]
    payload = {"status": grade, "strict": ev["strict"],
               "targets": ev["targets"]}
    if ev["errors"]:
        payload["target_errors"] = ev["errors"]
    try:   # lazy + tolerant: a freshness-plane bug must not take the
        from .freshness import FRESH   # liveness probe down

        fr = FRESH.budget_evaluate()
    except Exception:
        fr = None
    if fr is not None and (fr["targets"] or fr["errors"]):
        payload["freshness"] = fr["targets"]
        if fr["errors"]:
            payload["freshness_target_errors"] = fr["errors"]
        if _GRADE_ORDER[fr["grade"]] > _GRADE_ORDER[grade]:
            grade = fr["grade"]
            payload["status"] = grade
    try:   # same lazy-join contract as freshness: the resilience plane
        from ..resilience.degrade import DEGRADED   # must not kill probes

        recent = DEGRADED.recent(fast_window_s())
    except Exception:
        recent = 0
    if recent:
        # partial answers served inside the fast window: the process is
        # up but shedding coverage — at most "degraded" (a breaker doing
        # its job is not a 503-worthy burn; sustained latency/staleness
        # breaches still grade "burning" through their own budgets)
        payload["degraded_results_recent"] = recent
        if _GRADE_ORDER[grade] < _GRADE_ORDER["degraded"]:
            grade = "degraded"
            payload["status"] = grade
    code = 503 if grade == "burning" and ev["strict"] else 200
    return code, payload
