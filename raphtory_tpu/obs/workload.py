"""Per-tenant workload accounts — WHO is spending the cluster's budget.

The ledger (``obs/ledger.py``) answers "what did this query cost"; the
serving push (ROADMAP item 1) needs the roll-up one level higher: "what
has each TENANT cost", because admission control prices tenants, not
queries, and load shedding must name a victim. Requests carry an
optional tenant identity — the ``X-RTPU-Tenant`` header or a ``tenant``
body field, default ``anon`` — and every completed job's ledger is
merged into a bounded per-tenant account here (this is the sub-ledger
role :meth:`obs.ledger.Ledger.merge` was built and tested for).

Identity rules (mirrors the PR-10 wire-header contract: an observability
header can never fail a request):

* missing / empty → ``anon``;
* malformed — non-ASCII, longer than 64 chars, or characters outside
  ``[A-Za-z0-9._-]`` — → ``invalid`` (one shared account: a client typo,
  or an adversarial header, must not mint unbounded label cardinality
  or 4xx the request);
* past ``RTPU_TENANT_CAP`` distinct tenants, new names aggregate into
  ``other`` — per-tenant Prometheus label cardinality is PROVABLY
  bounded by cap + 3 sentinel names.

Each account carries: cost seconds by phase (fold/stage/ship/compute/
device_wait/emit/other + queue wait), est HBM + DCN + H2D bytes,
fold-cache hits consumed vs folds paid for (misses that populated the
cache), query counts by status, a bounded query-shape top-K, and the
most expensive recent queries with their trace ids (the advisor's
shed-this-tenant evidence). Surfaces: ``/workloadz``, a compact
``workload`` block in ``/statusz`` (what ``/clusterz`` federates into
the merged per-tenant view), and ``raphtory_tenant_*`` counters.

Knobs
-----
* ``RTPU_WORKLOAD`` — tenant-attributed accounting (default on; the
  ``advisor_overhead`` bench's off arm).
* ``RTPU_TENANT_CAP`` — distinct named tenant accounts (default 64);
  overflow tenants aggregate into ``other``.
"""

from __future__ import annotations

import os
import threading
import time

from ..analysis.sanitizer import (note_shared as _san_note,
                                  track_shared as _san_track)
from . import ledger as _ledger
from .slo import _metrics

#: request header carrying the tenant identity (jobs/rest.py reads it)
TENANT_HEADER = "X-RTPU-Tenant"
TENANT_DEFAULT = "anon"
TENANT_INVALID = "invalid"
TENANT_OVERFLOW = "other"
MAX_TENANT_LEN = 64
#: distinct query shapes tracked per account before aggregating
MAX_SHAPES = 32
#: most-expensive-query exemplars kept per account
TOP_QUERIES = 3

_ALLOWED = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")


def enabled() -> bool:
    """Re-read per completed job so the bench A/B (and operators) can
    flip attribution without a restart."""
    return os.environ.get("RTPU_WORKLOAD", "1") not in ("", "0", "false")


def tenant_cap() -> int:
    try:
        return max(1, int(os.environ.get("RTPU_TENANT_CAP", "64") or 64))
    except ValueError:
        return 64


def normalize_tenant(raw) -> str:
    """Normalize a client-supplied tenant identity to a safe account /
    metric-label name. NEVER raises — a malformed observability header
    must not fail the request it rides on (PR-10 rule), and must not
    mint unbounded label cardinality either, so everything suspicious
    lands in the one shared ``invalid`` account."""
    if raw is None:
        return TENANT_DEFAULT
    if not isinstance(raw, str):
        return TENANT_INVALID
    s = raw.strip()
    if not s:
        return TENANT_DEFAULT
    if len(s) > MAX_TENANT_LEN:
        return TENANT_INVALID
    if not all(c in _ALLOWED for c in s):
        return TENANT_INVALID
    if s == TENANT_OVERFLOW:
        # a client claiming the overflow aggregate by name would merge
        # into it cap-exempt and without the overflow count — `other`
        # must keep meaning "past-cap tenants", so the claim is invalid
        return TENANT_INVALID
    return s


class _Account:
    """One tenant's rolling account: a long-lived sub-ledger every
    completed query's ledger merges into, plus the scalars
    ``Ledger.merge`` deliberately leaves per-query (wall, queue wait,
    status counts) and the bounded shape/exemplar tables."""

    __slots__ = ("tenant", "ledger", "queries", "wall_seconds",
                 "queue_wait_seconds", "cost_seconds", "shapes",
                 "shapes_overflow", "top_queries", "first_unix",
                 "last_unix")

    def __init__(self, tenant: str):
        self.tenant = tenant
        self.ledger = _ledger.Ledger(query_id=f"tenant:{tenant}")
        self.queries: dict[str, int] = {}
        self.wall_seconds = 0.0
        self.queue_wait_seconds = 0.0
        self.cost_seconds = 0.0
        self.shapes: dict[str, int] = {}
        self.shapes_overflow = 0
        self.top_queries: list[dict] = []
        self.first_unix = time.time()
        self.last_unix = self.first_unix

    def add(self, led: "_ledger.Ledger", status: str) -> None:
        self.ledger.merge(led)
        self.queries[status] = self.queries.get(status, 0) + 1
        self.wall_seconds += led.wall_seconds
        self.queue_wait_seconds += led.queue_wait_seconds
        with led._lock:
            self.cost_seconds += sum(led.phase_seconds.values())
        shape = f"{led.algorithm or 'unknown'}/{led.views}v/{led.hops}h"
        if shape in self.shapes or len(self.shapes) < MAX_SHAPES:
            self.shapes[shape] = self.shapes.get(shape, 0) + 1
        else:
            self.shapes_overflow += 1
        self.top_queries.append({
            "query_id": led.query_id, "algorithm": led.algorithm,
            "trace_id": led.trace_id,
            "wall_seconds": round(led.wall_seconds, 6)})
        self.top_queries.sort(key=lambda q: -q["wall_seconds"])
        del self.top_queries[TOP_QUERIES:]
        self.last_unix = time.time()

    def as_dict(self, top_shapes: int = 8) -> dict:
        snap = self.ledger.as_dict()
        shapes = sorted(self.shapes.items(), key=lambda kv: -kv[1])
        out = {
            "tenant": self.tenant,
            "queries": dict(self.queries),
            "queries_total": sum(self.queries.values()),
            "wall_seconds": round(self.wall_seconds, 6),
            "queue_wait_seconds": round(self.queue_wait_seconds, 6),
            "cost_seconds": round(self.cost_seconds, 6),
            "phase_seconds": snap["phase_seconds"],
            "est_hbm_bytes": sum(
                k.get("est_hbm_bytes", 0.0)
                for k in snap["device"]["kernels"].values()),
            "est_bytes_accessed": snap["device"]["est_bytes_accessed"],
            "dcn_bytes": snap["dcn"]["bytes"],
            "h2d_bytes": snap["h2d"]["bytes"],
            # consumed = served from the cross-request fold cache;
            # paid = misses, i.e. folds this tenant ran that populated
            # the cache others (or its own repeats) then hit
            "fold_cache": {"hits_consumed": snap["fold"]["cache_hits"],
                           "folds_paid": snap["fold"]["cache_misses"]},
            "sweeps": snap["sweeps"], "views": snap["views"],
            "hops": snap["hops"],
            "shapes_top": dict(shapes[:max(0, int(top_shapes))]),
            "shapes_overflow": self.shapes_overflow,
            "top_queries": list(self.top_queries),
            "first_unix": round(self.first_unix, 3),
            "last_unix": round(self.last_unix, 3),
        }
        return out


class WorkloadRegistry:
    """Process-wide bounded per-tenant accounts. All mutation under one
    lock (publication runs on every job thread); the named-account table
    never exceeds ``RTPU_TENANT_CAP`` — later tenants merge into the
    ``other`` aggregate, counted so the overflow is visible."""

    def __init__(self):
        self._lock = threading.Lock()
        self._accounts: dict[str, _Account] = {}
        self.overflow_queries = 0
        self._san_tracker = _san_track("workload_accounts")

    def _account_locked(self, tenant: str) -> _Account:
        acct = self._accounts.get(tenant)
        if acct is None:
            if (tenant not in (TENANT_OVERFLOW, TENANT_INVALID,
                               TENANT_DEFAULT)
                    and len(self._accounts) >= tenant_cap()):
                tenant = TENANT_OVERFLOW
                acct = self._accounts.get(tenant)
                self.overflow_queries += 1
            if acct is None:
                acct = self._accounts[tenant] = _Account(tenant)
        return acct

    def record(self, led: "_ledger.Ledger", status: str = "done") -> None:
        """Roll one completed job's ledger into its tenant's account and
        mirror the bounded-cardinality counters. Called by the jobs
        layer after ``Ledger.finish()``; a no-op when ``RTPU_WORKLOAD``
        is off."""
        if not enabled():
            return
        tenant = normalize_tenant(getattr(led, "tenant", None))
        with self._lock:
            _san_note(self._san_tracker, True)
            acct = self._account_locked(tenant)
            acct.add(led, status)
            label = acct.tenant   # post-cap name: bounded cardinality
        m = _metrics()
        if m is None:
            return
        m.tenant_queries.labels(label, status).inc()
        for ph, sec in dict(led.phase_seconds).items():
            m.tenant_cost_seconds.labels(label, ph).inc(max(0.0, sec))
        m.tenant_cost_seconds.labels(label, "queue_wait").inc(
            max(0.0, led.queue_wait_seconds))
        hbm = sum(float(k.get("est_hbm_bytes") or 0.0)
                  for k in dict(led.kernels).values())
        if hbm:
            m.tenant_est_hbm_bytes.labels(label).inc(hbm)
        dcn = sum(d["bytes"] for d in dict(led.dcn).values())
        if dcn:
            m.tenant_dcn_bytes.labels(label).inc(dcn)

    # ---- export ----

    def tenants(self) -> list[str]:
        with self._lock:
            self._san_note_read()
            return sorted(self._accounts)

    def _san_note_read(self) -> None:
        _san_note(self._san_tracker, False)

    def top_by_cost(self, n: int = 8) -> list[dict]:
        """Accounts by total attributed cost seconds, descending — the
        advisor's shed-candidate ordering. Ranks on the cheap scalar and
        snapshots only the selected accounts, so the lock (which every
        completing job's record() also wants) is held for O(n) as_dict
        work, not the whole table's."""
        with self._lock:
            self._san_note_read()
            order = sorted(self._accounts.values(),
                           key=lambda a: -a.cost_seconds)
            return [a.as_dict() for a in order[:max(0, int(n))]]

    def account(self, tenant: str) -> dict | None:
        with self._lock:
            self._san_note_read()
            acct = self._accounts.get(tenant)
            return acct.as_dict() if acct is not None else None

    def status_block(self) -> dict:
        """The compact ``workload`` block /statusz embeds — and what
        ``/clusterz`` federates, so it stays small: per-tenant totals
        only, top 8 by cost."""
        with self._lock:
            self._san_note_read()
            rows = {t: {
                "queries": sum(a.queries.values()),
                "cost_seconds": round(a.cost_seconds, 6),
                "queue_wait_seconds": round(a.queue_wait_seconds, 6),
            } for t, a in self._accounts.items()}
            overflow = self.overflow_queries
        top = sorted(rows.items(), key=lambda kv: -kv[1]["cost_seconds"])
        return {"enabled": enabled(), "tenant_cap": tenant_cap(),
                "n_tenants": len(rows),
                "overflow_queries": overflow,
                "tenants": dict(top[:8])}

    def workloadz(self) -> dict:
        """The full ``/workloadz`` document."""
        with self._lock:
            self._san_note_read()
            accounts = [a.as_dict() for a in self._accounts.values()]
            overflow = self.overflow_queries
        accounts.sort(key=lambda a: -a["cost_seconds"])
        return {
            "enabled": enabled(),
            "tenant_cap": tenant_cap(),
            "n_tenants": len(accounts),
            "overflow_queries": overflow,
            "header": TENANT_HEADER,
            "identity_rule": (
                f"missing -> {TENANT_DEFAULT!r}; non-ASCII / >"
                f"{MAX_TENANT_LEN} chars / outside [A-Za-z0-9._-] -> "
                f"{TENANT_INVALID!r}; past RTPU_TENANT_CAP distinct "
                f"names -> {TENANT_OVERFLOW!r}"),
            "tenants": accounts,
        }

    def clear(self) -> None:
        with self._lock:
            self._accounts.clear()
            self.overflow_queries = 0


#: the process singleton the jobs layer records into
WORKLOAD = WorkloadRegistry()
