"""Durable telemetry journal — crash-safe, bounded, append-only.

Every observability surface before this module (trace ring, series
ring, ledger ring, advisor findings, fault/breaker/degrade events, live
epoch accounting) lives in process memory and reaches disk only via
best-effort exit dumps — a SIGKILLed cluster member takes its evidence
to the grave. The reference Raphtory archived entity history so state
survived failures (PAPER.md §2); this module applies the same principle
to telemetry: a segmented on-disk journal that continuously records
CRC-framed events, so ``tools/rtpu-postmortem`` can reconstruct a dead
member's final sweep and epoch state from its journal alone.

Design constraints, in order:

* **Never block a request path.** ``emit()`` appends to a bounded
  in-memory queue under one uncontended lock and returns; a single
  writer thread drains, serializes, frames and fsyncs in batches
  (``RTPU_JOURNAL_FLUSH_MS``). A full queue DROPS the record and counts
  it (``/journalz`` ``drops``) — backpressure on telemetry must never
  become backpressure on serving.
* **Crash-safe by framing, not by fsync-per-record.** Each record is
  ``<u32 length><u32 crc32(payload)><payload>``; a reader walks frames
  until EOF, a short read, or a CRC mismatch and STOPS — a torn final
  record (the SIGKILL case) is skipped, never fatal, and everything
  before the last batched fsync is guaranteed durable.
* **Bounded disk.** Segments rotate at ``total_cap/8`` bytes; when the
  per-process total exceeds ``RTPU_JOURNAL_MB`` the oldest segments are
  deleted. Each process manages only its OWN segments
  (``journal-p<process_index>-<seq>.rtj``) — many cluster members can
  share one directory without racing each other's rotation.
* **Zero overhead off.** ``enabled()`` is one environ lookup; with
  ``RTPU_JOURNAL=0`` (the default) no instance, thread, or file ever
  exists and every hook returns after that single check.
* **Standalone-importable.** stdlib only, no relative imports required
  at module load — ``tools/rtpu-postmortem`` loads THIS file by path
  (the rtpulint/perfwatch idiom) so the reader and writer can never
  drift apart.

Record schema (JSON payload, compact keys — docs/OBSERVABILITY.md):

===  ==========================================================
key  meaning
===  ==========================================================
k    kind: span|instant|series|ledger|advice|sched|epoch|fresh|
     fault|breaker|degrade|mesh|meta
w    wall-clock unix seconds at emit
m    monotonic seconds (time.perf_counter) at emit
p    process_index (cluster identity)
s    per-process emit sequence number (gaps = dropped records)
t    trace id ("" when none)
n    tenant ("" when none)
d    kind-specific data dict
===  ==========================================================

Knobs (all in docs/OPERATIONS.md):

* ``RTPU_JOURNAL`` — enable (default off; ``RTPU_JOURNAL_DIR`` set
  implies on, the RTPU_TRACE_DUMP precedent).
* ``RTPU_JOURNAL_DIR`` — segment directory (default
  ``<tmpdir>/rtpu-journal``).
* ``RTPU_JOURNAL_MB`` — per-process on-disk cap in MB (default 64);
  oldest segments rotate out.
* ``RTPU_JOURNAL_FLUSH_MS`` — writer-thread batch interval (default
  200): records are fsync-durable at most this far behind ``emit()``.
* ``RTPU_JOURNAL_QUEUE`` — bounded emit-queue capacity in records
  (default 8192); overflow drops-and-counts.
"""

from __future__ import annotations

import collections
import json
import os
import struct
import tempfile
import threading
import time
import zlib

#: segment file magic — 4 bytes at offset 0 of every segment
MAGIC = b"RTJ1"
#: frame header: little-endian u32 payload length, u32 crc32(payload)
HEADER = struct.Struct("<II")
#: a frame longer than this is corruption, not data (reader stops)
MAX_RECORD_BYTES = 8 << 20

DEFAULT_CAP_MB = 64
DEFAULT_FLUSH_MS = 200
DEFAULT_QUEUE = 8192
SEGMENT_FRACTION = 8        # segment size = total cap / 8

_VERSION = 1


def enabled() -> bool:
    """One environ lookup — the hot-path gate every hook checks first.
    ``RTPU_JOURNAL`` wins when set; otherwise a configured
    ``RTPU_JOURNAL_DIR`` implies on (the CI artifact idiom)."""
    v = os.environ.get("RTPU_JOURNAL")
    if v is not None:
        return v not in ("", "0", "false")
    return bool(os.environ.get("RTPU_JOURNAL_DIR"))


def journal_dir() -> str:
    return (os.environ.get("RTPU_JOURNAL_DIR")
            or os.path.join(tempfile.gettempdir(), "rtpu-journal"))


def _env_int(name: str, default: int, lo: int = 1) -> int:
    try:
        return max(lo, int(os.environ.get(name, "") or default))
    except ValueError:
        return default


# ---------------------------------------------------------------------
# framing — shared verbatim by writer (here) and reader (scan below,
# loaded standalone by tools/rtpu-postmortem)
# ---------------------------------------------------------------------

def encode_record(rec: dict) -> bytes:
    """One CRC-framed record. Serialization must never raise into the
    writer thread — non-JSON values degrade via ``default=str``."""
    payload = json.dumps(rec, separators=(",", ":"),
                         default=str).encode("utf-8")
    return HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def scan_segment(path: str):
    """Yield ``(record, offset)`` for every intact frame of a segment,
    stopping (silently — the caller counts via ``scan_report``) at the
    first torn or corrupt frame. Never raises for data-level damage;
    OS-level errors (unreadable file) propagate to the caller."""
    for rec, off in _scan(path)[0]:
        yield rec, off


def scan_report(path: str) -> tuple[list, dict]:
    """``(records, report)`` for one segment: every intact record (in
    file order) plus ``{"bytes", "torn", "reason"}`` where ``torn`` is
    1 when the walk stopped before EOF (truncated or corrupt tail —
    the SIGKILL signature)."""
    pairs, report = _scan(path)
    return [r for r, _ in pairs], report


def _scan(path: str) -> tuple[list, dict]:
    pairs: list = []
    size = os.path.getsize(path)
    report = {"bytes": size, "torn": 0, "reason": ""}
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            report["torn"] = 1
            report["reason"] = "bad-magic"
            return pairs, report
        off = len(MAGIC)
        while True:
            head = f.read(HEADER.size)
            if not head:
                return pairs, report           # clean EOF
            if len(head) < HEADER.size:
                report["torn"] = 1             # torn mid-header
                report["reason"] = f"short-header@{off}"
                return pairs, report
            length, crc = HEADER.unpack(head)
            if length > MAX_RECORD_BYTES:
                report["torn"] = 1
                report["reason"] = f"bad-length@{off}"
                return pairs, report
            payload = f.read(length)
            if len(payload) < length:
                report["torn"] = 1             # torn mid-payload
                report["reason"] = f"short-payload@{off}"
                return pairs, report
            if zlib.crc32(payload) != crc:
                report["torn"] = 1             # corrupt (or torn) bytes
                report["reason"] = f"crc@{off}"
                return pairs, report
            try:
                pairs.append((json.loads(payload), off))
            except ValueError:
                report["torn"] = 1
                report["reason"] = f"json@{off}"
                return pairs, report
            off += HEADER.size + length


def segment_name(process_index: int, seq: int) -> str:
    return f"journal-p{int(process_index)}-{int(seq):08d}.rtj"


def parse_segment_name(name: str) -> tuple[int, int] | None:
    """``(process_index, seq)`` or None for non-journal files."""
    if not (name.startswith("journal-p") and name.endswith(".rtj")):
        return None
    body = name[len("journal-p"):-len(".rtj")]
    try:
        pi, seq = body.split("-", 1)
        return int(pi), int(seq)
    except ValueError:
        return None


# ---------------------------------------------------------------------
# the writer
# ---------------------------------------------------------------------

class Journal:
    """One process's journal: bounded queue + single writer thread +
    segment rotation. Construct directly in tests; production uses the
    module-level ``emit()`` singleton."""

    def __init__(self, directory: str | None = None,
                 cap_mb: int | None = None,
                 flush_ms: int | None = None,
                 queue_cap: int | None = None,
                 process_index: int | None = None):
        self.dir = directory or journal_dir()
        self.cap_bytes = (cap_mb if cap_mb is not None
                          else _env_int("RTPU_JOURNAL_MB",
                                        DEFAULT_CAP_MB)) * (1 << 20)
        self.flush_s = (flush_ms if flush_ms is not None
                        else _env_int("RTPU_JOURNAL_FLUSH_MS",
                                      DEFAULT_FLUSH_MS)) / 1000.0
        self.queue_cap = (queue_cap if queue_cap is not None
                          else _env_int("RTPU_JOURNAL_QUEUE",
                                        DEFAULT_QUEUE))
        self.segment_bytes = max(64 << 10,
                                 self.cap_bytes // SEGMENT_FRACTION)
        if process_index is None:
            process_index = _env_int("RTPU_PROCESS_INDEX", 0, lo=0)
        self.process_index = int(process_index)
        self._pid = os.getpid()
        self._mu = threading.Lock()          # queue + counters
        self._queue: collections.deque = collections.deque()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._seq = 0
        self._enqueued = 0
        self._flushed = 0
        self._closed = False
        # counters (read under _mu via status())
        self.records_written = 0
        self.bytes_written = 0
        self.drops = 0
        self.encode_errors = 0
        self.rotations = 0
        self.segments_deleted = 0
        self.write_errors = 0
        self.last_flush_unix = 0.0
        self._oldest_pending_unix = 0.0
        # segment state (writer thread only, after __init__)
        os.makedirs(self.dir, exist_ok=True)
        self._seg_seq = self._next_segment_seq()
        self._seg_file = None
        self._seg_bytes = 0
        self._open_segment()
        self._emit_meta()
        self._thread = threading.Thread(target=self._loop,
                                        name="journal-writer", daemon=True)
        self._thread.start()

    # ---- segments ----

    def _own_segments(self) -> list[tuple[int, str, int]]:
        """Sorted ``(seq, path, bytes)`` of THIS process's segments."""
        rows = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return rows
        for name in names:
            parsed = parse_segment_name(name)
            if parsed is None or parsed[0] != self.process_index:
                continue
            path = os.path.join(self.dir, name)
            try:
                rows.append((parsed[1], path, os.path.getsize(path)))
            except OSError:
                continue
        rows.sort()
        return rows

    def _next_segment_seq(self) -> int:
        """Continue numbering past any previous run's segments — a
        restarted process must never clobber its predecessor's evidence
        (that evidence is exactly what postmortem reads)."""
        rows = self._own_segments()
        return rows[-1][0] + 1 if rows else 0

    def _open_segment(self) -> None:
        path = os.path.join(self.dir,
                            segment_name(self.process_index, self._seg_seq))
        self._seg_file = open(path, "ab")
        if self._seg_file.tell() == 0:
            self._seg_file.write(MAGIC)
        self._seg_bytes = self._seg_file.tell()
        self._seg_path = path

    def _rotate_locked_out(self) -> None:
        """Close the active segment, open the next, delete oldest
        segments past the byte cap. Writer thread only."""
        try:
            self._seg_file.flush()
            os.fsync(self._seg_file.fileno())
            self._seg_file.close()
        except OSError:
            self.write_errors += 1
        self._seg_seq += 1
        self.rotations += 1
        self._open_segment()
        rows = self._own_segments()
        total = sum(b for _, _, b in rows)
        for seq, path, nbytes in rows:
            if total <= self.cap_bytes:
                break
            if path == self._seg_path:
                break                       # never delete the active one
            try:
                os.remove(path)
                self.segments_deleted += 1
                total -= nbytes
            except OSError:
                break

    # ---- emit (any thread, non-blocking) ----

    def emit(self, kind: str, data: dict | None = None, *,
             trace_id: str | None = None,
             tenant: str | None = None) -> bool:
        """Queue one record; returns False when dropped (queue full or
        journal closed). Never blocks, never raises."""
        try:
            now = time.time()
            rec = {"k": kind, "w": round(now, 6),
                   "m": time.perf_counter(),
                   "p": self.process_index,
                   "t": trace_id or "", "n": tenant or "",
                   "d": data if data is not None else {}}
            with self._mu:
                # seq is assigned even to DROPPED records: a gap in the
                # journaled sequence is the postmortem-visible drop
                # evidence (the drops counter itself may be lost with
                # the process)
                self._seq += 1
                rec["s"] = self._seq
                if self._closed or len(self._queue) >= self.queue_cap:
                    self.drops += 1
                    return False
                self._enqueued += 1
                if not self._queue:
                    self._oldest_pending_unix = now
                self._queue.append(rec)
            return True
        except Exception:
            # a telemetry sink must never become a fault injector
            try:
                with self._mu:
                    self.encode_errors += 1
            except Exception:
                pass
            return False

    def _emit_meta(self) -> None:
        self.emit("meta", {
            "version": _VERSION, "pid": self._pid,
            "segment": self._seg_seq,
            "cap_mb": self.cap_bytes >> 20,
            "flush_ms": int(self.flush_s * 1000),
            # the mono↔wall anchor: every record carries both clocks,
            # but the offset here lets a reader sanity-check drift
            "mono_anchor": time.perf_counter(),
            "wall_anchor": time.time(),
        })

    # ---- writer thread ----

    def _drain(self) -> list[dict]:
        with self._mu:
            batch = list(self._queue)
            self._queue.clear()
            self._oldest_pending_unix = 0.0
        return batch

    def _write_batch(self, batch: list[dict]) -> None:
        wrote = 0
        nbytes = 0
        for rec in batch:
            try:
                frame = encode_record(rec)
            except Exception:
                with self._mu:
                    self.encode_errors += 1
                continue
            try:
                self._seg_file.write(frame)
                wrote += 1
                nbytes += len(frame)
                self._seg_bytes += len(frame)
            except OSError:
                with self._mu:
                    self.write_errors += 1
                break                       # a full disk drops the REST of
            if self._seg_bytes >= self.segment_bytes:
                # rotate MID-batch: one burst bigger than a segment must
                # still produce capped segments, or the oldest-first
                # deletion below would remove the single segment holding
                # the entire history
                self._rotate_locked_out()
        try:                                # the batch, not the process
            self._seg_file.flush()
            os.fsync(self._seg_file.fileno())
        except OSError:
            with self._mu:
                self.write_errors += 1
        with self._mu:
            self.records_written += wrote
            self.bytes_written += nbytes
            # the whole batch is PROCESSED (flush() waiters unblock)
            # even when writes failed — failures are counted, never
            # re-queued: replaying onto a sick disk would wedge the
            # writer behind an ever-growing backlog
            self._flushed += len(batch)
            self.last_flush_unix = time.time()

    def _loop(self) -> None:
        while not self._stop.wait(self.flush_s):
            batch = self._drain()
            if batch:
                self._write_batch(batch)
            if self._wake.is_set():
                self._wake.clear()
        # final drain on stop
        batch = self._drain()
        if batch:
            self._write_batch(batch)

    # ---- lifecycle ----

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until everything queued BEFORE the call is fsynced —
        tests and the exit path; production code never calls this."""
        deadline = time.monotonic() + timeout
        with self._mu:
            target = self._enqueued
        while time.monotonic() < deadline:
            with self._mu:
                if self._flushed >= target and not self._queue:
                    return True
            time.sleep(0.005)
        return False

    def close(self, timeout: float = 5.0) -> None:
        """Stop the writer after a final drain + fsync. Idempotent —
        the exit path may run it more than once."""
        with self._mu:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(timeout=timeout)
        try:
            self._seg_file.flush()
            os.fsync(self._seg_file.fileno())
            self._seg_file.close()
        except OSError:
            pass

    # ---- introspection ----

    def status(self) -> dict:
        rows = self._own_segments()
        with self._mu:
            queue_depth = len(self._queue)
            oldest = self._oldest_pending_unix
            st = {
                "dir": self.dir,
                "process_index": self.process_index,
                "cap_mb": self.cap_bytes >> 20,
                "flush_ms": int(self.flush_s * 1000),
                "segment_bytes": self.segment_bytes,
                "records_written": self.records_written,
                "bytes_written": self.bytes_written,
                "drops": self.drops,
                "encode_errors": self.encode_errors,
                "write_errors": self.write_errors,
                "rotations": self.rotations,
                "segments_deleted": self.segments_deleted,
                "queue_depth": queue_depth,
                "queue_cap": self.queue_cap,
                "last_flush_unix": round(self.last_flush_unix, 3),
                "closed": self._closed,
            }
        # flush lag: how stale the on-disk tail is relative to emits —
        # 0 when nothing is pending (everything emitted is durable)
        st["flush_lag_seconds"] = (round(max(0.0, time.time() - oldest), 3)
                                   if oldest else 0.0)
        st["segments"] = [{"seq": seq, "file": os.path.basename(path),
                           "bytes": nbytes} for seq, path, nbytes in rows]
        st["total_bytes"] = sum(r["bytes"] for r in st["segments"])
        return st

    def status_block(self) -> dict:
        """The compact /statusz block (federated at /clusterz)."""
        full = self.status()
        return {k: full[k] for k in
                ("dir", "total_bytes", "records_written", "drops",
                 "flush_lag_seconds", "queue_depth")} | {
                    "segments": len(full["segments"]), "enabled": True}


# ---------------------------------------------------------------------
# module singleton + hook surface
# ---------------------------------------------------------------------

_SINGLETON: Journal | None = None
_SINGLETON_MU = threading.Lock()
_FAILED = False


def get() -> Journal | None:
    """The process journal (lazily constructed when enabled). A failed
    construction (unwritable dir) disables journaling for the process —
    telemetry must never take serving down — and surfaces on
    ``journalz()`` as ``failed: true``."""
    global _SINGLETON, _FAILED
    j = _SINGLETON
    if j is not None:
        return j
    if _FAILED or not enabled():
        return None
    with _SINGLETON_MU:
        if _SINGLETON is None and not _FAILED:
            try:
                _SINGLETON = Journal()
                _register_exit(_SINGLETON)
            except Exception:
                _FAILED = True
                return None
        return _SINGLETON


def _register_exit(journal: Journal) -> None:
    """Close/flush at interpreter exit AND on SIGTERM via the shared
    exit-artifact module (obs/exitdump.py). Standalone loads (the
    postmortem tool) have no package context — then atexit directly."""
    try:
        from . import exitdump

        exitdump.register("journal", journal.close, last=True)
    except ImportError:
        import atexit

        atexit.register(journal.close)


def shutdown() -> None:
    """Close and forget the singleton (tests; re-arms on next emit)."""
    global _SINGLETON, _FAILED
    with _SINGLETON_MU:
        j, _SINGLETON = _SINGLETON, None
        _FAILED = False
    if j is not None:
        j.close()


def emit(kind: str, data: dict | None = None, *,
         trace_id: str | None = None, tenant: str | None = None) -> None:
    """The module-level hook every publication point calls:
    ``if journal.enabled(): journal.emit(...)``. Safe to call bare —
    the enabled() check is repeated here (one environ lookup)."""
    if not enabled():
        return
    j = get()
    if j is not None:
        j.emit(kind, data, trace_id=trace_id, tenant=tenant)


def emit_event(event: dict) -> None:
    """Forward one flight-recorder ring event (obs/trace.Tracer._record
    calls this after the ring append): ``ph: X`` → kind ``span``,
    ``ph: i`` → kind ``instant``. The event dict is recorded verbatim
    as the data block — the postmortem chrome exporter re-bases its
    tracer-epoch timestamps onto the record's wall stamp."""
    if not enabled():
        return
    j = get()
    if j is not None:
        kind = "span" if event.get("ph") == "X" else "instant"
        j.emit(kind, event, trace_id=event.get("trace") or None)


def journalz() -> dict:
    """The ``/journalz`` document."""
    on = enabled()
    doc: dict = {"enabled": on, "failed": _FAILED}
    j = _SINGLETON if _SINGLETON is not None else (get() if on else None)
    if j is not None:
        doc.update(j.status())
    return doc


def status_block() -> dict:
    """Compact /statusz block; ``{"enabled": False}`` when off."""
    on = enabled()
    j = _SINGLETON if _SINGLETON is not None else (get() if on else None)
    if j is None:
        return {"enabled": False}
    return j.status_block()
