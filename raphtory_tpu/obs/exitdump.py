"""Shared exit-artifact writers — one atexit hook, one SIGTERM handler.

Six modules (trace, series, sample, device, sched, fault — plus the
freshness plane) grew copy-pasted ``RTPU_*_DUMP`` atexit blocks, and
only ``obs/trace.py`` bothered with the SIGTERM case — a wedged run
killed by ``timeout`` (CI's kill) skipped every OTHER module's dump.
This module is the single registry they all feed:

* ``register(name, fn)`` — ``fn()`` writes one artifact (it owns its
  path; failures are swallowed — an exit dump must never mask the real
  exit reason). Registration is idempotent by name.
* One ``atexit`` hook runs every writer, in registration order, with
  ``last=True`` writers (the journal's close/flush) at the end — the
  journal must drain AFTER other writers in case their work emits
  final records.
* One SIGTERM handler (installed with the obs/trace.py guards: main
  thread only, and only when SIGTERM is still ``SIG_DFL`` so a
  server's own shutdown handler always wins) runs the same writers,
  then restores the default disposition and re-kills — the exit code
  stays 143 and the CI failure artifacts survive the kill.

stdlib-only: ``obs.trace`` (and the standalone-loadable journal) import
this module, so it must carry no runtime deps.
"""

from __future__ import annotations

import atexit
import os
import signal
import threading

_LOCK = threading.Lock()
_WRITERS: dict[str, tuple] = {}     # name -> (fn, last)
_INSTALLED = False


def register(name: str, fn, last: bool = False) -> None:
    """Add (or replace) an exit writer. ``last=True`` writers run after
    every ordinary one — the journal close slot."""
    global _INSTALLED
    with _LOCK:
        _WRITERS[str(name)] = (fn, bool(last))
        if not _INSTALLED:
            _INSTALLED = True
            atexit.register(run_all)
            _install_sigterm()


def unregister(name: str) -> None:
    with _LOCK:
        _WRITERS.pop(str(name), None)


def registered() -> list[str]:
    with _LOCK:
        return list(_WRITERS)


def run_all() -> None:
    """Run every writer (ordinary first, ``last`` writers after), each
    inside its own try/except — one broken artifact must not cost the
    others. Idempotent by construction: writers overwrite their own
    files and the journal close is itself idempotent, so running at
    SIGTERM and again at atexit is safe."""
    with _LOCK:
        writers = list(_WRITERS.values())
    ordered = [fn for fn, last in writers if not last] \
        + [fn for fn, last in writers if last]
    for fn in ordered:
        try:
            fn()
        except Exception:
            pass


def _install_sigterm() -> None:
    """Dump-then-default SIGTERM, with the guards obs/trace.py
    established: only from the main thread, and only while nothing
    else has claimed the signal."""
    try:
        if (threading.current_thread() is not threading.main_thread()
                or signal.getsignal(signal.SIGTERM)
                is not signal.SIG_DFL):
            return

        def _on_term(signum, frame):
            run_all()
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGTERM)   # keep exit code 143

        signal.signal(signal.SIGTERM, _on_term)
    except Exception:
        pass
