"""Device runtime plane — the MEASURED half of the ledger.

Everything the ledger (``obs/ledger.py``) knows about the device is a
compile-time estimate: XLA ``cost_analysis()`` FLOPs/bytes and the PCPM
traffic model, never a clock or a memory counter. This module is the
counterpart that measures — the instrument the adaptive runtime
(ROADMAP item 4) needs before it can trust the model it actuates on.
Four pieces, all surfaced at ``/devicez`` and federated per process by
``/clusterz``:

* **Measured kernel latency** (``TIMING``). ``instrument()``'s dispatch
  wrapper samples timed dispatches: a sampled dispatch blocks until the
  result is ready and records wall device seconds into a bounded
  per-(kernel, shape-sig) window. Sampling (``RTPU_DEVICE_TIMING``, a
  rate in (0, 1]) because an always-on sync would destroy the PR 2/5
  pipelining — and the measured number therefore includes dispatch
  overhead and any pipeline drain the sync forces (docs/OBSERVABILITY.md
  "Device runtime" spells out the caveat). The first dispatch of every
  (kernel, sig) is always timed (recorded separately as the COLD sample
  — it may include compile when the AOT harvest is off), the second is
  always timed (so every kernel dispatched twice has a warm p50), then
  every 1/rate-th. Each kernel row joins measured p50/p99 seconds,
  achieved FLOP/s and bytes/s, a measured-vs-estimated divergence ratio
  (measured p50 over the roofline model's predicted seconds), and a
  ``bound_measured`` re-classification next to the estimate-side
  ``bound`` / ``bound_refined``.
* **Device memory** (``memory_snapshot``). ``memory_stats()`` read off
  the first device, tolerant of backends that return None or raise
  (this CPU rig): the degrade is ``{"available": False}`` — never an
  exception out of a sampler thread, never a 500 off ``/devicez``. The
  PR 9 series ring samples bytes-in-use at 1 Hz, sampled dispatches max
  bytes-in-use into the active query ledger (``peak_device_bytes``),
  and the resident-buffer registry (``RESIDENT``) makes the engines'
  device-resident base tables a live gauge.
* **Resident-buffer registry** (``RESIDENT``). Weakref-keyed: an entry
  lives exactly as long as the engine (or log) that owns the buffer, so
  the gauge can never leak a dead engine's bytes (RT011 by
  construction). ``engine/hopbatch.py`` and ``engine/device_sweep.py``
  feed it at their upload sites.
* **Compile observability** (``note_compile``). Every
  ``lower().compile()`` in the kernel registry runs under an
  ``xla.compile`` span and lands here: per-kernel compile counts /
  seconds / last shape sig (joined into ``/statusz.compile_caches``),
  ``raphtory_compile{s,_seconds}_total{kernel}`` counters, and a
  bounded recent-compile ring whose density is the compile-storm signal
  (new shape sigs under request load recompiling faster than they can
  amortise) the advisor's ``device-pressure`` rule reads. The AOT
  harvest is the observation point, so ``RTPU_LEDGER_XLA=0`` (or an
  analyses-incapable backend) darkens this plane with the estimates.

Knobs
-----
* ``RTPU_DEVICE_TIMING`` — sampled timed-dispatch rate in (0, 1]
  (default 0.05; ``0`` disables; ``1`` times every dispatch). Rides the
  ledger plane: ``RTPU_LEDGER=0`` disables it too.
* ``RTPU_KERNEL_REGISTRY_CAP`` — (kernel, shape-sig) entry cap shared
  with the ledger's ``KernelRegistry`` (oldest evicted; ``0`` disables).
* ``RTPU_DEVICE_DUMP`` — file path; the full ``/devicez`` document is
  written there at interpreter exit (the CI failure-artifact hook).
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque

from ..analysis.sanitizer import (note_shared as _san_note,
                                  track_shared as _san_track)

DEFAULT_RATE = 0.05
#: bounded warm-sample window per (kernel, sig) — recent-biased, like
#: the flight recorder: the p50 should describe the CURRENT regime
SAMPLE_WINDOW = 128
#: recent-compile ring bound (the compile-storm evidence window)
COMPILE_RING = 256
DEFAULT_REGISTRY_CAP = 512
#: measured seconds beyond this multiple of the model's predicted
#: seconds re-classify as overhead_bound — the time is real but the
#: roofline terms don't explain it (dispatch overhead, sync drain)
OVERHEAD_FACTOR = 4.0


def timing_rate() -> float:
    """``RTPU_DEVICE_TIMING`` resolved to a sampling rate in [0, 1] —
    re-read per dispatch (one getenv, the ledger-gate pattern) so the
    bench A/B and operators can flip it without a restart."""
    raw = os.environ.get("RTPU_DEVICE_TIMING")
    if raw is None or raw == "":
        return DEFAULT_RATE
    if raw in ("0", "false"):
        return 0.0
    try:
        return min(1.0, max(0.0, float(raw)))
    except ValueError:
        return DEFAULT_RATE


def registry_cap() -> int:
    """``RTPU_KERNEL_REGISTRY_CAP`` — the (kernel, shape-sig) entry cap
    the ledger's KernelRegistry and this module's timing table share
    (shape-diverse request traffic must not grow either without bound —
    rtpulint RT011). 0 disables."""
    try:
        return max(0, int(os.environ.get("RTPU_KERNEL_REGISTRY_CAP",
                                         DEFAULT_REGISTRY_CAP)))
    except ValueError:
        return DEFAULT_REGISTRY_CAP


def evict_past_cap(table: dict, cap: int, keep) -> list:
    """Shrink ``table`` to ``cap`` entries by evicting from the FRONT of
    the dict — the single bounded-registry policy the kernel registry
    and the timing table share. Callers re-insert a key at the BACK on
    every touch, so front-of-dict means least-recently-used, not
    first-registered: a hot kernel's row is never the one to go. The
    just-inserted ``keep`` key is never evicted (a cap below 1 live
    entry must not thrash it). Returns the evicted keys; the caller
    holds the table's lock and runs any cross-table hooks AFTER
    releasing it."""
    evicted = []
    while cap and len(table) > cap:
        oldest = next(iter(table))
        if oldest == keep:
            break
        del table[oldest]
        evicted.append(oldest)
    return evicted


def _metrics():
    """obs.metrics bundle, or None when prometheus isn't importable."""
    try:
        from .metrics import METRICS

        return METRICS
    except Exception:
        return None


def _peaks():
    """(peak FLOP/s, peak B/s) for the probed platform — the ledger's
    roofline anchors (order-of-magnitude, not calibration; that gap is
    exactly what the divergence ratio renders visible)."""
    from . import ledger as _ledger

    platform = _ledger.xla_analysis_caps().get("platform", "cpu")
    return _ledger._PEAKS.get(platform, _ledger._PEAKS["cpu"])


def estimated_seconds(flops, hbm_bytes) -> float | None:
    """The roofline model's predicted per-dispatch seconds:
    max(flops / peak FLOP/s, bytes / peak bandwidth) — None without
    harvested estimates. The divergence ratio divides measured p50 by
    THIS, so it is a judgement on the whole model (XLA harvest + traffic
    model + platform anchors), not on one term."""
    if not flops and not hbm_bytes:
        return None
    pf, bw = _peaks()
    return max(float(flops or 0.0) / pf, float(hbm_bytes or 0.0) / bw)


# --------------------------------------------------------- kernel timing


class _Timing:
    """Warm-sample window + lifetime counters for one (kernel, sig)."""

    __slots__ = ("samples", "count", "sum_seconds", "min_seconds",
                 "max_seconds", "cold_seconds", "last_unix")

    def __init__(self):
        self.samples: deque = deque(maxlen=SAMPLE_WINDOW)
        self.count = 0          # warm timed dispatches, lifetime
        self.sum_seconds = 0.0
        self.min_seconds = float("inf")
        self.max_seconds = 0.0
        self.cold_seconds = None   # the always-timed first dispatch
        self.last_unix = 0.0

    def observe(self, seconds: float, cold: bool) -> None:
        self.last_unix = time.time()
        if cold:
            self.cold_seconds = seconds
            return
        self.samples.append(seconds)
        self.count += 1
        self.sum_seconds += seconds
        self.min_seconds = min(self.min_seconds, seconds)
        self.max_seconds = max(self.max_seconds, seconds)

    def summary(self) -> dict:
        out: dict = {"samples": self.count,
                     "last_unix": round(self.last_unix, 3)}
        if self.cold_seconds is not None:
            out["cold_seconds"] = round(self.cold_seconds, 6)
        vals = sorted(self.samples)
        if vals:
            out["p50_seconds"] = round(
                vals[(len(vals) - 1) // 2], 6)
            out["p99_seconds"] = round(
                vals[min(len(vals) - 1, int(0.99 * len(vals)))], 6)
            out["min_seconds"] = round(self.min_seconds, 6)
            out["max_seconds"] = round(self.max_seconds, 6)
            out["mean_seconds"] = round(
                self.sum_seconds / max(1, self.count), 6)
        elif self.cold_seconds is not None:
            # dispatched once, ever: the cold sample is all there is —
            # flagged so readers don't mistake compile for execute
            out["p50_seconds"] = round(self.cold_seconds, 6)
            out["cold_only"] = True
        return out


class DeviceTiming:
    """Process-wide sampled-dispatch timing table, keyed like the kernel
    registry by (kernel name, joined shape sig). Bounded by the SAME
    ``RTPU_KERNEL_REGISTRY_CAP`` (oldest evicted) and additionally
    pruned by the registry's own evictions (``evict``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._timings: dict[tuple, _Timing] = {}
        self._counters: dict[tuple, int] = {}
        self.evictions = 0
        self._san_tracker = _san_track("device_timing")

    def should_sample(self, name: str, sig: tuple) -> tuple[bool, bool]:
        """(timed, cold) decision for the dispatch about to run: first
        dispatch of a (kernel, sig) is always timed as the cold sample,
        the second always timed warm, then every 1/rate-th."""
        rate = timing_rate()
        if rate <= 0.0:
            return False, False
        key = (name, "×".join(sig))
        with self._lock:
            _san_note(self._san_tracker, True)
            n = self._counters.get(key, 0) + 1
            self._counters[key] = n
        if n == 1:
            return True, True
        if n == 2:
            return True, False
        interval = max(1, round(1.0 / rate))
        return n % interval == 0, False

    def observe(self, name: str, sig: tuple, seconds: float,
                cold: bool = False) -> None:
        key = (name, "×".join(sig))
        with self._lock:
            _san_note(self._san_tracker, True)
            t = self._timings.get(key)
            if t is None:
                t = self._timings[key] = _Timing()
                # counters share this lock: dropping an evicted key's
                # counter OUTSIDE it would race a concurrent
                # should_sample re-creating the key and delete the
                # fresh count (a phantom second cold sample)
                for old in evict_past_cap(self._timings,
                                          registry_cap(), key):
                    self.evictions += 1
                    self._counters.pop(old, None)
            else:
                # LRU touch: re-insert at the back so the cap evicts
                # the coldest (kernel, sig), never the hottest
                self._timings[key] = self._timings.pop(key)
            t.observe(float(seconds), cold)
        m = _metrics()
        if m is not None and not cold:
            m.device_kernel_seconds.labels(name).observe(float(seconds))

    def evict(self, key: tuple) -> None:
        """Registry-eviction hook: (name, sig tuple) keys from the
        ledger's KernelRegistry cap drop their timing rows too."""
        k = (key[0], "×".join(key[1]))
        with self._lock:
            _san_note(self._san_tracker, True)
            self._timings.pop(k, None)
            self._counters.pop(k, None)

    def summaries(self) -> dict[tuple, dict]:
        with self._lock:
            _san_note(self._san_tracker, False)
            return {k: t.summary() for k, t in self._timings.items()}

    def totals(self) -> dict:
        with self._lock:
            _san_note(self._san_tracker, False)
            return {"kernels_measured": len(self._timings),
                    "warm_samples": sum(t.count
                                        for t in self._timings.values()),
                    "evictions": self.evictions}

    def clear(self) -> None:
        with self._lock:
            self._timings.clear()
            self._counters.clear()
            self.evictions = 0


TIMING = DeviceTiming()


def block_ready(out) -> bool:
    """Block until ``out`` (any pytree of device arrays) is computed —
    the sampled-dispatch sync. Never raises: a backend losing the race
    mid-sync must cost a sample, not the dispatch that produced it.
    Returns False on a failed sync so the caller SKIPS the observation
    — an unsynced duration is enqueue time, and recording it would
    poison the percentiles the divergence/bound_measured math reads."""
    try:
        import jax

        jax.block_until_ready(out)
        return True
    except Exception:
        return False


def measured_row(rec: dict, timing: dict | None) -> dict:
    """Join one kernel-registry record with its measured timing summary:
    achieved FLOP/s / bytes/s at the measured p50, the divergence ratio
    over the roofline model's predicted seconds, and the
    ``bound_measured`` re-classification — ``overhead_bound`` when the
    measured time is more than ``OVERHEAD_FACTOR``x what BOTH roofline
    terms predict (the model does not explain where the time goes),
    else whichever predicted term dominates."""
    out = {"kernel": rec.get("kernel"), "sig": rec.get("sig"),
           "dispatches": rec.get("dispatches"),
           "bound": rec.get("bound"),
           "bound_refined": rec.get("bound_refined"),
           "bound_measured": "unknown",
           "measured": timing or {}}
    p50 = (timing or {}).get("p50_seconds")
    if not p50 or p50 <= 0:
        return out
    flops = rec.get("flops") or 0.0
    nbytes = rec.get("bytes_accessed") or 0.0
    hbm = rec.get("est_hbm_bytes") or nbytes
    if flops:
        out["achieved_flops_per_s"] = round(flops / p50, 1)
    if nbytes:
        out["achieved_bytes_per_s"] = round(nbytes / p50, 1)
    if hbm:
        out["achieved_hbm_bytes_per_s"] = round(hbm / p50, 1)
    est = estimated_seconds(flops, hbm)
    if est and est > 0:
        out["est_seconds"] = round(est, 9)
        out["divergence"] = round(p50 / est, 4)
        pf, bw = _peaks()
        compute_t = float(flops) / pf
        mem_t = float(hbm) / bw
        if p50 > OVERHEAD_FACTOR * max(compute_t, mem_t):
            out["bound_measured"] = "overhead_bound"
        else:
            out["bound_measured"] = ("compute_bound"
                                     if compute_t >= mem_t
                                     else "hbm_bound")
    return out


def measured_table() -> list[dict]:
    """Every registered kernel joined with its measured stats, most
    measured-time-covered first — the ``/devicez`` kernel table."""
    from . import ledger as _ledger

    summaries = TIMING.summaries()
    rows = []
    for rec in _ledger.REGISTRY.snapshot():
        t = summaries.get((rec.get("kernel"), rec.get("sig")))
        rows.append(measured_row(rec, t))
    rows.sort(key=lambda r: -(r["measured"].get("p50_seconds") or 0.0)
              * (r.get("dispatches") or 0))
    return rows


# --------------------------------------------------------- device memory


def memory_snapshot() -> dict:
    """``memory_stats()`` of the first device, degrade-tolerant: backends
    that return None or raise (CPU rigs, older jaxlibs) yield
    ``{"available": False}`` — the ``/devicez`` memory block and every
    sampler must keep serving through that, never crash or 500."""
    try:
        import jax

        dev = jax.devices()[0]
        stats = dev.memory_stats()
    except Exception as e:
        return {"available": False,
                "error": f"{type(e).__name__}: {e}"[:200]}
    if not stats:
        return {"available": False,
                "reason": "backend returns no memory_stats"}
    out = {"available": True,
           "bytes_in_use": int(stats.get("bytes_in_use") or 0),
           "peak_bytes_in_use": int(stats.get("peak_bytes_in_use") or 0)}
    limit = int(stats.get("bytes_limit") or 0)
    if limit:
        out["bytes_limit"] = limit
        out["in_use_fraction"] = round(out["bytes_in_use"] / limit, 4)
    return out


def series_bytes_in_use() -> float:
    """Series-ring collector (obs/slo.SERIES): raises when the backend
    has no memory counters so the sample records None — the ring's
    contract for a failing collector (the thread never dies)."""
    snap = memory_snapshot()
    if not snap.get("available"):
        raise RuntimeError("device memory_stats unavailable")
    return float(snap["bytes_in_use"])


def gauge_bytes_in_use() -> float:
    """Prometheus set_function callback — scrape callbacks must never
    raise, so unavailable degrades to 0.0 (the /devicez block is the
    authoritative 'unavailable vs empty' surface)."""
    try:
        snap = memory_snapshot()
        return float(snap.get("bytes_in_use") or 0.0) \
            if snap.get("available") else 0.0
    except Exception:
        return 0.0


# ------------------------------------------------ resident-buffer registry


class ResidentRegistry:
    """Live gauge of device-resident buffers, weakref-keyed by OWNER
    (an engine or a log): ``track(owner, kind, nbytes)`` upserts the
    owner's ``kind`` row, and the row disappears with the owner — the
    registry cannot outlive-leak a dead engine's bytes (RT011 by
    construction, no cap needed)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._by_owner: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._san_tracker = _san_track("device_resident")

    def track(self, owner, kind: str, nbytes: int, **info) -> None:
        """Upsert ``owner``'s ``kind`` buffer at ``nbytes``. Never
        raises: an owner that doesn't support weakrefs just isn't
        tracked (the gauge is best-effort observability)."""
        row = {"kind": str(kind), "nbytes": max(0, int(nbytes)),
               "owner": type(owner).__name__,
               "unix": round(time.time(), 3), **info}
        try:
            with self._lock:
                _san_note(self._san_tracker, True)
                self._by_owner.setdefault(owner, {})[str(kind)] = row
        except TypeError:
            pass

    def drop(self, owner, kind: str | None = None) -> None:
        try:
            with self._lock:
                _san_note(self._san_tracker, True)
                rows = self._by_owner.get(owner)
                if rows is None:
                    return
                if kind is None:
                    del self._by_owner[owner]
                else:
                    rows.pop(str(kind), None)
        except TypeError:
            pass

    def snapshot(self) -> dict:
        with self._lock:
            _san_note(self._san_tracker, False)
            rows = [dict(r) for rows in self._by_owner.values()
                    for r in rows.values()]
        rows.sort(key=lambda r: -r["nbytes"])
        return {"buffers": rows,
                "total_bytes": sum(r["nbytes"] for r in rows)}

    def clear(self) -> None:
        with self._lock:
            self._by_owner = weakref.WeakKeyDictionary()


RESIDENT = ResidentRegistry()


def nbytes_tree(obj) -> int:
    """Recursive ``nbytes`` sum over a tuple/list tree of (device or
    host) arrays — what the engines account their resident state at."""
    if obj is None:
        return 0
    if isinstance(obj, (list, tuple)):
        return sum(nbytes_tree(x) for x in obj)
    return int(getattr(obj, "nbytes", 0) or 0)


# ------------------------------------------------- compile observability


_COMPILE_LOCK = threading.Lock()
_COMPILES: dict[str, dict] = {}
_COMPILE_RING: deque = deque(maxlen=COMPILE_RING)


def note_compile(kernel: str, sig: str, seconds: float) -> None:
    """One observed ``lower().compile()`` from the kernel registry's
    harvest path (which shares the in-memory XLA cache with the dispatch
    path, so every NEW (kernel, shapes) program lands exactly once
    here). Coverage caveat: the AOT harvest IS the observation point —
    under ``RTPU_LEDGER_XLA=0`` (or on backends whose analyses probe
    unavailable) there is no AOT compile to observe and this plane goes
    dark along with the estimates (documented in OBSERVABILITY.md
    "Device runtime"). Never raises."""
    try:
        now = time.time()
        with _COMPILE_LOCK:
            rec = _COMPILES.get(kernel)
            if rec is None:
                rec = _COMPILES[kernel] = {
                    "compiles": 0, "seconds": 0.0,
                    "last_sig": "", "last_unix": 0.0}
            rec["compiles"] += 1
            rec["seconds"] = round(rec["seconds"] + float(seconds), 4)
            rec["last_sig"] = str(sig)
            rec["last_unix"] = round(now, 3)
            _COMPILE_RING.append({"kernel": kernel, "sig": str(sig),
                                  "seconds": round(float(seconds), 4),
                                  "unix": round(now, 3)})
        m = _metrics()
        if m is not None:
            m.compiles.labels(kernel).inc()
            m.compile_seconds.labels(kernel).inc(float(seconds))
    except Exception:
        pass


def compile_block() -> dict:
    """Per-kernel compile counts/seconds/last-shape-sig — the block
    ``/statusz.compile_caches`` embeds under ``kernels`` next to the
    lru factory stats."""
    with _COMPILE_LOCK:
        return {k: dict(v) for k, v in sorted(_COMPILES.items())}


def recent_compiles(n: int = 32) -> list[dict]:
    with _COMPILE_LOCK:
        snap = list(_COMPILE_RING)
    return snap[-max(0, int(n)):]


#: compile-storm detection window / threshold (the advisor rule's bar)
STORM_WINDOW_S = 60.0


def storm_threshold() -> int:
    """``RTPU_ADVISOR_COMPILE_STORM`` — compile events inside the last
    ``STORM_WINDOW_S`` seconds that count as a storm (default 16; a
    healthy warm-up compiles a handful, shape-diverse request traffic
    recompiling under load hits tens)."""
    try:
        return max(1, int(os.environ.get("RTPU_ADVISOR_COMPILE_STORM",
                                         16)))
    except ValueError:
        return 16


def compile_storm() -> dict:
    """The request-path compile-storm signal: how many compiles (and
    how many DISTINCT shape sigs) landed inside the detection window."""
    cutoff = time.time() - STORM_WINDOW_S
    with _COMPILE_LOCK:
        recent = [e for e in _COMPILE_RING if e["unix"] >= cutoff]
    return {
        "window_seconds": STORM_WINDOW_S,
        "threshold": storm_threshold(),
        "events_in_window": len(recent),
        "distinct_sigs_in_window": len({(e["kernel"], e["sig"])
                                        for e in recent}),
        "seconds_in_window": round(sum(e["seconds"] for e in recent), 4),
        "storm": len(recent) >= storm_threshold(),
    }


def clear_compiles() -> None:
    with _COMPILE_LOCK:
        _COMPILES.clear()
        _COMPILE_RING.clear()


# ------------------------------------------------------------- surfaces


def status_block() -> dict:
    """The compact ``device`` block /statusz embeds (what /clusterz
    federates per process): counts and gauges only, never the tables."""
    mem = memory_snapshot()
    storm = compile_storm()
    return {
        "timing": {"rate": timing_rate(), **TIMING.totals()},
        "memory": mem if mem.get("available")
        else {"available": False},
        "resident_bytes": RESIDENT.snapshot()["total_bytes"],
        "compile": {"kernels": len(compile_block()),
                    "events_in_window": storm["events_in_window"],
                    "storm": storm["storm"]},
    }


def devicez() -> dict:
    """The full ``/devicez`` document: the measured kernel table
    (estimates joined with sampled timings, divergence, and the
    measured re-classification), the device-memory snapshot (or its
    honest degrade), the resident-buffer registry, and recent compile
    events with the storm signal."""
    mem = memory_snapshot()
    return {
        "timing": {
            "rate": timing_rate(),
            **TIMING.totals(),
            "semantics": (
                "sampled dispatches block until ready and record wall "
                "seconds — dispatch overhead and pipeline drain "
                "included; divergence = measured p50 / roofline-model "
                "predicted seconds; bound_measured is overhead_bound "
                "when measured exceeds "
                f"{OVERHEAD_FACTOR:.0f}x both model terms"),
            "kernels": measured_table(),
        },
        "memory": mem if mem.get("available") else
        {"available": False, "detail": mem,
         "note": "memory: unavailable — backend exposes no "
                 "memory_stats; timing and compile planes unaffected"},
        "resident": RESIDENT.snapshot(),
        "compile": {
            **compile_storm(),
            "kernels": compile_block(),
            "recent": recent_compiles(32),
        },
    }


def advisor_signals() -> dict:
    """The ``device`` block of the advisor's signals dict
    (obs/advisor.gather_signals): measured kernel rows, the memory
    snapshot, and the compile-storm block."""
    return {"timing": measured_table(), "memory": memory_snapshot(),
            "compile": compile_storm()}


def clear() -> None:
    """Reset every device-plane table (tests + bench arms)."""
    TIMING.clear()
    RESIDENT.clear()
    clear_compiles()


_device_dump = os.environ.get("RTPU_DEVICE_DUMP")
if _device_dump:
    from . import exitdump as _exitdump

    def _dump_devicez(path=_device_dump):
        with open(path, "w") as f:
            json.dump(devicez(), f)

    _exitdump.register("device", _dump_devicez)
