"""Freshness plane — how fresh is what we serve?

The serving side has had a full SLO stack since PR 9 (latency histograms
with trace exemplars, error budgets, burn-rate grading); the ingest/live
side — the half the paper's "live temporal graph" identity rests on —
had two span instants and one watermark-lag gauge. This module is the
streaming mirror of ``obs/slo.py`` + ``obs/budget.py``:

* **Per-source ingest telemetry.** Every pipeline sink batch reports
  updates/s, batch sizes, op-type/tombstone mix, and an
  **out-of-orderness histogram**: the event-time distance each event
  arrived behind its source's high-water mark. The commutative
  bitemporal store makes disorder *safe*; this makes it *visible* — and
  an observed distance past the source's declared ``disorder`` bound is
  a watermark-promise violation the ``out-of-order-excess`` advisor
  rule alarms on.
* **Ingest-to-queryable latency.** Each sink batch is wall-stamped at
  arrival and becomes *queryable* when the global safe time passes its
  max event time (that is when ``view_at(T, exact=True)`` unblocks for
  it) — per-source "event at T became queryable at wall W" histograms
  whose buckets carry trace-ID exemplars (the PR 9 machinery,
  ``obs/slo._Hist``), drained by ``WatermarkRegistry`` on every fence
  advance.
* **Live-query staleness.** Every Live job run records its
  ``result_watermark`` against the ingest head into per-algorithm
  staleness-seconds histograms (a bounded head clock maps event-time
  heads to wall time). ``RTPU_FRESH_TARGET`` (``pagerank=p99:5s``)
  judges them through the ``obs/budget.py`` multi-window burn-rate
  machinery and grades ``/healthz``.
* **Surfaces.** ``/freshz`` (full document, ``RTPU_FRESH_DUMP`` CI
  artifact), a compact ``/statusz`` block, ``/slz`` series collectors
  (updates/s, queryable lag, backlog), ``raphtory_ingest_*`` /
  ``raphtory_freshness_*`` metrics, and ``/clusterz`` federation with a
  merged min-watermark + per-process watermark spread.

Everything follows the telemetry prime directive: no call here may
raise into the ingest hot path, all state is bounded (RT011), and
``RTPU_FRESH=0`` silences observation entirely (the
``ingest_obs_overhead`` bench's off arm).

Knobs
-----
* ``RTPU_FRESH`` — the whole plane's observation (default on).
* ``RTPU_FRESH_TARGET`` — staleness targets ``<algorithm>=p<Q>:<lat>``.
* ``RTPU_FRESH_PENDING`` — per-source pending-batch record cap.
* ``RTPU_FRESH_DUMP`` — file path; ``/freshz`` dumped at exit.
* ``RTPU_INGEST_OOO_BUCKETS`` — out-of-orderness histogram bounds
  (event-time units, comma-separated).
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref
from collections import deque

from ..analysis.sanitizer import (note_shared as _san_note,
                                  track_shared as _san_track)
from . import journal as _journal
from .slo import _Hist, _metrics
from .trace import TRACER

#: ingest→queryable / staleness histogram grid (seconds): live analytics
#: SLOs live in the sub-second..minutes band
DEFAULT_SECONDS_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                           10.0, 30.0, 60.0, 300.0)
#: out-of-orderness bounds in EVENT-TIME units (domain-specific; the
#: knob overrides). Bucket i counts distances in (bounds[i-1], bounds[i]].
DEFAULT_OOO_BOUNDS = (1, 10, 100, 1_000, 10_000, 100_000, 1_000_000)
DEFAULT_PENDING = 4096
#: registry caps (RT011): a misbehaving deployment must not mint
#: unbounded per-source/per-algorithm state through the ingest surface
MAX_SOURCES = 256
MAX_ALGOS = 64
#: live-subscription table cap (RT011): one row per live job, keyed by
#: job id — a runaway submitter must not mint unbounded registry state
MAX_LIVE_SUBS = 64
#: per-subscription ring of recent epochs (mode, delta rows, shipped
#: bytes, staleness) — the /freshz epoch SERIES, bounded per sub
MAX_LIVE_RECENT = 128
#: head-clock ring: (event_time_head, wall) pairs, ~1 per sink batch
HEAD_RING = 4096
#: per-source batch-arrival ring for the updates/s window
RATE_RING = 512
RATE_WINDOW_S = 10.0
#: per-event pass sampling: batches at or past DEEP_EXACT_N events pay
#: the O(n) accounting passes (op-mix bincount + out-of-orderness
#: check) only 1 in DEEP_SAMPLE batches — on a multi-M-updates/s
#: columnar stream those two passes ARE the plane's cost, and both
#: signals are fractions/distributions a deterministic batch sample
#: estimates without bias (the RTPU_DEVICE_TIMING rationale). Smaller
#: (row-path) batches are counted exactly. Event totals, batch sizes,
#: high-water marks, pending queryable records and the head clock stay
#: EXACT on every batch — only the mix and the disorder distribution
#: are sampled, and their coverage counters ride on /freshz.
DEEP_EXACT_N = 1024
DEEP_SAMPLE = 4
_NEG_INF = -(2**62)
_GRADE_ORDER = {"ok": 0, "degraded": 1, "burning": 2}
#: live-evaluation cache TTL (obs/budget.py rationale: /healthz probes,
#: /statusz scrapes and advisor ticks share one pass per second)
EVAL_CACHE_S = 1.0


def enabled() -> bool:
    """Re-read per observation so the A/B bench (and operators) can
    flip the plane without a restart — one getenv per sink BATCH, not
    per event."""
    return os.environ.get("RTPU_FRESH", "1") not in ("", "0", "false")


def pending_cap() -> int:
    try:
        v = int(os.environ.get("RTPU_FRESH_PENDING", "") or DEFAULT_PENDING)
        return max(16, v)
    except ValueError:
        return DEFAULT_PENDING


def ooo_bounds() -> tuple:
    """Out-of-orderness histogram upper bounds (event-time units),
    ascending; unparseable overrides fall back to the default grid
    (telemetry must never take ingest down)."""
    raw = os.environ.get("RTPU_INGEST_OOO_BUCKETS", "")
    if raw:
        try:
            bounds = tuple(sorted(int(float(x)) for x in raw.split(",")
                                  if x))
            if bounds and all(b > 0 for b in bounds):
                return bounds
        except ValueError:
            pass
    return DEFAULT_OOO_BOUNDS


#: event-kind display order (core/events.py constants 0..3)
_KIND_NAMES = ("vertex_add", "vertex_delete", "edge_add", "edge_delete")
_TOMBSTONE_KINDS = (1, 3)   # VERTEX_DELETE, EDGE_DELETE


class _SourceStats:
    """One ingest source's telemetry (mutated under the registry lock)."""

    __slots__ = ("name", "disorder", "stage", "events", "batches",
                 "large_batches", "batch_events_max", "kinds",
                 "kinds_events", "ooo_counts", "ooo_events",
                 "ooo_events_seen", "ooo_max", "max_t", "queryable",
                 "pending", "pending_dropped", "recent", "prom")

    def __init__(self, name: str, disorder: int, stage: str):
        self.name = name
        self.disorder = int(disorder)
        self.stage = stage
        self.events = 0
        self.batches = 0
        # counter of DEEP_EXACT_N-sized batches ONLY — the 1-in-
        # DEEP_SAMPLE decision keys on it, so a stream mixing small and
        # large batches still deep-samples exactly 1 in 4 of its LARGE
        # batches (keying on the global batch counter would let the
        # small batches alias the phase and over/under-sample the large
        # half arbitrarily)
        self.large_batches = 0
        self.batch_events_max = 0
        # op-mix + out-of-orderness counts over DEEP-SAMPLED events
        # (see DEEP_EXACT_N/DEEP_SAMPLE): kinds_events / ooo_events_seen
        # record the coverage so the fractions stay exact ratios of
        # what was actually counted
        self.kinds = [0, 0, 0, 0]
        self.kinds_events = 0            # events the mix counts cover
        self.ooo_events_seen = 0         # events the ooo pass covered
        self.ooo_counts = [0] * (len(ooo_bounds()) + 1)
        self.ooo_events = 0
        self.ooo_max = 0
        self.max_t = _NEG_INF            # source event-time high water
        # cached per-source Prometheus children — .labels() costs a
        # registry lock + dict walk per call, too much for the per-batch
        # hot path; None until the first mirror (or forever, without
        # prometheus)
        self.prom: tuple | None = None
        self.queryable = _Hist(DEFAULT_SECONDS_BUCKETS)
        # (batch max event time, arrival wall, trace_id) — queryable
        # once the global safe time passes the max event time
        self.pending: deque = deque()
        self.pending_dropped = 0
        self.recent: deque = deque(maxlen=RATE_RING)   # (wall, n_events)

    def updates_per_s(self, now: float) -> float:
        n = sum(c for w, c in self.recent if now - w <= RATE_WINDOW_S)
        span = RATE_WINDOW_S
        if self.recent and len(self.recent) == self.recent.maxlen:
            # the ring truncated history: at high batch rates 512
            # entries span far less than the nominal window, and
            # dividing by the full window would under-report the rate
            # by the truncation factor
            span = min(RATE_WINDOW_S,
                       max(now - self.recent[0][0], 1e-3))
        return n / span

    def as_dict(self, now: float, bounds: tuple) -> dict:
        """``bounds`` are the REGISTRY's cached counting bounds — the
        labels must describe the grid the counts accumulated against,
        not a live env re-read (a mid-run knob flip would otherwise
        silently relabel old counts)."""
        covered = max(1, self.kinds_events)
        tomb = sum(self.kinds[k] for k in _TOMBSTONE_KINDS)
        return {
            "stage": self.stage,
            "disorder_bound": self.disorder,
            "events": self.events,
            "batches": self.batches,
            "mean_batch_events": round(self.events / max(1, self.batches),
                                       1),
            "max_batch_events": self.batch_events_max,
            "updates_per_s": round(self.updates_per_s(now), 1),
            "kinds": dict(zip(_KIND_NAMES, self.kinds)),
            "mix_sampled_events": self.kinds_events,
            "tombstone_fraction": round(tomb / covered, 4),
            "out_of_order": {
                "bounds": list(bounds)[:len(self.ooo_counts) - 1],
                "counts": list(self.ooo_counts),
                "events": self.ooo_events,
                "sampled_events": self.ooo_events_seen,
                "max_distance": self.ooo_max,
                "past_disorder_bound": self.ooo_max > self.disorder,
            },
            "high_water_time": (self.max_t if self.max_t > _NEG_INF
                                else None),
            "queryable_seconds": self.queryable.as_dict(),
            "pending_batches": len(self.pending),
            "pending_dropped": self.pending_dropped,
        }


class FreshnessRegistry:
    """Process-wide freshness plane. All mutation under one lock; numpy
    batch math happens before the lock is taken, Prometheus mirroring
    after it is released (RT009 hygiene — the lock only ever guards
    dict/deque ops)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sources: dict[str, _SourceStats] = {}
        self.dropped_sources = 0
        #: (event_time_head, wall) ring mapping event-time heads to wall
        #: clocks — what dates a live result's staleness
        self._head: deque = deque(maxlen=HEAD_RING)
        self._staleness: dict[str, _Hist] = {}
        self.dropped_algos = 0
        #: per-live-subscription epoch table (bounded, keyed by job id):
        #: what /statusz + /freshz surface about the epoch engine
        self._live_subs: dict = {}
        self.dropped_live_subs = 0
        self.undated_results = 0
        self.last_safe: int | None = None
        self.last_safe_wall = 0.0
        #: weakly-held ingestion pipelines (backlog + queue bound)
        self._pipes: list = []
        #: router stage: per-shard routed event counts + dead-letter depth
        self._routed: dict[int, int] = {}
        self._route_pending = 0
        # freshness budget state (the RTPU_FRESH_TARGET judgment)
        self._registered: dict[str, float] = {}
        self._last_grades: dict[str, str] = {}
        self._eval_cache: tuple | None = None
        # cached knobs (a getenv — and for the bounds a parse+sort —
        # per batch is hot-path cost); re-read on clear(), the
        # test/bench reset point
        self._pending_cap = pending_cap()
        self._ooo_bounds = ooo_bounds()
        self._san_tracker = _san_track("freshness_registry")

    # ---- registration ----

    def register_source(self, name: str, disorder: int = 0,
                        stage: str = "source") -> None:
        with self._lock:
            _san_note(self._san_tracker, True)
            if name in self._sources:
                return
            if len(self._sources) >= MAX_SOURCES:
                self.dropped_sources += 1
                return
            self._sources[name] = _SourceStats(str(name), disorder, stage)

    def attach_pipeline(self, pipe) -> None:
        """Weakly attach an IngestionPipeline so /freshz and the series
        ring can read its staged backlog + queue bound without pinning a
        dead pipeline (the registry is process-wide)."""
        with self._lock:
            self._pipes = [r for r in self._pipes if r() is not None]
            if len(self._pipes) < 64:   # bounded (RT011)
                self._pipes.append(weakref.ref(pipe))

    # ---- ingest-side observation ----

    def note_batch(self, source: str, t, k=None,
                   trace_id: str | None = None,
                   now: float | None = None,
                   stage: str | None = None) -> None:
        """One sink batch arrived from ``source``: op mix, batch size,
        out-of-orderness vs the source high water, and a pending
        queryable record stamped at arrival. ``stage`` labels the sink
        mode (direct/staged). Numpy math runs before the lock; never
        raises into the ingest path."""
        if not enabled():
            return
        try:
            self._note_batch(source, t, k, trace_id, now, stage)
        except Exception:   # telemetry never takes ingest down
            pass

    def _note_batch(self, source, t, k, trace_id, now, stage) -> None:
        import numpy as np

        n = int(len(t))
        if not n:
            return
        now = time.time() if now is None else float(now)
        # CPython dict reads are atomic: the racy fast-path get saves a
        # lock round-trip per batch; a miss (first batch of an
        # unregistered source) takes the locked create path once
        st = self._sources.get(source)
        if st is None:
            with self._lock:
                st = self._sources.get(source)
                if st is None:
                    _san_note(self._san_tracker, True)
                    if len(self._sources) >= MAX_SOURCES:
                        self.dropped_sources += 1
                        return
                    st = self._sources[source] = _SourceStats(
                        str(source), 0, "source")
        # a source's batches arrive from ONE thread (its consume loop /
        # the staged writer runs the pipeline's ordering), so reading
        # its high water outside the lock is single-writer-consistent;
        # the numpy passes below then run lock-free (RT009 hygiene)
        prev_max = st.max_t
        t = np.asarray(t)
        ooo_n = 0
        # DEEP batches pay the O(n) accounting passes (ooo check + mix
        # bincount); shallow ones only the exact O(n)-but-SIMD max.
        # Deterministic on the LARGE-batch counter (see
        # _SourceStats.large_batches), so both arms of an A/B stream do
        # identical work per pair and mixed-size streams stay unbiased.
        large = n >= DEEP_EXACT_N
        deep = not large or st.large_batches % DEEP_SAMPLE == 0
        mix_scale = DEEP_SAMPLE if large else 1
        kind_counts = None
        # batch_max is the BATCH's own max event time — the queryable
        # record's fence bar (a late batch unblocks exact views once
        # the fence covers ITS events, not the source's high water);
        # the high water folds in separately at st.max_t below
        if not deep:
            batch_max = int(t.max())
        elif int(t[0]) >= prev_max \
                and (n < 2 or bool((t[1:] >= t[:-1]).all())):
            # a time-sorted batch landing at or past the high water
            # carries ZERO out-of-order events — one comparison pass
            # proves it and the distance math is skipped entirely
            batch_max = int(t[-1])
        else:
            # out-of-orderness: distance behind the running high water
            # (previous batches' max folded in) — the arrival-side view
            # of the disorder the watermark promise must absorb
            run = np.maximum.accumulate(t)
            high = np.maximum(prev_max, run) if prev_max > _NEG_INF \
                else run
            dist = high - t
            ooo = dist[dist > 0]
            ooo_n = int(len(ooo))
            batch_max = int(run[-1])
            bounds = self._ooo_bounds
            if ooo_n:
                bucket_i, bucket_c = np.unique(
                    np.searchsorted(bounds, ooo, side="left"),
                    return_counts=True)
                ooo_max = int(ooo.max())
        if deep and k is not None:
            kind_counts = np.bincount(np.asarray(k), minlength=4)
        if trace_id is None and TRACER.enabled:
            ctx = TRACER.capture()
            trace_id = ctx.trace_id if ctx is not None else None
        with self._lock:
            _san_note(self._san_tracker, True)
            if stage is not None:
                st.stage = stage
            if ooo_n:
                if len(st.ooo_counts) != len(bounds) + 1:
                    st.ooo_counts = [0] * (len(bounds) + 1)   # knob flip
                for i, c in zip(bucket_i.tolist(), bucket_c.tolist()):
                    st.ooo_counts[int(i)] += int(c)
                st.ooo_events += ooo_n
                st.ooo_max = max(st.ooo_max, ooo_max)
            st.events += n
            st.batches += 1
            if large:
                st.large_batches += 1
            if deep:
                st.ooo_events_seen += n
            if n > st.batch_events_max:
                st.batch_events_max = n
            st.recent.append((now, n))
            if kind_counts is not None:
                st.kinds_events += n
                for i in range(min(4, len(kind_counts))):
                    st.kinds[i] += int(kind_counts[i])
            if batch_max > st.max_t:
                st.max_t = batch_max
            # queryable pending record, stamped at ARRIVAL (staged-queue
            # wait is part of ingest-to-queryable by design)
            st.pending.append((batch_max, now, trace_id))
            while len(st.pending) > self._pending_cap:
                st.pending.popleft()
                st.pending_dropped += 1
            # head clock: only appended when the process-wide ingest
            # head actually advances, so the ring stays monotone in
            # event time (bisect depends on it)
            if not self._head or batch_max > self._head[-1][0]:
                self._head.append((batch_max, now))
        prom = st.prom
        if prom is None:
            m = _metrics()
            if m is None:
                return
            prom = st.prom = (m.ingest_batches.labels(source),
                              m.ingest_batch_events,
                              m.ingest_ooo_events.labels(source),
                              m.ingest_tombstones.labels(source),
                              m.freshness_queryable.labels(source))
        prom[0].inc()   # mirror outside the lock, cached children
        prom[1].observe(n)
        if ooo_n:
            prom[2].inc(ooo_n * mix_scale)
        if kind_counts is not None:
            tomb = int(sum(kind_counts[i] for i in _TOMBSTONE_KINDS
                           if i < len(kind_counts)))
            if tomb:
                # sampled batches scale up for an unbiased total
                # estimate (documented on the metric's /freshz twin,
                # whose raw sampled counts stay exact)
                prom[3].inc(tomb * mix_scale)

    def note_safe(self, safe_time: int, now: float | None = None) -> None:
        """The global safe-time fence moved to ``safe_time``
        (``WatermarkRegistry`` calls this OUTSIDE its own lock): every
        pending batch whose max event time the fence now covers became
        queryable — observe its arrival→now latency with its trace
        exemplar. Never raises into the watermark path."""
        if not enabled():
            return
        try:
            self._note_safe(safe_time, now)
        except Exception:   # telemetry never takes the fence down
            pass

    def _note_safe(self, safe_time, now) -> None:
        now = time.time() if now is None else float(now)
        safe_time = int(safe_time)
        # the fence sentinels (±2^62: all-done / idle-registered) are
        # not times — report null rather than garbage. The drain below
        # still runs: the positive sentinel drains EVERYTHING, the
        # negative one naturally drains nothing. Down-moves and the
        # rare out-of-order delivery of two concurrent advances are
        # stored as-is: the drain is idempotent (a lower fence drains
        # batches a newer call already popped — a no-op), and a
        # transiently-low reported last_safe self-corrects on the next
        # advance, whereas refusing non-monotone values froze the
        # plane after any legitimate fence down-move (a new live
        # source joining lowers the min).
        observed: list[tuple[_SourceStats, float]] = []
        with self._lock:
            _san_note(self._san_tracker, True)
            self.last_safe = (safe_time if abs(safe_time) < 2**62
                              else None)
            self.last_safe_wall = now
            for st in self._sources.values():
                if not st.pending:
                    continue
                # records carry each batch's OWN max, so a disordered
                # source's deque is not max_t-monotone — scan it, not
                # just the head (a late low-max batch must not wait
                # behind an earlier high-max one). The deque stays
                # arrival-ordered and small: every fence advance
                # drains, and a stalled fence generates no calls.
                kept: deque = deque()
                for bm, arrival, tid in st.pending:
                    if bm <= safe_time:
                        lat = max(0.0, now - arrival)
                        st.queryable.observe(lat, tid, now)
                        observed.append((st, lat))
                    else:
                        kept.append((bm, arrival, tid))
                if len(kept) != len(st.pending):
                    st.pending = kept
        for st, lat in observed:   # cached children, outside the lock
            if st.prom is not None:
                st.prom[4].observe(lat)
            if _journal.enabled():
                _journal.emit("fresh", {
                    "source": st.name,
                    "queryable_latency_s": round(lat, 6),
                    "safe_time": self.last_safe})

    def note_route(self, owner_counts: dict,
                   pending_events: int = 0) -> None:
        """Router-stage telemetry (ingestion/router.ShardRouter): events
        routed per shard this batch + the dead-letter (down-shard) queue
        depth. Never raises into the routing path."""
        if not enabled():
            return
        try:
            with self._lock:
                _san_note(self._san_tracker, True)
                for sid, n in owner_counts.items():
                    if len(self._routed) < 4096 \
                            or int(sid) in self._routed:
                        self._routed[int(sid)] = \
                            self._routed.get(int(sid), 0) + int(n)
                self._route_pending = int(pending_events)
        except Exception:   # telemetry never takes routing down
            pass

    # ---- live-query staleness ----

    def note_live_result(self, algorithm: str, result_time: int,
                         head_time: int | None = None,
                         trace_id: str | None = None,
                         now: float | None = None) -> float | None:
        """One Live job run emitted a result computed at event time
        ``result_time``: record its staleness — how long ago the data it
        reflects stopped being the ingest head — into the per-algorithm
        histogram, and return it (None when the result can't be dated)
        so the epoch engine can feed its per-subscription table and
        cadence without re-walking the head ring. ``head_time`` (the
        caller's ``graph.latest_time``) backs up the head clock for
        graphs ingested outside the pipeline; a result we cannot date is
        counted, never guessed. Never raises into the live-job loop."""
        if not enabled():
            return None
        try:
            return self._note_live_result(algorithm, result_time,
                                          head_time, trace_id, now)
        except Exception:   # telemetry never fails a live job
            return None

    def _note_live_result(self, algorithm, result_time, head_time,
                          trace_id, now) -> float | None:
        now = time.time() if now is None else float(now)
        result_time = int(result_time)
        staleness: float | None = None
        with self._lock:
            _san_note(self._san_tracker, True)
            head = self._head[-1][0] if self._head else head_time
            if head is None:
                self.undated_results += 1
                return None
            if result_time >= int(head):
                staleness = 0.0    # the result reflects the whole head
            else:
                # EARLIEST head-clock entry past the result's watermark
                # = the wall time the result became stale. Reverse walk
                # (the ring is event-time monotone): live results sit
                # near the head, so this terminates in a few steps and
                # never materializes the ring as a list under the lock
                wall = None
                for ev_t, w in reversed(self._head):
                    if ev_t <= result_time:
                        break
                    wall = w
                if wall is None:   # ring empty (head_time backstop only)
                    self.undated_results += 1
                    return None
                staleness = max(0.0, now - wall)
            alg = str(algorithm)
            h = self._staleness.get(alg)
            if h is None:
                if len(self._staleness) >= MAX_ALGOS:
                    self.dropped_algos += 1
                    return staleness
                h = self._staleness[alg] = _Hist(DEFAULT_SECONDS_BUCKETS)
            h.observe(staleness, trace_id, now)
        m = _metrics()
        if m is not None:
            m.freshness_staleness.labels(str(algorithm)).observe(staleness)
        return staleness

    def note_live_epoch(self, key: str, *, algorithm: str, mode: str,
                        delta_rows: int = 0, ship_bytes: int = 0,
                        staleness_s: float | None = None,
                        result_time: int | None = None,
                        now: float | None = None) -> None:
        """One epoch of a live subscription was served: update the
        bounded per-subscription table /statusz + /freshz surface.
        ``key`` identifies the subscription (job id), ``mode`` is the
        epoch mode (incremental|rebase|resweep|skipped|resync).
        Never raises into the live-job loop."""
        if not enabled():
            return
        try:
            now = time.time() if now is None else float(now)
            with self._lock:
                _san_note(self._san_tracker, True)
                row = self._live_subs.get(key)
                if row is None:
                    if len(self._live_subs) >= MAX_LIVE_SUBS:
                        self.dropped_live_subs += 1
                        return
                    row = self._live_subs[key] = {
                        "algorithm": str(algorithm), "epochs": 0,
                        "incremental": 0, "fallback": 0,
                        "modes": {},
                        "last_delta_rows": 0, "last_ship_bytes": 0,
                        "last_staleness_seconds": None,
                        "last_result_time": None, "last_wall": 0.0,
                        "recent": deque(maxlen=MAX_LIVE_RECENT),
                    }
                row["epochs"] += 1
                row["modes"][str(mode)] = row["modes"].get(str(mode), 0) + 1
                if mode in ("incremental", "resync"):
                    row["incremental"] += 1
                elif mode in ("resweep", "rebase"):
                    row["fallback"] += 1
                row["last_delta_rows"] = int(delta_rows)
                row["last_ship_bytes"] = int(ship_bytes)
                if staleness_s is not None:
                    row["last_staleness_seconds"] = round(
                        float(staleness_s), 4)
                if result_time is not None:
                    row["last_result_time"] = int(result_time)
                row["last_wall"] = now
                # bounded per-epoch ring: lets /freshz (and the
                # live_stream bench's median-staleness / ship-bytes
                # verification) see the epoch SERIES, not just the last
                row["recent"].append({
                    "mode": str(mode),
                    "delta_rows": int(delta_rows),
                    "ship_bytes": int(ship_bytes),
                    "staleness_seconds": (None if staleness_s is None
                                          else round(float(staleness_s),
                                                     4)),
                })
        except Exception:   # telemetry never fails a live job
            pass

    def live_subscription_rows(self) -> dict:
        """Snapshot of the per-subscription epoch table (exported on
        /statusz + /freshz; jobs/manager embeds it in failure-artifact
        dumps)."""
        with self._lock:
            return {k: dict(v, modes=dict(v["modes"]),
                            recent=[dict(r) for r in v["recent"]])
                    for k, v in self._live_subs.items()}

    def live_grade(self, algorithm: str) -> str:
        """Most recent staleness-budget grade for ``algorithm`` (as
        written by ``budget_evaluate``; "ok" when the algorithm has no
        target). The epoch engine's cadence reads this — a burning
        budget shortens the inter-epoch wait to the floor."""
        self.budget_evaluate()   # refresh (cached for EVAL_CACHE_S)
        with self._lock:
            return self._last_grades.get(str(algorithm), "ok")

    # ---- readers (series-ring collectors, surfaces) ----

    def total_events(self) -> float:
        with self._lock:
            return float(sum(s.events for s in self._sources.values()))

    def backlog_events(self) -> float:
        """Staged parse→append backlog summed over attached pipelines."""
        with self._lock:
            pipes = [r() for r in self._pipes]
        return float(sum(p.backlog() for p in pipes if p is not None))

    def queue_max_events(self) -> int:
        with self._lock:
            pipes = [r() for r in self._pipes]
        return max((int(p.queue_max_events) for p in pipes
                    if p is not None), default=0)

    def staged_queues(self) -> list[dict]:
        """Per-pipeline (backlog, bound) rows for the STAGED pipelines —
        saturation is a per-queue property (the ``ingest-backlog``
        advisor rule judges the worst queue, not a sum-vs-max mix)."""
        with self._lock:
            pipes = [r() for r in self._pipes]
        return [{"backlog_events": int(p.backlog()),
                 "queue_max_events": int(p.queue_max_events)}
                for p in pipes
                if p is not None and p.queue_max_events > 0]

    def pending_batches(self) -> int:
        """Not-yet-queryable batch count (the prometheus gauge's read)."""
        with self._lock:
            return sum(len(s.pending) for s in self._sources.values())

    def queryable_lag_seconds(self, now: float | None = None) -> float:
        """Age of the OLDEST not-yet-queryable batch — the live
        ingest-to-queryable lag signal the series ring samples (0 when
        everything appended is already behind the fence)."""
        now = time.time() if now is None else float(now)
        with self._lock:
            oldest = min((st.pending[0][1]
                          for st in self._sources.values() if st.pending),
                         default=None)
        return 0.0 if oldest is None else max(0.0, now - oldest)

    def staleness_totals_below(self, algorithm: str,
                               threshold_s: float) -> tuple[int, int]:
        """``(total, good)`` staleness observations for ``algorithm``
        where *good* means buckets ≤ ``threshold_s`` — the freshness
        error-budget numerator (same conservative rule as
        ``slo.totals_below``; case-insensitive, targets are
        operator-typed)."""
        alg = str(algorithm).lower()
        total = good = 0
        with self._lock:
            for a, h in self._staleness.items():
                if a.lower() != alg:
                    continue
                total += h.count
                for i, bound in enumerate(h.bounds):
                    if bound <= threshold_s:
                        good += h.counts[i]
        return total, good

    # ---- the RTPU_FRESH_TARGET staleness budget ----

    def _ensure_collectors(self, targets: list) -> None:
        """Register per-target cumulative (observations, breaches)
        collectors into the /slz series ring — ``fresh_obs_<alg>_total``
        / ``fresh_bad_<alg>_total``, the windowed-burn inputs. Retired
        on retarget exactly like obs/budget (changed thresholds
        re-register: the closures capture them). ONLY the process
        singleton registers: the closures capture ``self``, so a
        throwaway registry (tests, tooling) would otherwise be pinned
        alive by the process-global ring and clobber the singleton's
        collectors; non-singleton registries keep the cumulative-burn
        fallback instead."""
        from .slo import SERIES

        if globals().get("FRESH") is not self:
            return
        current = {t.algorithm for t in targets}
        fresh, stale = [], []
        with self._lock:
            _san_note(self._san_tracker, True)
            for t in targets:
                if self._registered.get(t.algorithm) != t.threshold_s:
                    self._registered[t.algorithm] = t.threshold_s
                    fresh.append(t)
            for alg in set(self._registered) - current:
                del self._registered[alg]
                self._last_grades.pop(alg, None)
                stale.append(alg)
        for t in fresh:
            alg, thr = t.algorithm, t.threshold_s

            def _obs(alg=alg, thr=thr):
                return float(self.staleness_totals_below(alg, thr)[0])

            def _bad(alg=alg, thr=thr):
                total, good = self.staleness_totals_below(alg, thr)
                return float(total - good)

            SERIES.register(f"fresh_obs_{alg}_total", _obs)
            SERIES.register(f"fresh_bad_{alg}_total", _bad)
        for alg in stale:
            SERIES.unregister(f"fresh_obs_{alg}_total")
            SERIES.unregister(f"fresh_bad_{alg}_total")
            m = _metrics()
            if m is not None:
                for window in ("fast", "slow"):
                    try:
                        m.freshness_burn_rate.remove(alg, window)
                    except Exception:
                        pass

    def budget_evaluate(self, now: float | None = None,
                        rows: list | None = None) -> dict:
        """The staleness-budget judgment: per-target cumulative +
        fast/slow windowed burns over the series ring, graded
        ok|degraded|burning — ``RTPU_FRESH_TARGET`` through the
        obs/budget machinery (same parser, same ``window_burn``, same
        dead-ring fallback to the cumulative burn). Live evaluations are
        cached for ``EVAL_CACHE_S`` keyed on the knob env."""
        from . import budget as _budget
        from .slo import SERIES

        live = now is None and rows is None
        env_key = (os.environ.get("RTPU_FRESH_TARGET"),
                   os.environ.get("RTPU_BUDGET_FAST_S"),
                   os.environ.get("RTPU_BUDGET_SLOW_S"))
        if live:
            with self._lock:
                cached = self._eval_cache
            if cached is not None and cached[0] == env_key and \
                    time.monotonic() - cached[1] < EVAL_CACHE_S:
                return cached[2]
        targets, errors = _budget.parse_targets(
            os.environ.get("RTPU_FRESH_TARGET", ""))
        self._ensure_collectors(targets)
        if rows is None:
            rows = SERIES.rows()
        if now is None:
            now = time.time()
        fast_s = _budget.fast_window_s()
        slow_s = _budget.slow_window_s()
        out_targets = []
        transitions = []
        grade = "ok"
        m = _metrics()
        for t in targets:
            # the SHARED grading core (obs/budget.judge_target): burn
            # math and the 2-of-2 grade ladder can never diverge
            # between the latency and staleness planes
            row, t_grade, eff_fast, eff_slow = _budget.judge_target(
                t, rows, now, fast_s, slow_s,
                self.staleness_totals_below, prefix="fresh")
            if _GRADE_ORDER[t_grade] > _GRADE_ORDER[grade]:
                grade = t_grade
            out_targets.append(row)
            if m is not None:
                m.freshness_burn_rate.labels(t.algorithm,
                                             "fast").set(eff_fast)
                m.freshness_burn_rate.labels(t.algorithm,
                                             "slow").set(eff_slow)
            with self._lock:
                prev = self._last_grades.get(t.algorithm, "ok")
                self._last_grades[t.algorithm] = t_grade
            if _GRADE_ORDER[t_grade] > _GRADE_ORDER[prev]:
                transitions.append((t.algorithm, prev, t_grade, row))
        for alg, prev, cur, row in transitions:   # instants outside locks
            TRACER.instant("freshness.burn", algorithm=alg, grade=cur,
                           previous=prev, fast_burn=row["fast_burn"],
                           slow_burn=row["slow_burn"],
                           cumulative_burn=row["cumulative_burn"])
        result = {"targets": out_targets, "errors": errors,
                  "grade": grade,
                  "windows_seconds": {"fast": fast_s, "slow": slow_s}}
        if live:
            with self._lock:
                self._eval_cache = (env_key, time.monotonic(), result)
        return result

    # ---- export ----

    def status_block(self) -> dict:
        """The compact ``freshness`` block /statusz embeds (what
        /clusterz federates — per-source tables stay on /freshz)."""
        now = time.time()
        with self._lock:
            _san_note(self._san_tracker, False)
            ups = sum(s.updates_per_s(now) for s in self._sources.values())
            n_sources = len(self._sources)
            pending = sum(len(s.pending) for s in self._sources.values())
            stale_p99 = {a: h.quantile(0.99)
                         for a, h in self._staleness.items()}
            last_safe = self.last_safe
            # compact block: the per-epoch ``recent`` ring stays on
            # /freshz (this block is federated via /clusterz)
            live_subs = {k: {f: (dict(val) if f == "modes" else val)
                             for f, val in v.items() if f != "recent"}
                         for k, v in self._live_subs.items()}
        bud = self.budget_evaluate()
        return {
            "enabled": enabled(),
            "sources": n_sources,
            "updates_per_s": round(ups, 1),
            "backlog_events": int(self.backlog_events()),
            "pending_batches": pending,
            "queryable_lag_seconds": round(
                self.queryable_lag_seconds(now), 3),
            "last_safe_time": last_safe,
            "staleness_p99_seconds": {a: round(v, 4)
                                      for a, v in stale_p99.items()},
            "live_subscriptions": live_subs,
            "grade": bud["grade"],
        }

    def freshz(self) -> dict:
        """The full ``/freshz`` document: per-source tables, staleness
        histograms + exemplars, the head clock's span, the router-stage
        table, and the staleness-budget judgment."""
        now = time.time()
        with self._lock:
            _san_note(self._san_tracker, False)
            sources = {name: st.as_dict(now, self._ooo_bounds)
                       for name, st in sorted(self._sources.items())}
            staleness = {a: h.as_dict()
                         for a, h in sorted(self._staleness.items())}
            head = {
                "entries": len(self._head),
                "event_time": self._head[-1][0] if self._head else None,
                "oldest_event_time": (self._head[0][0] if self._head
                                      else None),
            }
            router = {"routed_events_by_shard": dict(self._routed),
                      "dead_letter_events": self._route_pending}
            meta = {"dropped_sources": self.dropped_sources,
                    "dropped_algorithms": self.dropped_algos,
                    "dropped_live_subscriptions": self.dropped_live_subs,
                    "undated_results": self.undated_results,
                    "last_safe_time": self.last_safe}
            live_subs = {k: dict(v, modes=dict(v["modes"]),
                                 recent=[dict(r) for r in v["recent"]])
                         for k, v in self._live_subs.items()}
        return {
            "enabled": enabled(),
            "sources": sources,
            "staleness_seconds": staleness,
            "head": head,
            "router": router,
            "backlog_events": int(self.backlog_events()),
            "queue_max_events": self.queue_max_events(),
            "staged_queues": self.staged_queues(),
            "queryable_lag_seconds": round(
                self.queryable_lag_seconds(now), 3),
            "live_subscriptions": live_subs,
            "budget": self.budget_evaluate(),
            **meta,
        }

    def advisor_signals(self) -> dict:
        """The compact signals dict the advisor rules read
        (obs/advisor.py ``ingest-backlog`` / ``out-of-order-excess`` /
        ``freshness-burn``)."""
        now = time.time()
        with self._lock:
            _san_note(self._san_tracker, False)
            sources = {name: {
                "events": st.events,
                "disorder_bound": st.disorder,
                "ooo_events": st.ooo_events,
                "ooo_max": st.ooo_max,
                "updates_per_s": round(st.updates_per_s(now), 1),
                "pending_batches": len(st.pending),
            } for name, st in self._sources.items()}
            stale_p99 = {a: round(h.quantile(0.99), 4)
                         for a, h in self._staleness.items()}
        return {
            "sources": sources,
            "backlog_events": int(self.backlog_events()),
            "queue_max_events": self.queue_max_events(),
            "staged_queues": self.staged_queues(),
            "queryable_lag_seconds": round(
                self.queryable_lag_seconds(now), 3),
            "staleness_p99_seconds": stale_p99,
            "budget": self.budget_evaluate(),
        }

    def clear(self) -> None:
        with self._lock:
            registered = list(self._registered)
            self._sources.clear()
            self._head.clear()
            self._staleness.clear()
            self._live_subs.clear()
            self.dropped_live_subs = 0
            self._routed.clear()
            self._route_pending = 0
            self._pipes = []
            self.dropped_sources = 0
            self.dropped_algos = 0
            self.undated_results = 0
            self.last_safe = None
            self._registered.clear()
            self._last_grades.clear()
            self._eval_cache = None
            self._pending_cap = pending_cap()
            self._ooo_bounds = ooo_bounds()
        from .slo import SERIES

        for alg in registered:
            SERIES.unregister(f"fresh_obs_{alg}_total")
            SERIES.unregister(f"fresh_bad_{alg}_total")


#: the process singleton the pipeline, watermark registry, jobs layer
#: and REST surfaces all feed/read
FRESH = FreshnessRegistry()


def note_live_result(algorithm, result_time, head_time=None,
                     trace_id=None, now=None) -> float | None:
    """Module-level convenience for the jobs layer."""
    return FRESH.note_live_result(algorithm, result_time,
                                  head_time=head_time,
                                  trace_id=trace_id, now=now)


def freshz() -> dict:
    return FRESH.freshz()


_fresh_dump = os.environ.get("RTPU_FRESH_DUMP")
if _fresh_dump:
    from . import exitdump as _exitdump

    def _dump_freshz(path=_fresh_dump):
        with open(path, "w") as f:
            json.dump(freshz(), f, default=str)

    _exitdump.register("fresh", _dump_freshz)
