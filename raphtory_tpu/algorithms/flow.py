"""FlowGraph — temporal flux aggregation per vertex.

Re-design of ``core/analysis/Algorithms/FlowGraph.scala`` (location co-visit
flows in the track-and-trace example): for a graph whose edges carry a
numeric ``flow`` property (visit counts, transferred value, …), compute each
vertex's windowed in-flux, out-flux and net flux, plus the top flow
corridors (heaviest edges). Zero supersteps — flux is two segment-sums, done
in the reducer over the exact windowed edge set (no message loop to run).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..engine.program import Context, VertexProgram


@dataclass(frozen=True)
class FlowGraph(VertexProgram):
    needs_vids = False
    needs_vertex_times = False
    needs_edge_times = False
    flow_prop: str = "flow"
    default_flow: float = 1.0
    max_steps: int = 0

    def init(self, ctx: Context):
        return {}

    def finalize(self, state, ctx: Context):
        return {"in_deg": ctx.in_deg, "out_deg": ctx.out_deg}

    def reduce(self, result, view, window=None):
        if window is None:
            emask = np.asarray(view.e_mask)
            vmask = np.asarray(view.v_mask)
        else:
            vm, em = view.window_masks([window])
            vmask, emask = vm[0], em[0]
        w = view.edge_prop(self.flow_prop)
        w = np.where(np.isnan(w), self.default_flow, w)
        influx = np.zeros(view.n_pad)
        outflux = np.zeros(view.n_pad)
        np.add.at(influx, view.e_dst[emask], w[emask])
        np.add.at(outflux, view.e_src[emask], w[emask])
        net = influx - outflux
        score = np.where(vmask, np.abs(net), -np.inf)
        order = np.argsort(-score, kind="stable")
        top = [
            {
                "id": int(view.vids[i]),
                "influx": float(influx[i]),
                "outflux": float(outflux[i]),
                "net": float(net[i]),
            }
            for i in order[:10]
            if vmask[i]
        ]
        wm = np.where(emask, w, -np.inf)
        heavy = np.argsort(-wm, kind="stable")[:10]
        corridors = [
            {
                "src": int(view.vids[view.e_src[j]]),
                "dst": int(view.vids[view.e_dst[j]]),
                "flow": float(w[j]),
            }
            for j in heavy
            if emask[j]
        ]
        return {
            "total_flow": float(w[emask].sum()),
            "top_vertices": top,
            "top_corridors": corridors,
        }
