"""Ranking analysers: DegreeRanking, Density, and a PageRank ranking.

Parity targets: ``DegreeRanking`` / ``DegreeBasic`` top-k output
(``core/analysis/Algorithms/DegreeRanking.scala``), the random example's
``Density`` analyser, and ``EthereumDegreeRanking``. Rankings are reducers
over zero-or-few-superstep device results.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..engine.program import Context, VertexProgram


@dataclass(frozen=True)
class DegreeRanking(VertexProgram):
    needs_vids = False
    needs_vertex_times = False
    needs_edge_times = False
    top_k: int = 10
    by: str = "total"   # 'in' | 'out' | 'total'
    max_steps: int = 0

    def init(self, ctx: Context):
        return {}

    def finalize(self, state, ctx: Context):
        return {"in": ctx.in_deg, "out": ctx.out_deg}

    def reduce(self, result, view, window=None):
        ind = np.asarray(result["in"])
        outd = np.asarray(result["out"])
        if window is None:
            mask = np.asarray(view.v_mask)
        else:
            mask = view.window_masks([window])[0][0]
        score = {"in": ind, "out": outd, "total": ind + outd}[self.by]
        score = np.where(mask, score, -1)
        order = np.argsort(-score, kind="stable")[: self.top_k]
        return {
            "ranking": [
                {"id": int(view.vids[i]), "in": int(ind[i]), "out": int(outd[i])}
                for i in order
                if mask[i]
            ]
        }


@dataclass(frozen=True)
class StarNode(VertexProgram):
    """The vertex with maximum in-degree in the (windowed) view — parity with
    the random example's ``StarNode`` analyser
    (``examples/random/depricated/StarNode.scala``)."""

    needs_vids = False
    needs_vertex_times = False
    needs_edge_times = False
    max_steps: int = 0

    def init(self, ctx: Context):
        return {}

    def finalize(self, state, ctx: Context):
        return {"in": ctx.in_deg}

    def reduce(self, result, view, window=None):
        ind = np.asarray(result["in"])
        if window is None:
            mask = np.asarray(view.v_mask)
        else:
            mask = view.window_masks([window])[0][0]
        score = np.where(mask, ind, -1)
        if not mask.any():
            return {"star": None, "inDegree": 0}
        i = int(np.argmax(score))
        return {"star": int(view.vids[i]), "inDegree": int(ind[i])}


@dataclass(frozen=True)
class Density(VertexProgram):
    """|E| / (|V| * (|V|-1)) on the (windowed) view."""

    needs_vids = False
    needs_vertex_times = False
    needs_edge_times = False
    max_steps: int = 0

    def init(self, ctx: Context):
        return {}

    def finalize(self, state, ctx: Context):
        return {"out": ctx.out_deg}

    def reduce(self, result, view, window=None):
        if window is None:
            vmask = np.asarray(view.v_mask)
            emask = np.asarray(view.e_mask)
        else:
            vm, em = view.window_masks([window])
            vmask, emask = vm[0], em[0]
        n = int(vmask.sum())
        m = int(emask.sum())
        return {
            "vertices": n,
            "edges": m,
            "density": (m / (n * (n - 1))) if n > 1 else 0.0,
        }
