"""Label propagation (community detection) — the generic-inbox algorithm.

Synchronous LPA (Raghavan et al.): every vertex starts in its own community
and repeatedly adopts the MOST FREQUENT label among its in-neighbours (ties
break to the smallest label; a vertex with no in-neighbours keeps its label),
halting when no label changes. The per-vertex label histogram is exactly the
inbox-style aggregation the reference's arbitrary typed vertex messages allow
(``VertexVisitor.scala:99-161``) and an elementwise sum/min/max combiner
cannot express — here it rides the sort-based ``segment_mode`` routing path
through ``combiner='custom'``.

Labels are GLOBAL PADDED vertex indices (i32), mesh-consistent like
ConnectedComponents'.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..engine.program import Context, Edges, VertexProgram
from ..ops.segment import segment_mode

_I32_MAX = np.int32(np.iinfo(np.int32).max)


@dataclass(frozen=True)
class LabelPropagation(VertexProgram):
    max_steps: int = 30
    combiner = "custom"
    direction = "out"            # labels flow src -> dst; histogram at dst
    needs_vids = False
    needs_vertex_times = False
    needs_edge_times = False

    def init(self, ctx: Context):
        return jnp.where(ctx.v_mask, ctx.global_index(), _I32_MAX)

    def message(self, src_state, edge: Edges):
        return src_state

    def exchange(self, payload, seg_ids, num_segments, mask):
        # mode of the inbox per destination; -1 marks "no messages"
        return segment_mode(payload, seg_ids, num_segments, mask, default=-1)

    def update(self, state, agg, ctx: Context):
        new = jnp.where((agg >= 0) & ctx.v_mask, agg, state)
        new = jnp.where(ctx.v_mask, new, _I32_MAX)
        return new, new == state

    def reduce(self, result, view, window=None):
        """Community stats (same shape as ConnectedComponents.reduce)."""
        labels = np.asarray(result)
        if window is None:
            mask = np.asarray(view.v_mask)
        else:
            mask = view.window_masks([window])[0][0]
        lab = labels[mask]
        if len(lab) == 0:
            return {"vertices": 0, "communities": 0, "biggest": 0, "top5": []}
        uniq, counts = np.unique(lab, return_counts=True)
        counts.sort()
        return {
            "vertices": int(len(lab)),
            "communities": int(len(uniq)),
            "biggest": int(counts[-1]),
            "top5": counts[::-1][:5].tolist(),
        }
