"""Built-in algorithm library — parity with the reference's
``core/analysis/Algorithms/`` plus the example-space analysers (SURVEY §2.8)."""

from .connected_components import ConnectedComponents
from .degree import DegreeBasic
from .pagerank import PageRank

__all__ = ["ConnectedComponents", "DegreeBasic", "PageRank"]
