"""Built-in algorithm library — parity with the reference's
``core/analysis/Algorithms/`` plus the example-space analysers (SURVEY §2.8):
ConnectedComponents, DegreeBasic/DegreeRanking, PageRank, BinaryDiffusion,
FlowGraph, Density, temporal TaintTracking (EthereumTaintTracking),
BFS/SSSP (LDBC bar)."""

from .connected_components import ConnectedComponents
from .degree import DegreeBasic
from .diffusion import BinaryDiffusion
from .flow import FlowGraph
from .lpa import LabelPropagation
from .pagerank import PageRank
from .rankings import DegreeRanking, Density, StarNode
from .taint import TaintTracking
from .traversal import BFS, SSSP

__all__ = [
    "ConnectedComponents",
    "DegreeBasic",
    "DegreeRanking",
    "Density",
    "StarNode",
    "BinaryDiffusion",
    "FlowGraph",
    "LabelPropagation",
    "PageRank",
    "TaintTracking",
    "BFS",
    "SSSP",
]
