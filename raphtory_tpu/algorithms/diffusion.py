"""BinaryDiffusion — randomised infection spread.

Parity with ``core/analysis/Algorithms/BinaryDefusion.scala`` (sic): a random
seed vertex is infected; each superstep every infected vertex infects a
random subset of its out-neighbours; runs until quiescence. Randomness is
counter-based (``jax.random.fold_in`` of seed, superstep and edge index) so
the program stays a pure function — reruns reproduce exactly.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.program import Context, Edges, VertexProgram


@dataclass(frozen=True)
class BinaryDiffusion(VertexProgram):
    seeds: tuple = ()          # empty -> vertex with min global id
    seed: int = 42             # PRNG stream
    spread_prob: float = 0.5
    max_steps: int = 50
    combiner = "max"
    direction = "out"

    def init(self, ctx: Context):
        if self.seeds:
            ids = jnp.asarray(self.seeds, ctx.vids.dtype)
            infected = (ctx.vids[:, None] == ids[None, :]).any(axis=1)
        else:
            masked = jnp.where(ctx.v_mask, ctx.vids, jnp.iinfo(jnp.int64).max)
            global_min = jnp.min(masked)
            if ctx.axis_name is not None:
                global_min = jax.lax.pmin(global_min, ctx.axis_name)
            infected = ctx.vids == global_min
        return (infected & ctx.v_mask).astype(jnp.int32)

    def message(self, src_state, edge: Edges):
        m = edge.src.shape[0]
        key = jax.random.fold_in(jax.random.PRNGKey(self.seed), edge.step)
        coin = jax.random.uniform(key, (m,)) < self.spread_prob
        return jnp.where(coin, src_state, 0)

    def update(self, state, agg, ctx: Context):
        new = jnp.maximum(state, agg)
        new = jnp.where(ctx.v_mask, new, 0)
        return new, new == state

    def finalize(self, state, ctx: Context):
        return state

    def reduce(self, result, view, window=None):
        inf = np.asarray(result)
        mask = np.asarray(view.v_mask)
        return {
            "infected": int(inf[mask].sum()),
            "fraction": float(inf[mask].sum() / max(mask.sum(), 1)),
        }
