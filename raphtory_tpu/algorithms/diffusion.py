"""BinaryDiffusion — randomised infection spread.

Parity with ``core/analysis/Algorithms/BinaryDefusion.scala`` (sic): a random
seed vertex is infected; each superstep every infected vertex infects a
random subset of its out-neighbours; runs until quiescence. Randomness is
counter-based (an integer hash of seed, superstep and edge endpoints) so the
program stays a pure function — reruns reproduce exactly, independent of how
the engine lays out the window batch.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.program import Context, Edges, VertexProgram


@dataclass(frozen=True)
class BinaryDiffusion(VertexProgram):
    seeds: tuple = ()          # empty -> vertex with min global id
    seed: int = 42             # PRNG stream
    spread_prob: float = 0.5
    max_steps: int = 50
    combiner = "max"
    direction = "out"
    needs_vertex_times = False
    needs_edge_times = False

    def init(self, ctx: Context):
        if self.seeds:
            ids = jnp.asarray(self.seeds, ctx.vids.dtype)
            infected = (ctx.vids[:, None] == ids[None, :]).any(axis=1)
        else:
            masked = jnp.where(ctx.v_mask, ctx.vids, jnp.iinfo(jnp.int64).max)
            global_min = jnp.min(masked)
            if ctx.axis_name is not None:
                global_min = jax.lax.pmin(global_min, ctx.axis_name)
            infected = ctx.vids == global_min
        return (infected & ctx.v_mask).astype(jnp.int32)

    def message(self, src_state, edge: Edges):
        # Counter-based coin per (edge endpoints, superstep, seed): a pure
        # integer hash, NOT jax.random over the array shape — the engine may
        # lay the window batch out flat (k*m), and position-based draws
        # would then give each window different coins (batched runs would
        # diverge from single-window runs). Hashing the edge's endpoints
        # keeps draws identical across layouts and duplicate windows.
        h = (edge.src.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
             ^ edge.dst.astype(jnp.uint32) * jnp.uint32(0x85EBCA77)
             ^ (edge.step.astype(jnp.uint32) + jnp.uint32(self.seed))
             * jnp.uint32(0xC2B2AE3D))
        h = h ^ (h >> 15)
        h = h * jnp.uint32(0x2C1B3C6D)
        h = h ^ (h >> 12)
        h = h * jnp.uint32(0x297A2D39)
        h = h ^ (h >> 15)
        coin = (h.astype(jnp.float32) / jnp.float32(2**32)) < self.spread_prob
        return jnp.where(coin, src_state, 0)

    def update(self, state, agg, ctx: Context):
        new = jnp.maximum(state, agg)
        new = jnp.where(ctx.v_mask, new, 0)
        return new, new == state

    def finalize(self, state, ctx: Context):
        return state

    def reduce(self, result, view, window=None):
        inf = np.asarray(result)
        mask = np.asarray(view.v_mask)
        return {
            "infected": int(inf[mask].sum()),
            "fraction": float(inf[mask].sum() / max(mask.sum(), 1)),
        }
