"""Connected components via min-label propagation.

Capability parity with the reference's flagship algorithm
(``core/analysis/Algorithms/ConnectedComponents.scala:10-42``): every vertex
starts labelled with its own id, repeatedly adopts the min label over its
neighbourhood (both directions), votes to halt when unchanged; the reducer
reports cluster count / biggest / islands / average like the reference's
``processResults`` (``ConnectedComponents.scala:44-122``).

TPU note: labels are GLOBAL PADDED vertex indices (i32) on device — small,
mesh-consistent, and never 64-bit external ids; ``view.vids[label]`` recovers
the external id of a component's representative when needed.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..engine.program import Context, Edges, VertexProgram

_I32_MAX = np.int32(np.iinfo(np.int32).max)


@dataclass(frozen=True)
class ConnectedComponents(VertexProgram):
    max_steps: int = 100
    combiner = "min"
    direction = "both"
    monotone_min = True        # min-label merge — sparse-route eligible
    reduce_shell_safe = True   # reducer reads vids/v_mask only
    needs_vids = False
    needs_vertex_times = False
    needs_edge_times = False

    def init(self, ctx: Context):
        return jnp.where(ctx.v_mask, ctx.global_index(), _I32_MAX)

    def message(self, src_state, edge: Edges):
        return src_state

    def update(self, state, agg, ctx: Context):
        new = jnp.minimum(state, agg)
        new = jnp.where(ctx.v_mask, new, _I32_MAX)
        return new, new == state

    def finalize(self, state, ctx: Context):
        return state

    def reduce(self, result, view, window=None):
        """Cluster stats in the reference's output shape
        (ConnectedComponents.scala:93-122): top-5 sizes, counts, islands."""
        labels = np.asarray(result)
        if window is None:
            mask = np.asarray(view.v_mask)
        else:
            mask = view.window_masks([window])[0][0]
        lab = labels[mask]
        if len(lab) == 0:
            return {
                "vertices": 0, "clusters": 0, "biggest": 0,
                "islands": 0, "proportion": 0.0, "top5": [],
            }
        uniq, counts = np.unique(lab, return_counts=True)
        counts.sort()
        top5 = counts[::-1][:5].tolist()
        return {
            "vertices": int(len(lab)),
            "clusters": int(len(uniq)),
            "biggest": int(counts[-1]),
            "islands": int((counts == 1).sum()),
            "proportion": float(counts[-1] / len(lab)),
            "top5": top5,
        }
