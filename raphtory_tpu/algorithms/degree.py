"""Degree statistics — DegreeBasic/InDegree/OutDegree parity.

Reference: ``core/analysis/Algorithms/DegreeBasic.scala`` (per-vertex
(in, out) pairs + totals/max in the reducer) and the random-example
``InDegree``/``OutDegree`` analysers. Zero supersteps: degrees are already a
segment-sum in the engine context.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..engine.program import Context, VertexProgram


@dataclass(frozen=True)
class DegreeBasic(VertexProgram):
    max_steps: int = 0
    reduce_shell_safe = True   # reducer reads vids/v_mask only
    needs_vids = False
    needs_vertex_times = False
    needs_edge_times = False

    def init(self, ctx: Context):
        return {}

    def finalize(self, state, ctx: Context):
        return {
            "in": jnp.where(ctx.v_mask, ctx.in_deg, 0),
            "out": jnp.where(ctx.v_mask, ctx.out_deg, 0),
        }

    def reduce(self, result, view, window=None):
        ind = np.asarray(result["in"])
        outd = np.asarray(result["out"])
        if window is None:
            mask = np.asarray(view.v_mask)
        else:
            mask = view.window_masks([window])[0][0]
        n = int(mask.sum())
        tot = ind + outd
        return {
            "vertices": n,
            "total_in": int(ind.sum()),
            "total_out": int(outd.sum()),
            "max_in": int(ind.max(initial=0)),
            "max_out": int(outd.max(initial=0)),
            "avg_degree": float(tot.sum() / max(n, 1)),
        }
