"""BFS / SSSP — min-plus traversal from seed vertices.

The LDBC-SNB capability bar (BASELINE.md configs: "BFS / SSSP Analyser over
sliding windows"). BFS is hop counting; SSSP weights edges with a numeric
property (default weight 1). Both are the same min-plus program.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..engine.program import Context, Edges, VertexProgram

FINF = np.float32(np.inf)


def _member(vids, ids: tuple):
    if not ids:
        return jnp.zeros(vids.shape, bool)
    ids_arr = jnp.asarray(ids, vids.dtype)
    return (vids[:, None] == ids_arr[None, :]).any(axis=1)


@dataclass(frozen=True)
class SSSP(VertexProgram):
    seeds: tuple = ()
    weight_prop: str | None = None   # None -> unit weights (= BFS hop count)
    directed: bool = True
    max_steps: int = 100
    top_k: int = 20                  # farthest reached vertices in the summary
    full_distances: bool = False     # opt-in: ship every reached distance
    combiner = "min"
    monotone_min = True        # min-plus relaxation — sparse-route eligible
    reduce_shell_safe = True   # reducer reads vids/v_mask only
    needs_vertex_times = False
    needs_edge_times = False

    @property
    def direction(self):  # type: ignore[override]
        return "out" if self.directed else "both"

    @property
    def edge_props(self):  # type: ignore[override]
        return (self.weight_prop,) if self.weight_prop else ()

    def init(self, ctx: Context):
        seeded = _member(ctx.vids, self.seeds) & ctx.v_mask
        return jnp.where(seeded, 0.0, FINF).astype(jnp.float32)

    def message(self, src_state, edge: Edges):
        if self.weight_prop:
            w = edge.props[self.weight_prop]
            w = jnp.where(jnp.isnan(w), 1.0, w).astype(jnp.float32)
        else:
            w = 1.0
        return src_state + w

    def update(self, state, agg, ctx: Context):
        new = jnp.minimum(state, agg)
        new = jnp.where(ctx.v_mask, new, FINF)
        return new, new == state

    def reduce(self, result, view, window=None):
        """Top-k + hop histogram summary (PageRank reducer discipline).

        A range sweep runs this once per hop; shipping every reached
        vertex's distance per hop balloons job results and REST payloads, so
        the default reports the k farthest vertices plus a distance
        histogram. Full per-vertex distances stay available behind
        ``full_distances=True``.
        """
        dist = np.asarray(result)
        reached = np.isfinite(dist) & np.asarray(view.v_mask)
        out = {
            "reached": int(reached.sum()),
            "max_distance": float(dist[reached].max()) if reached.any() else None,
        }
        idx = np.flatnonzero(reached)
        if len(idx):
            k = min(self.top_k, len(idx))
            part = idx[np.argpartition(dist[idx], len(idx) - k)[len(idx) - k:]]
            order = part[np.argsort(dist[part])[::-1]]
            out["top"] = [
                {"vertex": int(view.vids[i]), "distance": float(dist[i])}
                for i in order
            ]
            # integer-bucket histogram of reached distances (hops for BFS)
            buckets = np.floor(dist[idx]).astype(np.int64)
            uniq, counts = np.unique(buckets, return_counts=True)
            out["histogram"] = {int(u): int(c) for u, c in zip(uniq, counts)}
        else:
            out["top"] = []
            out["histogram"] = {}
        if self.full_distances:
            out["distances"] = {
                int(view.vids[i]): float(dist[i]) for i in idx
            }
        return out


def BFS(seeds: tuple = (), directed: bool = True, max_steps: int = 100,
        top_k: int = 20, full_distances: bool = False) -> SSSP:
    """Hop-count traversal (unit-weight SSSP)."""
    return SSSP(seeds=seeds, weight_prop=None, directed=directed,
                max_steps=max_steps, top_k=top_k,
                full_distances=full_distances)
