"""Temporal taint tracking — time-respecting infection propagation.

Capability parity with ``EthereumTaintTracking``
(``examples/blockchain/analysers/EthereumTaintTracking.scala:93-127``): a set
of seed accounts becomes tainted at a start time; taint flows along an edge
OCCURRENCE (individual transaction) only if the occurrence happens at or
after the moment its source became tainted — so propagation respects the
arrow of time through the multigraph of edge events, not the deduped
topology. ``TaintTrackExchangeStop`` variant: a stop-list of vertices that
absorb taint but never re-emit (exchanges).

State is the earliest taint time per vertex (i64, IMAX = clean); message
along occurrence e=(u→v, t): ``t if taint[u] <= t else IMAX``; combiner min.
Fixpoint ≤ diameter supersteps; each step is one masked segment-min.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..engine.program import Context, Edges, VertexProgram

IMAX = np.int64(np.iinfo(np.int64).max)


def _member(vids, ids: tuple):
    if not ids:
        return jnp.zeros(vids.shape, bool)
    ids_arr = jnp.asarray(ids, vids.dtype)
    return (vids[:, None] == ids_arr[None, :]).any(axis=1)


@dataclass(frozen=True)
class TaintTracking(VertexProgram):
    seeds: tuple = ()            # global vertex ids tainted at start_time
    start_time: int = 0
    stop_list: tuple = ()        # absorb but never re-emit (exchange stop)
    max_steps: int = 50
    value_prop: str | None = None  # per-occurrence value gate (see below)
    min_value: float = 0.0
    combiner = "min"
    direction = "out"
    needs_occurrences = True
    needs_vertex_times = False

    @property
    def edge_props(self):  # type: ignore[override]
        """Value-weighted taint: with ``value_prop`` set, an occurrence only
        carries taint when its OWN event property (e.g. the transferred
        amount) is >= ``min_value`` — dust transactions don't propagate."""
        return (self.value_prop,) if self.value_prop else ()

    def init(self, ctx: Context):
        tainted = _member(ctx.vids, self.seeds) & ctx.v_mask
        taint_t = jnp.where(tainted, jnp.int64(self.start_time), IMAX)
        stopped = _member(ctx.vids, self.stop_list)
        return {"taint": taint_t, "stopped": stopped}

    def message(self, src_state, edge: Edges):
        # edge.time is the occurrence (transaction) time; taint flows only
        # forward in time, and never OUT of a stop-listed vertex
        can_emit = (src_state["taint"] <= edge.time) & ~src_state["stopped"]
        if self.value_prop:
            val = edge.props[self.value_prop]
            can_emit &= ~jnp.isnan(val) & (val >= self.min_value)
        return jnp.where(can_emit, edge.time, IMAX)

    def update(self, state, agg, ctx: Context):
        new = jnp.minimum(state["taint"], agg)
        new = jnp.where(ctx.v_mask, new, IMAX)
        return {"taint": new, "stopped": state["stopped"]}, new == state["taint"]

    def finalize(self, state, ctx: Context):
        return state["taint"]

    def reduce(self, result, view, window=None):
        taint = np.asarray(result)
        hit = np.flatnonzero(taint < IMAX)
        rows = sorted(
            ((int(view.vids[i]), int(taint[i])) for i in hit),
            key=lambda r: (r[1], r[0]),
        )
        return {
            "tainted": len(rows),
            "infections": [{"id": vid, "taintedAt": t} for vid, t in rows],
        }
