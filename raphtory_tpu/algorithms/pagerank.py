"""PageRank as a pull-combine vertex program — the flagship benchmark kernel.

The reference ships a deprecated 10-step push PageRank
(``examples/random/depricated/PageRank.scala:21-45``). This is the proper
power-iteration formulation: each superstep every vertex pulls
``rank/out_deg`` along in-edges (sum combiner), applies damping with a
dangling-mass correction, and votes to halt when its rank moved less than
``tol``. f32 on device; windowed sweeps batch as a leading vmap axis.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..engine.program import Context, Edges, VertexProgram


@dataclass(frozen=True)
class PageRank(VertexProgram):
    damping: float = 0.85
    tol: float = 1e-6
    max_steps: int = 50
    combiner = "sum"
    direction = "out"   # payload flows src→dst, combined at dst = pull at dst
    reduce_shell_safe = True   # reducer reads vids/v_mask only
    needs_vids = False
    needs_vertex_times = False
    needs_edge_times = False

    def init(self, ctx: Context):
        n = jnp.maximum(ctx.num_vertices, 1.0)
        rank = jnp.where(ctx.v_mask, 1.0 / n, 0.0).astype(jnp.float32)
        return {"rank": rank, "out_deg": ctx.out_deg.astype(jnp.float32)}

    def message(self, src_state, edge: Edges):
        deg = jnp.maximum(src_state["out_deg"], 1.0)
        return src_state["rank"] / deg

    def update(self, state, agg, ctx: Context):
        n = jnp.maximum(ctx.num_vertices, 1.0)
        # dangling vertices redistribute their mass uniformly (global scalar —
        # a psum across shards when running on a mesh)
        dangling = ctx.global_sum(
            jnp.where(ctx.v_mask & (ctx.out_deg == 0), state["rank"], 0.0)
        )
        new = (1.0 - self.damping) / n + self.damping * (agg + dangling / n)
        new = jnp.where(ctx.v_mask, new, 0.0).astype(jnp.float32)
        votes = jnp.abs(new - state["rank"]) < self.tol
        return {"rank": new, "out_deg": state["out_deg"]}, votes

    def finalize(self, state, ctx: Context):
        return state["rank"]

    def reduce(self, result, view, window=None):
        import numpy as np

        ranks = np.asarray(result)
        order = np.argsort(ranks)[::-1][:10]
        return {
            "sum": float(ranks.sum()),
            "top10": [
                (int(view.vids[i]), float(ranks[i])) for i in order if ranks[i] > 0
            ],
        }
