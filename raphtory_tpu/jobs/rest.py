"""REST job API — wire-compatible surface with the reference.

``AnalysisRestApi.scala`` serves on :8081 (line 30): POST
``/LiveAnalysisRequest`` ``/ViewAnalysisRequest`` ``/RangeAnalysisRequest``
and GET ``/AnalysisResults?jobID=`` ``/KillTask?jobID=`` (lines 35-129).
Same five endpoints here on a stdlib ThreadingHTTPServer (no web-framework
dependency). Request bodies take the reference's field names
(analyserName, timestamp, start/end/jump, windowType, windowSize, windowSet,
repeatTime, rawFile) with `params` as an extension for hyperparameters.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import registry
from .manager import AnalysisManager, LiveQuery, RangeQuery, ViewQuery

DEFAULT_PORT = 8081


def _windows_from(body: dict):
    """windowType: 'none' | 'single' | 'batched' (the reference's 3-way task
    split per query type)."""
    wt = body.get("windowType", "none")
    if wt in ("none", "false", None):
        return None, None
    if wt in ("single", "true"):
        return int(body["windowSize"]), None
    if wt == "batched":
        return None, tuple(int(w) for w in body["windowSet"])
    raise ValueError(f"unknown windowType {wt!r}")


def _program_from(body: dict):
    if body.get("rawFile"):
        return registry.compile_source(body["rawFile"])
    return registry.resolve(body["analyserName"], body.get("params"))


class _Handler(BaseHTTPRequestHandler):
    manager: AnalysisManager = None  # injected by serve()
    allow_dynamic: bool = True

    def log_message(self, *a):  # quiet
        pass

    def _json(self, code: int, payload) -> None:
        data = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_POST(self):
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            path = self.path.rstrip("/")
            if path not in ("/ViewAnalysisRequest", "/RangeAnalysisRequest",
                            "/LiveAnalysisRequest"):
                return self._json(404, {"error": f"unknown path {self.path}"})
            if body.get("rawFile") and not self.allow_dynamic:
                return self._json(403, {"error": "dynamic analysers disabled"})
            window, windows = _windows_from(body)
            program = _program_from(body)
            if path == "/ViewAnalysisRequest":
                q = ViewQuery(int(body["timestamp"]), window, windows)
            elif path == "/RangeAnalysisRequest":
                q = RangeQuery(int(body["start"]), int(body["end"]),
                               int(body["jump"]), window, windows)
            else:  # /LiveAnalysisRequest (path validated above)
                max_runs = body.get("maxRuns")
                q = LiveQuery(float(body.get("repeatTime", 1.0)),
                              bool(body.get("eventTime", False)),
                              int(max_runs) if max_runs is not None else None,
                              window, windows)
            # sinkName is a file name resolved INSIDE the server's
            # configured sink dir (jobs/sink.py) — absolute/escaping paths
            # are rejected; with no sink dir configured it is ignored
            job = self.manager.submit(
                program, q, job_id=body.get("jobID"),
                sink_name=body.get("sinkName"),
                sink_format=body.get("sinkFormat"))
            payload = {"jobID": job.id, "status": job.status}
            if job.sink is not None:
                payload["sinkPath"] = job.sink.path
            self._json(200, payload)
        except (KeyError, ValueError, TypeError) as e:
            self._json(400, {"error": f"{type(e).__name__}: {e}"})
        except Exception as e:  # noqa: BLE001
            self._json(500, {"error": f"{type(e).__name__}: {e}"})

    def do_GET(self):
        try:
            parsed = urllib.parse.urlparse(self.path)
            qs = urllib.parse.parse_qs(parsed.query)
            path = parsed.path.rstrip("/")
            if path == "/AnalysisResults":
                job = self.manager.get(qs["jobID"][0])
                return self._json(200, {
                    "jobID": job.id, "status": job.status,
                    "error": job.error, "results": job.results,
                })
            if path == "/KillTask":
                self.manager.kill(qs["jobID"][0])
                return self._json(200, {"jobID": qs["jobID"][0],
                                        "status": "killed"})
            if path == "/Jobs":
                return self._json(200, self.manager.jobs())
            if path == "/Analysers":
                return self._json(200, registry.names())
            return self._json(404, {"error": f"unknown path {self.path}"})
        except KeyError as e:
            self._json(404, {"error": f"KeyError: {e}"})
        except Exception as e:  # noqa: BLE001
            self._json(500, {"error": f"{type(e).__name__}: {e}"})


class RestServer:
    def __init__(self, manager: AnalysisManager, port: int = DEFAULT_PORT,
                 host: str = "127.0.0.1", allow_dynamic: bool = True):
        handler = type("Handler", (_Handler,),
                       {"manager": manager, "allow_dynamic": allow_dynamic})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "RestServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="rest", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
