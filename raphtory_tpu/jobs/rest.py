"""REST job API — wire-compatible surface with the reference.

``AnalysisRestApi.scala`` serves on :8081 (line 30): POST
``/LiveAnalysisRequest`` ``/ViewAnalysisRequest`` ``/RangeAnalysisRequest``
and GET ``/AnalysisResults?jobID=`` ``/KillTask?jobID=`` (lines 35-129).
Same five endpoints here on a stdlib ThreadingHTTPServer (no web-framework
dependency). Request bodies take the reference's field names
(analyserName, timestamp, start/end/jump, windowType, windowSize, windowSet,
repeatTime, rawFile) with `params` as an extension for hyperparameters.

Operational extensions (no reference analogue — SURVEY §5.1 "No spans"):
GET ``/healthz`` (liveness), ``/statusz`` (job table, watermarks, transfer
stats, compile-cache sizes, flight-recorder + ledger state), ``/tracez``
(recent spans; ``?n=``, ``?trace_id=`` for ONE request's spans across
every thread it touched, ``?format=chrome`` for a full Chrome trace-event
document, ``?dump=1`` to write it to a server-side temp file,
``?enable=0|1`` to toggle tracing at runtime), ``/costz`` (the cost
ledger: per-kernel XLA cost/memory analysis with roofline classification
plus recent per-query ledgers — docs/OBSERVABILITY.md "Cost ledger"),
``/slz`` (per-algorithm SLO latency histograms whose tail buckets carry
trace-ID exemplars, plus the bounded queue-depth/stall series ring with
text sparklines — obs/slo.py), ``/profilez`` (the continuous
sampling profiler: JSON status, ``?format=collapsed`` flamegraph lines,
``?enable=0|1`` — obs/sampler.py), ``/workloadz`` (per-tenant workload
accounts rolled up from the query ledgers — obs/workload.py; POSTs may
carry an ``X-RTPU-Tenant`` header or ``tenant`` body field), and
``/advisez`` (the rule-driven advisor's evidence-linked findings;
``?cluster=0`` keeps the pass local — obs/advisor.py), and ``/devicez``
(the measured device runtime: sampled kernel latencies joined with the
estimates, measured-vs-estimated divergence and ``bound_measured``,
device-memory snapshot or its honest degrade, the resident-buffer
registry, and recent XLA compile events with the compile-storm signal —
obs/device.py), and ``/freshz`` (the freshness plane: per-source ingest
telemetry with out-of-orderness histograms, ingest-to-queryable latency
with trace exemplars, live-result staleness quantiles and the
``RTPU_FRESH_TARGET`` staleness-budget judgment — obs/freshness.py).
``/healthz`` is graded ok|degraded|burning from the ``RTPU_SLO_TARGET``
latency budgets joined with the ``RTPU_FRESH_TARGET`` staleness budgets
(obs/budget.py, obs/freshness.py). POST bodies additionally accept ``explain`` (truthy):
the job's resource ledger rides back with ``/AnalysisResults``.

Serving-scheduler fields (jobs/scheduler.py, docs/SERVING.md): POST
bodies may carry ``deadline_ms`` (positive number — expired-in-queue
jobs fail fast with status ``expired``), ``batch`` (boolean; ``false``
opts out of cross-request coalescing) and ``priority`` (int 0..9; >= 8
bypasses the collect window). Malformed values 400 via ``_BadParam``.
With ``RTPU_ADMISSION=1`` an over-budget / over-share /
deadline-infeasible request is shed with **429** + ``Retry-After`` and
the evidence (queue depth, priced cost, budget) that justified it.

Every POST runs under a ``rest.request`` span: the span's trace context
is captured at submit and adopted by the job thread (obs/trace.py), so
``/tracez?trace_id=`` reconstructs REST → job → fold workers → transfer
as ONE trace.
"""

from __future__ import annotations

import json
import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..obs import budget as _budget
from ..obs import device as _device
from ..obs import freshness as _freshness
from ..obs import journal as _journal
from ..obs import ledger as _ledger
from ..obs import slo as _slo
from ..obs import workload as _workload
from ..obs.advisor import ADVISOR
from ..obs.sampler import SAMPLER
from ..obs.trace import TRACER, TraceContext
from ..resilience import faults as _faults
from ..utils.config import process_index, strided_port
from . import registry
from . import scheduler as _scheduler
from .manager import AnalysisManager, LiveQuery, RangeQuery, ViewQuery

DEFAULT_PORT = 8081


def rest_conn_timeout_s() -> float | None:
    """``RTPU_REST_CONN_TIMEOUT_S`` — per-connection socket timeout. A
    half-open client (connected, never finishes its request, or stops
    reading the response) used to pin one ``rest-req-*`` handler thread
    FOREVER; with the timeout the blocked read/write raises, the
    connection closes, and the thread returns to the pool. ``0``
    disables (the old behaviour)."""
    try:
        v = float(os.environ.get("RTPU_REST_CONN_TIMEOUT_S", "") or 30.0)
    except ValueError:
        v = 30.0
    return None if v <= 0 else v


class _BadParam(ValueError):
    """A malformed CLIENT-supplied query parameter — the only
    ValueError do_GET maps to 400. Internal ValueErrors from payload
    construction stay 500: reclassifying them would hide genuine server
    bugs from exactly the 5xx alerting they should trip."""


def _num_param(qs: dict, key: str, default, cast):
    vals = qs.get(key)
    if not vals:
        return default
    try:
        return cast(vals[0])
    except ValueError:
        raise _BadParam(f"{key}={vals[0]!r} is not a number") from None


def _body_deadline_ms(body: dict):
    """Validated ``deadline_ms`` body field: None, or a finite positive
    number. Anything else — bool, container, NaN, negative — is a
    malformed CLIENT field and 400s via ``_BadParam`` (never a 500)."""
    import math as _math

    v = body.get("deadline_ms")
    if v is None:
        return None
    if isinstance(v, bool) or not isinstance(v, (int, float, str)):
        raise _BadParam(f"deadline_ms={v!r} is not a positive number")
    try:
        f = float(v)
    except ValueError:
        raise _BadParam(f"deadline_ms={v!r} is not a positive "
                        "number") from None
    if not _math.isfinite(f) or f <= 0:
        raise _BadParam(f"deadline_ms={v!r} must be a finite positive "
                        "number of milliseconds")
    return f


def _body_priority(body: dict) -> int:
    """Validated ``priority`` body field: an integer 0..9 (>=8 bypasses
    the coalescing collect window — jobs/scheduler.py)."""
    v = body.get("priority")
    if v is None:
        return 0
    if isinstance(v, bool) or not isinstance(v, (int, str)):
        raise _BadParam(f"priority={v!r} is not an integer 0..9")
    try:
        i = int(v)
    except ValueError:
        raise _BadParam(f"priority={v!r} is not an integer 0..9") \
            from None
    if not 0 <= i <= 9:
        raise _BadParam(f"priority={i} out of range 0..9")
    return i


def _body_batch(body: dict):
    """Validated ``batch`` body field: None (default: batchable), or a
    boolean — ``false`` opts this request out of coalescing."""
    v = body.get("batch")
    if v is None:
        return None
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)) and v in (0, 1):
        return bool(v)
    if isinstance(v, str) and v.lower() in ("0", "1", "true", "false",
                                            "yes", "no"):
        return v.lower() in ("1", "true", "yes")
    raise _BadParam(f"batch={v!r} is not a boolean")


def _compile_cache_sizes() -> dict:
    """currsize/hits/misses of every lru_cached compiled-program factory —
    the \"how many XLA programs is this process holding\" signal that made
    the PREWARM sizing note in docs/OPERATIONS.md guesswork until now."""
    out = {}
    from ..engine import bsp as _bsp
    from ..engine import device_sweep as _ds
    from ..engine import hopbatch as _hb

    for mod, names in ((_bsp, ("_compiled_runner",)),
                       (_ds, ("_compiled_run", "_compiled_apply")),
                       (_hb, ("_compiled", "_compiled_delta", "_compiled_cc",
                              "_compiled_bfs", "_compiled_scale"))):
        short = mod.__name__.rsplit(".", 1)[-1]
        for nm in names:
            fn = getattr(mod, nm, None)
            info = getattr(fn, "cache_info", None)
            if info is None:
                continue
            ci = info()
            out[f"{short}.{nm}"] = {"size": ci.currsize, "hits": ci.hits,
                                    "misses": ci.misses}
    # the measured compile half (obs/device.py): per-kernel XLA compile
    # counts/seconds/last-shape-sig observed at the registry's
    # lower().compile() sites — next to the factory lru stats above
    out["kernels"] = _device.compile_block()
    return out


def _fold_cache_status() -> dict:
    """Cross-request fold-cache occupancy + hit rates (core/sweep)."""
    from ..core.sweep import fold_cache

    cache = fold_cache()
    if cache is None:
        return {"enabled": False}
    return {"enabled": True, **cache.stats()}


def _statusz(manager: AnalysisManager,
             handler: "type[_Handler] | _Handler | None" = None) -> dict:
    from ..parallel.sharded import COLLECTIVES
    from ..utils.transfer import shared_engine

    g = manager.graph
    eng = shared_engine()
    status: dict = {
        "jobs": manager.jobs(),
        "log_events": int(g.log.n),
        "watermark": {
            "safe_time": int(g.safe_time()),
            "lag_seconds": round(g.watermarks.lag_seconds(), 3),
            "sources": {k: int(v)
                        for k, v in g.watermarks.snapshot().items()},
        },
        "transfer": {"depth": eng.depth, **eng.stats.as_dict()},
        # the serving scheduler (jobs/scheduler.py): queue depth by
        # class, batches formed, coalesced-jobs histogram, shed and
        # deadline-expired counters, admission backlog + price book
        "scheduler": manager.scheduler.status_block(),
        "compile_caches": _compile_cache_sizes(),
        "fold_cache": _fold_cache_status(),
        "trace": TRACER.status(),
        "ledger": _ledger.status_block(),
        # the judgment plane (PR 11): per-tenant workload accounts,
        # error-budget grades, and the advisor's compact block — what
        # /clusterz federates into the merged mesh view
        "workload": _workload.WORKLOAD.status_block(),
        "budget": _budget.BUDGET.status_block(),
        "advisor": ADVISOR.status_block(),
        # the freshness plane (obs/freshness.py): per-source updates/s
        # total, staged backlog, queryable lag, staleness p99s and the
        # RTPU_FRESH_TARGET grade — what /clusterz federates into the
        # merged min-watermark / watermark-spread view
        "freshness": _freshness.FRESH.status_block(),
        # the measured device plane (PR 12): sampled kernel-timing
        # totals, the memory snapshot (or its honest degrade), resident
        # bytes, and the compile-storm signal — what /clusterz federates
        "device": _device.status_block(),
        # the resilience plane (resilience/): armed failpoints, breaker
        # states, degraded-results tally — the full document is /faultz
        "resilience": _resilience_block(),
        # the durable journal (obs/journal.py): segment bytes, drops,
        # flush lag — what /clusterz federates so a mesh-wide postmortem
        # knows which members have replayable evidence
        "journal": _journal.status_block(),
        # the mesh-divergence sanitizer (analysis/sanitizer.py, armed by
        # RTPU_SANITIZE): per-process dispatch-fingerprint ring — what
        # /clusterz prefix-checks across processes to name the first
        # divergent superstep
        "mesh_sanitizer": _mesh_sanitizer_block(),
        # the distributed half: which process this is, where its
        # listeners actually bound (what /clusterz discovery reads), and
        # what the cross-shard collectives moved
        "cluster": _cluster_block(handler),
    }
    try:
        status["latest_time"] = int(g.latest_time)
    except Exception:   # empty log has no latest time
        status["latest_time"] = None
    status["collectives"] = COLLECTIVES.snapshot()
    return status


def _resilience_block() -> dict:
    """The compact ``resilience`` block of /statusz (federated by
    /clusterz): enough for the merged view to see injected chaos, open
    breakers, and degraded serves without fetching every /faultz."""
    doc = _faults.faultz()
    return {
        "faults_enabled": doc["enabled"],
        "armed_sites": sorted(doc["sites"]),
        "injected": sum(s["injected"] for s in doc["sites"].values()),
        "breakers_open": sorted(
            name for name, b in doc["breakers"].items()
            if b["state"] != "closed"),
        "degraded_results": doc["degraded"].get("total", 0),
    }


def _mesh_sanitizer_block() -> dict:
    """The ``mesh_sanitizer`` block of /statusz: disabled stub when
    RTPU_SANITIZE is off, else the fingerprint ring + counters the
    /clusterz divergence cross-check consumes."""
    from ..analysis.sanitizer import mesh_active

    san = mesh_active()
    if san is None:
        return {"enabled": False}
    return {"enabled": True, **san.status_block()}


def _cluster_block(handler=None) -> dict:
    """The ``cluster`` block of /statusz: process identity, ACTUAL bound
    ports (ephemeral binds resolve here — the ports peers federate on),
    and watchdog membership when this server fronts a NodeRuntime."""
    from ..obs import metrics as _metrics

    out: dict = {"process_index": process_index()}
    ports: dict = {}
    if handler is not None and getattr(handler, "rest_port", None):
        ports["rest"] = handler.rest_port
    mp = _metrics.bound_port()
    if mp:
        ports["metrics"] = mp
    out["ports"] = ports
    wd = getattr(handler, "watchdog", None) if handler is not None else None
    if wd is not None:
        out["watchdog"] = wd.status()
    return out


def _windows_from(body: dict):
    """windowType: 'none' | 'single' | 'batched' (the reference's 3-way task
    split per query type)."""
    wt = body.get("windowType", "none")
    if wt in ("none", "false", None):
        return None, None
    if wt in ("single", "true"):
        return int(body["windowSize"]), None
    if wt == "batched":
        return None, tuple(int(w) for w in body["windowSet"])
    raise ValueError(f"unknown windowType {wt!r}")


def _program_from(body: dict):
    if body.get("rawFile"):
        return registry.compile_source(body["rawFile"])
    return registry.resolve(body["analyserName"], body.get("params"))


class _Handler(BaseHTTPRequestHandler):
    manager: AnalysisManager = None  # injected by serve()
    allow_dynamic: bool = True
    watchdog = None       # NodeRuntime's WatchDog when serving a node
    rest_port: int = 0    # actual bound port, set by RestServer

    def log_message(self, *a):  # quiet
        pass

    def _json(self, code: int, payload, headers: dict | None = None) -> None:
        data = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(data)

    def _text(self, code: int, text: str) -> None:
        data = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    @staticmethod
    def _name_thread() -> None:
        """ThreadingHTTPServer spawns one anonymous ``Thread-N`` per
        request — rename it so traces and profiles read as REST work
        (the tracer refreshes a recycled ident's name on next span)."""
        t = threading.current_thread()
        if t.name.startswith("Thread-"):
            t.name = f"rest-req-{t.ident}"

    def do_POST(self):
        self._name_thread()
        # a POST carrying X-RTPU-Trace is a forwarded hop of a request
        # that started on another process: adopt the wire context so this
        # process's spans JOIN that trace instead of opening a new one
        ctx = TraceContext.from_wire(self.headers.get(TraceContext.HEADER))
        with TRACER.adopt(ctx):
            with TRACER.span("rest.request", method="POST", path=self.path,
                             process=TRACER.process_index) as rsp:
                if ctx is not None:
                    rsp.set(origin_process=ctx.origin)
                self._post(rsp)

    def _post(self, rsp):
        try:
            # the rest.handler failpoint: an injected error terminates
            # HONESTLY as a classified 503 with evidence (the chaos
            # bench's zero-unclassified-500s bar), never a bare 500
            _faults.fire("rest.handler")
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            path = self.path.rstrip("/")
            if path not in ("/ViewAnalysisRequest", "/RangeAnalysisRequest",
                            "/LiveAnalysisRequest"):
                return self._json(404, {"error": f"unknown path {self.path}"})
            if body.get("rawFile") and not self.allow_dynamic:
                return self._json(403, {"error": "dynamic analysers disabled"})
            window, windows = _windows_from(body)
            program = _program_from(body)
            if path == "/ViewAnalysisRequest":
                q = ViewQuery(int(body["timestamp"]), window, windows)
            elif path == "/RangeAnalysisRequest":
                q = RangeQuery(int(body["start"]), int(body["end"]),
                               int(body["jump"]), window, windows)
            else:  # /LiveAnalysisRequest (path validated above)
                max_runs = body.get("maxRuns")
                q = LiveQuery(float(body.get("repeatTime", 1.0)),
                              bool(body.get("eventTime", False)),
                              int(max_runs) if max_runs is not None else None,
                              window, windows)
            # sinkName is a file name resolved INSIDE the server's
            # configured sink dir (jobs/sink.py) — absolute/escaping paths
            # are rejected; with no sink dir configured it is ignored.
            # explain=1 asks for the job's resource ledger back with the
            # results (/AnalysisResults gains a "ledger" block).
            explain = str(body.get("explain", "")).lower() \
                in ("1", "true", "yes")
            # tenant identity: the X-RTPU-Tenant header wins, a `tenant`
            # body field backs it up. Normalization happens inside the
            # job (obs/workload.py) and NEVER fails the request — a
            # malformed value lands in the shared `invalid` account
            tenant = self.headers.get(_workload.TENANT_HEADER)
            if tenant is None or not tenant.strip():
                # a present-but-blank header (proxy artifacts) must not
                # suppress the body-field fallback
                tenant = body.get("tenant")
            # serving-scheduler fields (jobs/scheduler.py): each is
            # validated HERE so malformed client values 400 via the
            # _BadParam path instead of 500ing deep in the jobs layer
            deadline_ms = _body_deadline_ms(body)
            priority = _body_priority(body)
            batch = _body_batch(body)
            job = self.manager.submit(
                program, q, job_id=body.get("jobID"),
                sink_name=body.get("sinkName"),
                sink_format=body.get("sinkFormat"),
                explain=explain, tenant=tenant,
                deadline_ms=deadline_ms, priority=priority, batch=batch)
            rsp.set(job_id=job.id, tenant=job.tenant)
            payload = {"jobID": job.id, "status": job.status,
                       "tenant": job.tenant}
            # the submitter (or forwarding peer) learns the trace id
            # without polling /AnalysisResults — what the 2-process smoke
            # joins cross-process traces on. The handler span's trace IS
            # the job's trace (the job thread adopts the context captured
            # under it); job.trace_id itself only lands once the job
            # thread starts, which this response must not wait for.
            if rsp.trace:
                payload["traceID"] = rsp.trace
            if job.sink is not None:
                payload["sinkPath"] = job.sink.path
            self._json(200, payload)
        except _scheduler.AdmissionDenied as e:
            # a SHED request, not an error: 429 with the Retry-After the
            # pricing computed and the evidence line (queue depth,
            # priced cost, budget) that justified it — clients and
            # operators alike can see WHY, not just that they were told
            # to go away
            rsp.set(shed=e.evidence.get("reason"))
            self._json(
                429,
                {"error": f"AdmissionDenied: {e}",
                 "evidence": e.evidence,
                 "retryAfterSeconds": e.retry_after_s},
                headers={"Retry-After": str(int(e.retry_after_s))})
        except _faults.FaultError as e:
            rsp.set(injected=True)
            self._json(503, {"error": f"FaultError: {e}",
                             "injected": True,
                             "evidence": {"site": "rest.handler"}},
                       headers={"Retry-After": "1"})
        except (KeyError, ValueError, TypeError) as e:
            self._json(400, {"error": f"{type(e).__name__}: {e}"})
        except Exception as e:  # noqa: BLE001
            self._json(500, {"error": f"{type(e).__name__}: {e}"})

    def _tracez(self, qs: dict) -> None:
        """Flight-recorder surface: recent spans as JSON. ``enable=0|1``
        toggles tracing; ``dump=1`` writes the full Chrome trace to a
        server-chosen temp file (never a caller-supplied path — the REST
        surface must not become a file-write primitive)."""
        if "enable" in qs:
            (TRACER.enable if qs["enable"][0] not in ("0", "false")
             else TRACER.disable)()
        payload: dict = dict(TRACER.status())
        if qs.get("dump", ["0"])[0] not in ("0", "false"):
            payload["dumped"] = TRACER.dump()
        if qs.get("format", [""])[0] == "chrome":
            payload["trace"] = TRACER.chrome_trace()
        elif qs.get("trace_id"):
            # one request's spans across every thread it touched — what
            # an /slz exemplar's trace_id resolves to
            tid = qs["trace_id"][0]
            payload["trace_id"] = tid
            payload["spans"] = TRACER.for_trace(tid)
        else:
            payload["spans"] = TRACER.recent(_num_param(qs, "n", 200, int))
        self._json(200, payload)

    def _profilez(self, qs: dict) -> None:
        """Continuous sampling profiler surface (obs/sampler.py):
        ``?enable=1`` starts it (``&hz=`` overrides the rate),
        ``?enable=0`` stops it, ``?format=collapsed`` returns the
        flamegraph collapsed-stack text."""
        if "enable" in qs:
            if qs["enable"][0] not in ("0", "false"):
                SAMPLER.start(_num_param(qs, "hz", None, float))
            else:
                SAMPLER.stop()
        if qs.get("format", [""])[0] == "collapsed":
            return self._text(200, SAMPLER.collapsed())
        self._json(200, SAMPLER.status())

    def _advisez(self, qs: dict) -> None:
        """Advisor surface (obs/advisor.py): one on-demand rule pass.
        ``?cluster=0`` keeps it local; by default the pass federates the
        peers' /statusz via the bounded /clusterz scraper so ONE process
        advises on the whole mesh (straggler + skew rules need the
        per-process rows). The scrape happens here on the request
        thread, outside every lock — the advisor never does network I/O
        from inside its registry."""
        cluster = None
        if qs.get("cluster", ["1"])[0] not in ("0", "false"):
            from ..obs.cluster import clusterz

            cluster = clusterz(
                manager=self.manager, handler=self,
                refresh=(qs.get("refresh", ["0"])[0]
                         not in ("0", "false")))
        self._json(200, ADVISOR.advisez(cluster=cluster))

    def do_GET(self):
        self._name_thread()
        # peer scrapes (/clusterz federation) carry X-RTPU-Trace: adopt
        # it so the serve side of the scrape lands in the SAME trace as
        # the scraping process's rest.scrape span. Plain GETs (no
        # header) keep their zero-span fast path.
        ctx = TraceContext.from_wire(self.headers.get(TraceContext.HEADER))
        with TRACER.adopt(ctx):
            if ctx is not None:
                with TRACER.span("rest.serve_scrape", path=self.path,
                                 process=TRACER.process_index,
                                 origin_process=ctx.origin):
                    self._get()
            else:
                self._get()

    def _get(self):
        try:
            parsed = urllib.parse.urlparse(self.path)
            qs = urllib.parse.parse_qs(parsed.query)
            path = parsed.path.rstrip("/")
            if path != "/faultz":
                # rest.handler failpoint (GET side) — /faultz itself is
                # exempt so the chaos run's own evidence endpoint stays
                # readable while every other route is being failed
                _faults.fire("rest.handler")
            if path == "/AnalysisResults":
                job = self.manager.get(qs["jobID"][0])
                payload = {
                    "jobID": job.id, "status": job.status,
                    "error": job.error,
                    # snapshot: the RTPU_RESULT_ROWS trim shrinks the
                    # live list on the job thread mid-serialization
                    "results": job.results_snapshot(),
                }
                if job.trace_id:
                    # the request's trace: /tracez?trace_id=<this>
                    payload["traceID"] = job.trace_id
                if getattr(job, "degraded", False):
                    # the degraded-serving contract: PARTIAL results,
                    # honestly marked, with the watermark the sweep
                    # actually covered (docs/RESILIENCE.md)
                    payload["degraded"] = True
                    payload["coveredTime"] = job.covered_time
                    payload["degradedReason"] = job.degraded_reason
                if job.results_dropped:
                    # oldest rows rolled off the RTPU_RESULT_ROWS cap —
                    # the sink file (when configured) has the full set
                    payload["resultsDropped"] = job.results_dropped
                if job.explain:
                    payload["ledger"] = job.ledger.as_dict()
                return self._json(200, payload)
            if path == "/KillTask":
                self.manager.kill(qs["jobID"][0])
                return self._json(200, {"jobID": qs["jobID"][0],
                                        "status": "killed"})
            if path == "/Jobs":
                return self._json(200, self.manager.jobs())
            if path == "/Analysers":
                return self._json(200, registry.names())
            if path == "/healthz":
                # graded from the error-budget state (obs/budget.py):
                # ok|degraded|burning in the body; HTTP 503 on burning
                # only under RTPU_HEALTH_STRICT=1, so load balancers can
                # act on burn without parsing JSON
                code, payload = _budget.healthz()
                return self._json(code, payload)
            if path == "/statusz":
                return self._json(200, _statusz(self.manager, self))
            if path == "/clusterz":
                from ..obs.cluster import clusterz

                return self._json(200, clusterz(
                    manager=self.manager, handler=self,
                    trace_id=(qs.get("trace_id") or [None])[0],
                    refresh=(qs.get("refresh", ["0"])[0]
                             not in ("0", "false"))))
            if path == "/tracez":
                return self._tracez(qs)
            if path == "/costz":
                # per-kernel harvested XLA cost/memory analysis with the
                # roofline classification + recent per-query ledgers
                return self._json(200, _ledger.costz())
            if path == "/devicez":
                # the measured device plane (obs/device.py): sampled
                # kernel latencies joined with estimates (divergence +
                # bound_measured), device memory (or its degrade),
                # resident buffers, recent compile events + storm
                return self._json(200, _device.devicez())
            if path == "/freshz":
                # the freshness plane (obs/freshness.py): per-source
                # ingest telemetry (op mix, out-of-orderness),
                # ingest-to-queryable histograms with trace exemplars,
                # live-result staleness quantiles, the staleness-budget
                # judgment (RTPU_FRESH_TARGET)
                return self._json(200, _freshness.freshz())
            if path == "/slz":
                # SLO histograms + trace exemplars + the series ring
                return self._json(
                    200, _slo.slz_payload(_num_param(qs, "n", 120, int)))
            if path == "/profilez":
                return self._profilez(qs)
            if path == "/faultz":
                # the resilience plane (resilience/): armed failpoints
                # with injection counts, per-peer breaker states, the
                # degraded-results ledger — docs/RESILIENCE.md
                return self._json(200, _faults.faultz())
            if path == "/journalz":
                # the durable journal (obs/journal.py): segment
                # inventory with bytes, drop/error counters, flush lag
                # — docs/OBSERVABILITY.md "Durable journal"
                return self._json(200, _journal.journalz())
            if path == "/workloadz":
                # per-tenant workload accounts (obs/workload.py)
                return self._json(200, _workload.WORKLOAD.workloadz())
            if path == "/advisez":
                return self._advisez(qs)
            return self._json(404, {"error": f"unknown path {self.path}"})
        except _faults.FaultError as e:
            self._json(503, {"error": f"FaultError: {e}",
                             "injected": True,
                             "evidence": {"site": "rest.handler"}},
                       headers={"Retry-After": "1"})
        except KeyError as e:
            self._json(404, {"error": f"KeyError: {e}"})
        except _BadParam as e:
            # malformed numeric query params (?n=abc, ?hz=abc) are the
            # CLIENT's fault — a 500 here would trip 5xx alerting on the
            # very observability surface being queried. Only _BadParam:
            # an internal ValueError from payload construction is a
            # server bug and must stay 500.
            self._json(400, {"error": f"ValueError: {e}"})
        except Exception as e:  # noqa: BLE001
            self._json(500, {"error": f"{type(e).__name__}: {e}"})


class RestServer:
    def __init__(self, manager: AnalysisManager, port: int = DEFAULT_PORT,
                 host: str = "127.0.0.1", allow_dynamic: bool = True,
                 watchdog=None):
        handler = type("Handler", (_Handler,),
                       {"manager": manager, "allow_dynamic": allow_dynamic,
                        "watchdog": watchdog,
                        # per-connection socket timeout (stdlib
                        # StreamRequestHandler honours the class attr in
                        # setup()): a half-open client's blocked read or
                        # write raises instead of pinning a rest-req-*
                        # thread forever
                        "timeout": rest_conn_timeout_s()})
        # stride the listen port by jax.process_index() so an N-process
        # localhost cluster never collides on :8081 (RTPU_PORT_STRIDE;
        # port 0 stays ephemeral, process 0 binds the base verbatim)
        self.httpd = ThreadingHTTPServer((host, strided_port(port)),
                                         handler)
        self.port = self.httpd.server_address[1]
        handler.rest_port = self.port   # what /statusz reports to peers
        # the UNSTRIDED base: what peer-URL derivation needs (peer i is
        # base + i*stride — deriving from an already-strided port would
        # double-offset every peer on a non-zero process)
        handler.rest_base_port = int(port) or None
        self._thread: threading.Thread | None = None
        # the /slz series ring samples THIS manager's queue depth and
        # in-flight jobs (weakly registered — the ring is process-wide);
        # the advisor reads the same manager's graph for watermark lag
        _slo.SERIES.attach_manager(manager)
        ADVISOR.attach_manager(manager)

    def start(self) -> "RestServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="rest", daemon=True)
        self._thread.start()
        # a serving process is what the over-time surfaces exist for:
        # start the series ring, and the profiler when RTPU_SAMPLE_HZ
        # asks for it. Both process-wide singletons, idempotent — left
        # running on stop() (another server in this process may depend
        # on them, and an idle 1 Hz sampler is noise)
        _slo.SERIES.start()
        SAMPLER.maybe_start()
        # the periodic advisor tick (RTPU_ADVISOR gates it) — strictly
        # read-only rule evaluation; same leave-running-on-stop contract
        # as the ring and the sampler
        ADVISOR.maybe_start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
