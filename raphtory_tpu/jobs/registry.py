"""Algorithm registry — the reflection/runtime-compilation surface.

The reference resolves analysers by ``Class.forName`` and, failing that,
compiles Scala source shipped in the REST payload with a ToolBox
(``AnalysisManager.scala:192-213``, ``Analyser.scala:23-28``). Here:
a name registry for built-ins + plain-Python dynamic definitions ("dynamic
analyser" = a Python snippet defining ``program``), no compiler machinery.
"""

from __future__ import annotations

import threading

from ..engine.program import VertexProgram

_REGISTRY: dict[str, type] = {}
_REGISTRY_LOCK = threading.Lock()   # REST threads register dynamic
_BUILTINS_LOADED = False            # analysers while jobs resolve built-ins


def register(name: str | None = None):
    def deco(cls):
        with _REGISTRY_LOCK:
            _REGISTRY[name or cls.__name__] = cls
        return cls
    return deco


def names() -> list[str]:
    _ensure_builtins()
    with _REGISTRY_LOCK:
        return sorted(_REGISTRY)


def resolve(name: str, params: dict | None = None) -> VertexProgram:
    """Instantiate a registered program by name with hyperparams."""
    _ensure_builtins()
    with _REGISTRY_LOCK:
        cls = _REGISTRY.get(name)
        known = sorted(_REGISTRY)
    if cls is None:
        raise KeyError(
            f"unknown analyser {name!r}; registered: {known}")
    # REST params arrive as JSON, so sequence hyperparams (e.g. SSSP
    # seeds) come in as lists — programs must stay hashable (the
    # compiled-runner cache keys on them), so freeze them here
    params = {k: tuple(v) if isinstance(v, list) else v
              for k, v in (params or {}).items()}
    return cls(**params)


def compile_source(source: str) -> VertexProgram:
    """Dynamic analyser: exec Python source that binds ``program`` (the
    LoadExternalAnalyser capability — the reference accepts raw analyser
    source over REST, ``AnalysisRestApi`` rawFile field). Runs with full
    interpreter privileges, exactly like the reference's ToolBox compile;
    deployments that do not want this must not expose the REST port."""
    ns: dict = {}
    exec(source, ns)  # noqa: S102 — capability parity with reference
    prog = ns.get("program")
    if prog is None:
        raise ValueError("dynamic analyser source must define `program`")
    return prog


def _ensure_builtins() -> None:
    global _BUILTINS_LOADED
    if _BUILTINS_LOADED:   # benign racy fast-path; the slow path locks
        return
    from .. import algorithms as A   # import OUTSIDE the lock: an import

    with _REGISTRY_LOCK:             # that re-enters the registry (the
        if _BUILTINS_LOADED:         # @register decorators) must not
            return                   # deadlock against it
        for nm in A.__all__:
            # bounded by the builtin algorithm list (loaded once behind
            # _BUILTINS_LOADED); dynamic rawFile programs are instantiated
            # per request, never registered — the table cannot grow with
            # traffic.  # rtpulint: disable=unbounded-growth-on-request-path
            _REGISTRY.setdefault(nm, getattr(A, nm))
        _BUILTINS_LOADED = True
