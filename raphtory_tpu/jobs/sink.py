"""Result file sinks — per-job line writers for analysis output.

The reference appends every analysis result row as a text line to an
env-configured path — ``Utils.scala:107-126`` (``writeLines``: print to
stdout when unset, else mkdirs + append file) — and each algorithm formats
its rows inline (``ConnectedComponents.scala:46,62`` JSON rows,
``/analysis/Analyser.scala`` subclasses generally). Without a sink a long
Range job's results lived only in the job object and died with the process.

Here the sink is a small thread-safe line-writer attached to the job's
emit path: rows stream to disk the moment they are computed (line-buffered,
so a killed job's partial output survives), in ``jsonl`` (one JSON object
per row, the reference's shape) or ``csv`` (header + one row per view),
while the same rows stay in memory for the REST surface.
"""

from __future__ import annotations

import csv
import io
import json
import os
import threading

__all__ = ["ResultSink", "resolve_sink_path"]

_CSV_FIELDS = ("time", "windowsize", "viewTime", "steps", "result")


class ResultSink:
    """Append result rows to ``path`` as lines; format inferred from the
    suffix (``.csv`` → csv, anything else → jsonl) unless ``fmt`` forces
    one. Parent directories are created (the reference's mkdirs). Writes
    are flushed per line so readers — and post-kill inspection — always
    see every emitted row."""

    def __init__(self, path: str, fmt: str | None = None):
        if fmt is None:
            fmt = "csv" if str(path).endswith(".csv") else "jsonl"
        if fmt not in ("jsonl", "csv"):
            raise ValueError(f"unknown sink format {fmt!r}")
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self.path = str(path)
        self.fmt = fmt
        self.rows_written = 0
        self._lock = threading.Lock()
        self._fh: io.TextIOBase | None = open(path, "a", encoding="utf-8",
                                              newline="")   # csv contract
        self._csv = csv.writer(self._fh) if fmt == "csv" else None
        self._header_done = fmt != "csv"

    def write(self, row: dict) -> None:
        """Append one result row (no-op after close, so a racing emit
        during job teardown cannot raise)."""
        with self._lock:
            if self._fh is None:
                return
            if not self._header_done:
                # deferred to the first row: a sink that is opened but
                # loses the manager's in-use check never dirties the file,
                # and an append to an existing file keeps its one header
                if self._fh.tell() == 0:
                    self._csv.writerow(_CSV_FIELDS)
                self._header_done = True
            if self.fmt == "csv":
                self._csv.writerow(
                    [json.dumps(row.get(k), default=str)
                     if k == "result" else row.get(k)
                     for k in _CSV_FIELDS])
            else:
                self._fh.write(json.dumps(row, default=str) + "\n")
            self._fh.flush()
            self.rows_written += 1

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "ResultSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def resolve_sink_path(sink_dir: str, job_id: str,
                      requested: str | None = None,
                      fmt: str = "jsonl") -> str | None:
    """Resolve a job's sink path. With no configured ``sink_dir`` sinks are
    disabled (returns None) — matching the reference's unset-env behaviour
    minus the stdout spam. Both the ``requested`` name (from a REST body)
    and the job id (also caller-supplied over REST) are interpreted
    RELATIVE to ``sink_dir`` and must stay inside it: network callers pick
    a file name, never an absolute filesystem location. Extensionless
    names get the ``fmt`` suffix so the format survives suffix inference."""
    if not sink_dir:
        return None
    if fmt not in ("jsonl", "csv"):
        raise ValueError(f"unknown sink format {fmt!r}")
    if requested is not None and not isinstance(requested, str):
        raise ValueError(f"sink name must be a string, got "
                         f"{type(requested).__name__}")
    base = os.path.realpath(sink_dir)
    name = requested if requested else f"{job_id}.{fmt}"
    if not name.endswith((".jsonl", ".csv")):
        name += f".{fmt}"
    # realpath (not abspath): a symlink planted inside the sink dir must
    # not smuggle writes outside it
    cand = os.path.realpath(os.path.join(base, name))
    if os.path.commonpath([base, cand]) != base or cand == base:
        raise ValueError(f"sink path {name!r} escapes the sink dir")
    if os.path.isdir(cand):
        raise ValueError(f"sink path {name!r} is a directory")
    return cand
