"""Live epoch engine: incremental delta maintenance for standing queries.

A Live subscription used to pay a FULL re-sweep per tick — `_run_at`
rebuilt the view (or re-advanced the resident sweep) and re-ran the
whole algorithm even when one event arrived since the last tick. The
epoch engine keeps ONE columnar hop-batched engine (engine/hopbatch)
alive per subscription, device-resident base included, and serves each
tick ("epoch") by:

* adopting the log suffix appended since the last epoch in place
  (``SweepBuilder.repin`` — same coordinate space, so fold state, the
  device-resident advanced base and the host delta base all stay
  valid),
* folding ONLY the events in ``(t_prev, t]`` and shipping O(Σdelta)
  bytes through ``run_columns_delta``'s delta path, and
* warm-starting the solve from the previous epoch's output — PageRank
  unconditionally (contraction), CC/BFS by min-merge under the
  monotone gate (add-only epoch delta, unwindowed — the kernel
  docstrings in engine/hopbatch state the equivalence argument), SSSP
  never (a weight update can raise distances).

Every epoch falls back to the legacy full re-sweep (``Job._run_at``)
when the incremental path cannot serve — non-columnar program, engine
construction/dispatch failure, memory guards — so the fallback IS the
correctness oracle: both paths emit through ``Job._emit`` with
identical row shapes. Every ``RTPU_LIVE_RESYNC`` epochs the engine
drops device residency and the warm seed ("resync"): the next epoch
re-ships the base from the exact integer host fold state, bounding
f32 warm-seed drift without rebuilding host state.

Epoch modes (the ``raphtory_live_epochs_total{algorithm,mode}`` label
set, closed):

* ``incremental`` — suffix adopted, delta folded, warm-seeded solve
* ``rebase``      — fresh engine built (first epoch, or repin refused:
                    compaction / new vertex / new pair / out-of-order
                    / dtype overflow); full base ships once
* ``resync``      — scheduled residency + warm-seed drop (drift bound)
* ``resweep``     — legacy full re-sweep fallback
* ``skipped``     — wall-clock mode, neither safe_time nor the log
                    moved: the previous result is still THE result at
                    t, so no work is re-run (freshness still recorded)
"""

from __future__ import annotations

import os
import time as _time

import numpy as np

from ..obs import freshness as _fresh
from ..obs import journal as _journal
from ..obs.metrics import METRICS
from ..obs.trace import TRACER, block_steps as _block_steps

import logging

_live_log = logging.getLogger(__name__)

#: device/host admission guards for the standing engine — same bounds
#: the columnar range route applies per request (jobs/manager.py
#: ``_columnar_range_prep``); a subscription holds them for its lifetime
MAX_DEVICE_MASK_BYTES = 1 << 32
MAX_HOST_COLUMN_BYTES = 1 << 29


def live_enabled() -> bool:
    """``RTPU_LIVE=0`` restores the legacy full-re-sweep-per-tick live
    loop (the bench A/B off arm). Re-read per epoch — flipping it
    mid-stream is legal and lands on the next epoch (the standing
    engine is dropped, not leaked)."""
    return os.environ.get("RTPU_LIVE", "1") not in ("", "0", "false")


def epoch_floor_s() -> float:
    """Minimum inter-epoch wait in wall-clock mode (``RTPU_LIVE_EPOCH_MS``,
    milliseconds): the cadence floor a burning staleness budget is
    allowed to reach. Unparseable values fall back to the default."""
    try:
        v = float(os.environ.get("RTPU_LIVE_EPOCH_MS", "") or 25.0)
    except ValueError:
        v = 25.0
    return max(0.0, v) / 1000.0


def resync_every() -> int:
    """Scheduled full-resync period in epochs (``RTPU_LIVE_RESYNC``):
    every N incremental epochs the engine drops device residency and
    the warm seed, bounding f32 warm-start drift. 0 disables."""
    try:
        v = int(os.environ.get("RTPU_LIVE_RESYNC", "") or 64)
    except ValueError:
        v = 64
    return max(0, v)


class LiveEpochState:
    """Per-subscription epoch state: the standing columnar engine, the
    previous epoch's raw output (the warm seed), and the skip-gate
    bookkeeping. Owned and driven by ONE job thread (``Job._run_live``)
    — no locking; the engine's own device state is job-private."""

    def __init__(self, job):
        self.job = job
        self.hb = None                  # standing hop-batched engine
        self._builder_failed = False    # program has no columnar engine
        self.last_t: int | None = None
        self.last_log_n = -1
        self.last_out = None            # [W, n_pad] previous raw output
        self.served = 0                 # epochs that emitted rows
        self.since_resync = 0
        self.mode_counts: dict[str, int] = {}

    # ---- the epoch ----

    def epoch(self, q, t: int) -> str:
        """Serve one epoch at event time ``t``; returns the epoch mode.
        Emission, ledger phases and telemetry all happen inside — the
        caller (``_run_live``) only computes ``t`` and paces."""
        t = int(t)
        t0 = _time.perf_counter()
        alg = (self.job.ledger.algorithm
               or type(self.job.program).__name__)
        log = self.job.graph.log
        log_n = int(log.n)

        if (not q.event_time and self.served > 0
                and self.last_t == t and self.last_log_n == log_n):
            # wall-clock skip gate (belt and braces: the watermark
            # contract alone implies an unchanged t has an unchanged
            # fold, but a direct log append is legal and unfenced, so
            # the row count is checked too): neither the safe time nor
            # the log moved since the last served epoch — the previous
            # result IS the result at t. Serve it from the results
            # buffer by doing nothing; staleness is still recorded
            # (the data aged even if the graph didn't change).
            TRACER.instant("live.epoch", mode="skipped", time=t,
                           algorithm=alg)
            self._finish("skipped", t, alg, delta_rows=0, ship_bytes=0,
                         seconds=_time.perf_counter() - t0, priced=False)
            return "skipped"

        if not live_enabled():
            self.hb = None          # flipping the knob drops the engine
            self.last_out = None
            return self._resweep(q, t, alg, t0)

        mode = "incremental"
        if self.hb is not None:
            status = self.hb.repin()
            if status == "rebuild":
                # the adopted-suffix invariants broke (compaction, new
                # vertex/pair, out-of-order arrival past t_prev, dtype
                # overflow): the engine's pin may be rebound past the
                # decision point — discard it wholesale and rebase
                self.hb = None
                self.last_out = None    # n_pad may change under a rebuild
        if self.hb is None:
            if self._builder_failed:
                return self._resweep(q, t, alg, t0)
            try:
                hb = self.job._columnar_builder()
            except (TypeError, ValueError, MemoryError) as e:
                _live_log.info("live epoch engine declined: %s: %s",
                               type(e).__name__, e)
                self._builder_failed = True
                return self._resweep(q, t, alg, t0)
            windows = (list(q.windows) if q.windows is not None
                       else [q.window])
            if (hb.device_mask_bytes(len(windows)) > MAX_DEVICE_MASK_BYTES
                    or hb.host_column_bytes(1) > MAX_HOST_COLUMN_BYTES):
                self._builder_failed = True   # a guard is a property of
                return self._resweep(q, t, alg, t0)  # the graph's size
            self.hb = hb
            mode = "rebase"
        hb = self.hb

        if hb.sw.t_prev is not None and t < int(hb.sw.t_prev):
            # time went backward (watermark regression is a caller bug,
            # but never serve a wrong answer for it): the hop engine
            # only ascends — full re-sweep and rebuild next epoch
            self.hb = None
            self.last_out = None
            return self._resweep(q, t, alg, t0)

        if (mode == "incremental" and resync_every() > 0
                and self.since_resync >= resync_every()):
            # scheduled drift bound: drop residency AND the warm seed —
            # the next dispatch re-ships the base from the exact
            # integer host fold state and solves cold, so only this
            # epoch pays O(base) ship; host fold state is NOT rebuilt
            mode = "resync"
            hb._drop_residency()
            self.last_out = None
            self.since_resync = 0

        delta_rows, add_only = self._delta_stats(hb, t)
        windows = list(q.windows) if q.windows is not None else [q.window]
        warm = None
        if self.last_out is not None and mode == "incremental":
            if hb.supports_warm_start:
                warm = self.last_out        # contraction: always valid
            elif (hb.supports_epoch_warm and add_only
                    and windows == [None]):
                # min-merge warm init is only equivalent when the graph
                # monotonically grew since the seed was computed and no
                # window can drop edges (kernel docstrings argue this)
                warm = self.last_out

        shells = {}

        def grab_shell(T, sw):
            shells[int(T)] = _manager()._shell_from_fold(
                hb.tables, sw, int(T))

        try:
            with TRACER.span("live.epoch", mode=mode, time=t,
                             algorithm=alg, delta_rows=int(delta_rows),
                             warm=warm is not None):
                ranks, steps = hb.run([t], windows, chunks=1,
                                      hop_callback=grab_shell,
                                      warm_state=warm)
                b0 = _time.perf_counter()
                ranks, steps = _block_steps(
                    lambda: (np.asarray(ranks), steps))
                self.job.ledger.add_phase("device_wait",
                                          _time.perf_counter() - b0)
        except Exception as e:
            # ANY incremental failure (fold, dispatch, device) falls
            # back to the oracle path for THIS epoch and rebuilds the
            # engine on the next — a live job must keep serving
            _live_log.warning("live epoch failed (%s: %s) — falling "
                              "back to full re-sweep",
                              type(e).__name__, e)
            self.hb = None
            self.last_out = None
            return self._resweep(q, t, alg, t0)

        ship = int(hb.ship_bytes)
        elapsed = _time.perf_counter() - t0
        METRICS.snapshot_build_seconds.observe(hb.fold_seconds)
        METRICS.supersteps.inc(max(int(steps), 0))
        self.job.ledger.count_supersteps(int(steps))
        per_row = elapsed / max(len(windows), 1)
        for i, w in enumerate(windows):
            if self.job._kill.is_set():
                break
            self.job._emit(t, w, ranks[i], shells[t], int(steps),
                           _time.perf_counter() - per_row)
        self.last_out = ranks
        self.last_t = t
        self.last_log_n = log_n
        self.served += 1
        self.since_resync += 1
        self._finish(mode, t, alg, delta_rows=delta_rows,
                     ship_bytes=ship,
                     seconds=_time.perf_counter() - t0)
        return mode

    # ---- cadence ----

    def next_wait(self, q) -> float:
        """Wall-clock inter-epoch wait, adapted to the staleness budget:
        a burning budget serves back-to-back at the ``RTPU_LIVE_EPOCH_MS``
        floor, a degraded one halves the requested repeat, an ok one
        coalesces at the requested repeat (never below the floor)."""
        floor = epoch_floor_s()
        alg = (self.job.ledger.algorithm
               or type(self.job.program).__name__)
        grade = _fresh.FRESH.live_grade(alg)
        if grade == "burning":
            return floor
        if grade == "degraded":
            return max(floor, float(q.repeat) / 2.0)
        return max(floor, float(q.repeat))

    # ---- internals ----

    def _delta_stats(self, hb, t: int):
        """(rows folded this epoch, add-only?) — BY TIME over the full
        pinned log, not by pin growth: event-time mode can fold OLD
        pinned rows (t advanced past them), and the add-only warm gate
        must see every row entering the fold window ``(t_prev, t]``."""
        sw = hb.sw
        tcol, kcol = sw._t, sw._k
        t_prev = sw.t_prev
        if not len(tcol):
            return 0, True
        if sw._t_sorted:
            lo = 0 if t_prev is None else int(
                np.searchsorted(tcol, t_prev, side="right"))
            hi = int(np.searchsorted(tcol, t, side="right"))
            kinds = kcol[lo:hi]
            n = hi - lo
        else:
            m = tcol <= t
            if t_prev is not None:
                m &= tcol > t_prev
            kinds = kcol[m]
            n = int(m.sum())
        from ..core.events import EDGE_DELETE, VERTEX_DELETE

        add_only = not bool(((kinds == VERTEX_DELETE)
                             | (kinds == EDGE_DELETE)).any())
        return n, add_only

    def _resweep(self, q, t: int, alg: str, t0: float) -> str:
        """The legacy full re-sweep — the oracle path every degraded
        epoch takes (``exact=False`` mirrors the pre-epoch live loop)."""
        with TRACER.span("live.epoch", mode="resweep", time=t,
                         algorithm=alg):
            self.job._run_at(t, q, exact=False)
        self.last_t = t
        self.last_log_n = int(self.job.graph.log.n)
        self.served += 1
        self._finish("resweep", t, alg, delta_rows=-1, ship_bytes=-1,
                     seconds=_time.perf_counter() - t0)
        return "resweep"

    def _finish(self, mode: str, t: int, alg: str, *, delta_rows: int,
                ship_bytes: int, seconds: float,
                priced: bool = True) -> None:
        """Per-epoch telemetry, identical across modes: staleness into
        the freshness plane (returned staleness feeds the subscription
        table), the bounded epochs counter, and the ``live:`` admission
        price (skipped epochs are free and never priced — an EWMA of
        zeros would undercharge the epochs that do work)."""
        # keyed by the closed epoch-mode set (incremental / rebase /
        # resweep / skipped / resync — the docs/LIVE.md table and the
        # metric label), so at most five entries for the subscription's
        # lifetime.  # rtpulint: disable=unbounded-growth-on-request-path
        self.mode_counts[mode] = self.mode_counts.get(mode, 0) + 1
        try:
            head = int(self.job.graph.latest_time)
        except Exception:       # empty log has no latest time
            head = None
        staleness = _fresh.FRESH.note_live_result(
            alg, t, head_time=head, trace_id=self.job.trace_id)
        _fresh.FRESH.note_live_epoch(
            self.job.id, algorithm=alg, mode=mode,
            delta_rows=delta_rows, ship_bytes=ship_bytes,
            staleness_s=staleness, result_time=t)
        METRICS.live_epochs.labels(alg, mode).inc()
        if _journal.enabled():
            _journal.emit("epoch", {
                "job_id": self.job.id, "algorithm": alg, "mode": mode,
                "result_time": t, "delta_rows": delta_rows,
                "ship_bytes": ship_bytes,
                "staleness_s": (round(staleness, 6)
                                if staleness is not None else None),
                "seconds": round(seconds, 6), "served": self.served},
                trace_id=self.job.trace_id)
        if priced and self.job._sched is not None:
            try:
                self.job._sched.note_live_epoch(alg, seconds)
            except Exception:   # pricing never fails a live job
                pass


def _manager():
    # late import: jobs/manager imports THIS module inside _run_live
    from . import manager

    return manager
