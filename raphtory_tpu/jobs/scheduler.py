"""Serving scheduler — cross-request query batching, ledger-priced
admission control, and per-request deadlines (ROADMAP item 1).

The fold cache and resident engines made *repeat* queries cheap; this
module makes *concurrent distinct* queries cheap. Today every REST
request is its own job thread dispatching its own sweep, even when ten
in-flight requests want overlapping (hop, window) views of the same
graph. Their views are just more COLUMNS — ``engine/hopbatch.py``
already batches columns — so the scheduler sits between ``jobs/rest.py``
and the engines and does three things:

* **Coalescing.** A short collect window (``RTPU_BATCH_WINDOW_MS``,
  default 3 ms; ``0`` restores today's behaviour exactly) groups
  compatible concurrent jobs — same graph log, same algorithm family
  and parameters, View/Range queries whose (hop, window) grids can
  stack — into ONE shared columnar dispatch
  (``hopbatch.stack_grids``), demultiplexing per-request results and
  splitting the shared phase seconds by column share
  (``Ledger.absorb_share``) afterwards. Incompatible jobs (meshes,
  live queries, non-columnar programs, tight deadlines, ``batch:false``
  or ``priority >= 8`` requests) pass through unbatched on exactly the
  pre-scheduler path; a window that collects only ONE job also declines
  to batch, so an idle server's per-request behaviour is unchanged.
  Fold checkpoints and fold-cache entries are shared across tenants
  exactly as the content-addressed ``FoldCache`` already permits.

* **Admission control** (``RTPU_ADMISSION=1``). Before a job is even
  created, the request is priced from the ledger's recent
  per-algorithm cost history (an EWMA seconds-per-view book fed by
  every completed job) times its view count, and judged against the
  live backlog of admitted-but-unfinished cost: over-budget requests,
  deadline-infeasible requests, over-share tenants and — while some
  SLO error budget is burning — the top-cost tenant (the advisor's
  ``queue-burn-shed-top-tenant`` recommendation, actuated) are shed
  with HTTP 429 + ``Retry-After`` and the evidence that justified it.

* **Deadlines.** Requests may carry ``deadline_ms``; a job whose
  deadline passes while it waits in a collect window fails fast with
  status ``expired`` — it never dispatches — and a job whose deadline
  is too tight for the collect window is never batched behind one.

Concurrency contract (rtpulint RT009/RT010/RT011): one lock guards the
queue + admission counters; no engine, device, or cross-module call
ever runs under it (batch dispatch, budget evaluation and workload
reads all happen outside); the queue, the price book and the per-tenant
live table are all explicitly bounded. The dispatcher thread is lazy —
started on first enqueue, exits after an idle period — so short-lived
managers in tests never leak threads.

Surfaces: a ``scheduler`` block in ``/statusz``, ``raphtory_scheduler_*``
Prometheus metrics, ``sched.batch`` / ``sched.shed`` / ``sched.deadline``
flight-recorder instants, and ``RTPU_SCHED_DUMP`` (full scheduler state
written at interpreter exit — the CI failure artifact). Design doc:
docs/SERVING.md.
"""

from __future__ import annotations

import itertools
import logging
import math
import os
import threading
import time as _time
import weakref

from ..analysis.sanitizer import (note_shared as _san_note,
                                  track_shared as _san_track)
from ..obs import budget as _budget
from ..obs import journal as _journal
from ..obs import ledger as _ledger
from ..obs import workload as _workload
from ..obs.metrics import METRICS
from ..obs.trace import TRACER
from ..resilience import faults as _faults

_log = logging.getLogger(__name__)

#: requests at or above this priority bypass the collect window — the
#: client's "latency over throughput" escape hatch (docs/SERVING.md)
PRIORITY_BYPASS = 8
#: per-request (hop, window) grid cell cap — matches the jobs layer's
#: columnar-route guard, so a request the scheduler would take is one
#: the solo path would also have taken columnar
MAX_REQUEST_CELLS = 256
#: jobs collected into one batch before it dispatches early
MAX_BATCH_JOBS = 128
#: distinct algorithm labels the price book tracks (dynamic ``rawFile``
#: programs could otherwise mint unbounded keys — RT011)
MAX_PRICE_KEYS = 128
#: default seconds-per-view before any cost history exists
DEFAULT_PRICE_S = 0.05

#: live scheduler instances (weak — a dead manager's scheduler must be
#: collectable) for the process-wide gauges and the RTPU_SCHED_DUMP /
#: tier-1 failure artifact
_INSTANCES: "weakref.WeakSet[ServingScheduler]" = weakref.WeakSet()
_BATCH_IDS = itertools.count()


# ------------------------------------------------------------------ knobs


def window_ms() -> float:
    """Collect-window length. Re-read per enqueue so the bench A/B (and
    operators) can flip coalescing without a restart; ``0`` disables the
    scheduler entirely — bit-identical to the pre-scheduler path."""
    try:
        return max(0.0, float(os.environ.get("RTPU_BATCH_WINDOW_MS", "3")
                              or 3.0))
    except ValueError:
        return 3.0


def admission_enabled() -> bool:
    return os.environ.get("RTPU_ADMISSION", "0") not in ("", "0", "false")


def admission_budget_s() -> float:
    try:
        return max(0.1, float(
            os.environ.get("RTPU_ADMISSION_BUDGET_S", "60") or 60.0))
    except ValueError:
        return 60.0


def admission_max_jobs() -> int:
    try:
        return max(1, int(
            os.environ.get("RTPU_ADMISSION_MAX_JOBS", "512") or 512))
    except ValueError:
        return 512


def tenant_share() -> float:
    """Max fraction of the admitted-job cap one tenant may hold."""
    try:
        return min(1.0, max(0.01, float(
            os.environ.get("RTPU_SCHED_TENANT_SHARE", "0.5") or 0.5)))
    except ValueError:
        return 0.5


def queue_cap() -> int:
    """Total jobs waiting in collect windows; past it, new jobs pass
    through unbatched (never dropped) — the queue is provably bounded."""
    try:
        return max(1, int(
            os.environ.get("RTPU_SCHED_QUEUE_CAP", "1024") or 1024))
    except ValueError:
        return 1024


def max_gate_ms() -> float:
    """Upper bound on how long backpressure gating may hold a waiting
    member behind its family's in-flight batch. Gating is what GROWS
    batches under load (the next batch collects while the current one
    runs), but unbounded gating puts a whole dispatch duration into the
    tail — past this bound the batch spills and dispatches concurrently
    instead (docs/SERVING.md "Backpressure")."""
    try:
        return max(0.0, float(
            os.environ.get("RTPU_SCHED_MAX_GATE_MS", "300") or 300.0))
    except ValueError:
        return 300.0


def max_cols() -> int:
    """Column cap of one coalesced dispatch (the batch grid is the hop
    union × window union cross product; overflow members start the next
    batch)."""
    try:
        return max(2, int(
            os.environ.get("RTPU_SCHED_MAX_COLS", "1024") or 1024))
    except ValueError:
        return 1024


# ------------------------------------------------- request classification


def family_of(program):
    """The batch-compatibility key of a program, or None when it has no
    columnar engine: programs coalesce ONLY when the whole tuple —
    family and every result-affecting parameter — matches, so a shared
    dispatch can never change any member's semantics."""
    from ..algorithms import ConnectedComponents as _CC
    from ..algorithms import PageRank as _PR
    from ..algorithms.traversal import SSSP as _SSSP

    p = program
    if type(p) is _PR:
        return ("pagerank", float(p.damping), float(p.tol),
                int(p.max_steps))
    if type(p) is _CC:
        return ("cc", int(p.max_steps))
    if type(p) is _SSSP:
        return ("sssp" if p.weight_prop else "bfs",
                tuple(sorted(int(s) for s in p.seeds)),
                p.weight_prop, bool(p.directed), int(p.max_steps))
    return None


def request_grid(query):
    """``(hops, windows)`` of a View/Range query — the request's own
    (hop, window) grid in the EXACT order a serial columnar dispatch
    would emit it (hops ascending, the request's window order), shared
    by the batch packer and the member-side demux
    (``Job._emit_coalesced``). None for live queries and over-cap
    grids."""
    from .manager import RangeQuery, ViewQuery

    if isinstance(query, ViewQuery):
        hops = [int(query.timestamp)]
    elif isinstance(query, RangeQuery):
        # COUNT before materialising: this runs on the REST submit
        # thread for every request, and a hostile (start, end, jump)
        # span must be rejected arithmetically, not after allocating
        # the hop list
        n_hops = _range_hop_count(query)
        windows_n = (len(query.windows) if query.windows is not None
                     else 1)
        if not n_hops or n_hops * windows_n > MAX_REQUEST_CELLS:
            return None
        hops = list(range(int(query.start), int(query.end) + 1,
                          int(query.jump)))
    else:
        return None
    windows = list(query.windows) if query.windows is not None \
        else [query.window]
    if not hops or len(hops) * len(windows) > MAX_REQUEST_CELLS:
        return None
    return hops, windows


def _range_hop_count(query) -> int:
    start, end, jump = int(query.start), int(query.end), int(query.jump)
    if end < start or jump <= 0:
        return 0
    return (end - start) // jump + 1


def views_of(query) -> int:
    """View count a query will emit — the admission price multiplier.
    Computed ARITHMETICALLY (never via request_grid): the biggest
    requests are exactly the ones admission exists to price, so an
    over-cap range must be priced at its real view count, not fall
    through to 1. Live queries estimate a bounded number of runs (they
    are unbatchable and long-lived; admission prices their near-term
    cost, not eternity)."""
    from .manager import LiveQuery, RangeQuery, ViewQuery

    if isinstance(query, ViewQuery):
        return len(query.windows) if query.windows is not None else 1
    if isinstance(query, RangeQuery):
        w = len(query.windows) if query.windows is not None else 1
        return max(1, _range_hop_count(query) * w)
    if isinstance(query, LiveQuery):
        per_run = len(query.windows) if query.windows is not None else 1
        runs = query.max_runs if query.max_runs is not None else 20
        return per_run * max(1, min(int(runs), 20))
    return 1


class AdmissionDenied(Exception):
    """A request shed by admission control — ``jobs/rest.py`` maps it to
    HTTP 429 with a ``Retry-After`` header and the evidence body. NOT a
    ValueError subclass: the REST layer's 400 mapping must never
    swallow a shed into a client-error response."""

    def __init__(self, message: str, retry_after_s: float,
                 evidence: dict):
        super().__init__(message)
        self.retry_after_s = max(1.0, float(retry_after_s))
        self.evidence = dict(evidence)


class _Pending:
    """One job waiting in a collect window. The job's OWN thread blocks
    on ``done`` and performs all result emission; the scheduler thread
    only computes the shared arrays and hands them over — result/ledger
    ownership never crosses threads."""

    __slots__ = ("job", "grid", "enqueued", "deadline", "done",
                 "outcome", "payload")

    def __init__(self, job, grid):
        self.job = job
        self.grid = grid
        self.enqueued = _time.monotonic()
        self.deadline = job.deadline
        self.done = threading.Event()
        #: "ok" | "declined" | "expired" | "killed" — set before done
        self.outcome = None
        self.payload = None

    def finish(self, outcome: str, payload: dict | None = None) -> None:
        self.outcome = outcome
        self.payload = payload
        self.done.set()


class ServingScheduler:
    """Per-manager coalescing queue + process-shared admission state.

    One instance per ``AnalysisManager`` (one graph per manager, so the
    "same graph log" compatibility rule is structural); the admission
    counters, price book and metrics are per instance but surfaced
    process-wide via the weak instance registry."""

    def __init__(self, graph):
        self._graph = graph
        self._cond = threading.Condition(threading.Lock())
        #: family key -> [_Pending] in arrival order (bounded: queue_cap)
        self._queues: dict[tuple, list[_Pending]] = {}
        #: family key -> monotonic time its CURRENT window opened
        self._opened: dict[tuple, float] = {}
        #: family key -> batches IN FLIGHT: while nonzero the family's
        #: queue keeps collecting (backpressure grows the next batch
        #: under load — the amortisation the whole subsystem exists
        #: for) while other families dispatch concurrently; the gate is
        #: bounded by max_gate_ms so a member never waits a whole
        #: dispatch duration into the tail
        self._dispatching: dict[tuple, int] = {}
        self._thread: threading.Thread | None = None
        # admission state (same lock): ledger-priced cost admitted but
        # not yet completed, per-tenant live job counts (entries pop at
        # zero, so the table is bounded by the live-job cap)
        self._live_cost_s = 0.0
        self._live_jobs = 0
        self._tenant_live: dict[str, int] = {}
        #: algorithm label -> (ewma seconds per view, observations)
        self._prices: dict[str, tuple[float, int]] = {}
        self._stats = {"batches": 0, "coalesced_jobs": 0,
                       "deadline_expired": 0, "solo_passthrough": 0,
                       "queue_overflow_passthrough": 0,
                       "batch_declined": 0}
        self._shed: dict[str, int] = {}
        self._batch_sizes: dict[int, int] = {}
        self._san_tracker = _san_track("scheduler_queue")
        _INSTANCES.add(self)

    # ------------------------------------------------------- coalescing

    def _eligible(self, job):
        """(family, grid) when ``job`` may join a collect window, else
        None (pass through on today's path)."""
        if window_ms() <= 0.0:
            return None
        if job.mesh is not None or job.no_batch:
            return None
        if job.priority >= PRIORITY_BYPASS:
            return None
        fam = family_of(job.program)
        if fam is None:
            return None
        grid = request_grid(job.query)
        if grid is None:
            return None
        try:
            if self._graph.safe_time() < max(grid[0]):
                return None   # the cold path owns the fence wait
        except Exception:
            return None
        if job.deadline is not None:
            # never batch a tight-deadline job behind a collect window:
            # the worst queueing a batched job can see is the window
            # PLUS the backpressure gate (its family's in-flight
            # dispatch, bounded by max_gate_ms) — a deadline without
            # slack for BOTH must take the solo path, where it
            # dispatches the moment its thread runs
            slack = job.deadline - _time.monotonic()
            worst_queue_s = (2.0 * window_ms() + max_gate_ms()) / 1000.0
            if slack < worst_queue_s + 0.005:
                return None
        return fam, grid

    def offer(self, job) -> bool:
        """Enqueue ``job`` into its family's collect window; returns
        False (job passes through unbatched) for ineligible jobs and
        when the bounded queue is full — the scheduler sheds WORK into
        the solo path, never drops it."""
        elig = self._eligible(job)
        if elig is None:
            return False
        fam, grid = elig
        pend = _Pending(job, grid)
        with self._cond:
            _san_note(self._san_tracker, True)
            if sum(len(q) for q in self._queues.values()) >= queue_cap():
                self._stats["queue_overflow_passthrough"] += 1
                return False
            q = self._queues.get(fam)
            if q is None:
                q = self._queues[fam] = []
                self._opened[fam] = _time.monotonic()
            q.append(pend)
            self._ensure_thread_locked()
            self._cond.notify_all()
        job._coalesce = pend
        return True

    def _ensure_thread_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="sched-dispatch", daemon=True)
            self._thread.start()

    def _pop_due_locked(self, now: float):
        """(due batches, seconds until the next window closes)."""
        win_s = window_ms() / 1000.0
        gate_s = max_gate_ms() / 1000.0
        due, wait = [], None
        for key in list(self._queues):
            if self._dispatching.get(key):
                # this family's previous batch is still on the device:
                # keep collecting (the next batch grows — backpressure
                # IS the batching signal) UNTIL the oldest waiter has
                # been gated max_gate_ms — then spill and dispatch
                # concurrently, so gating never puts a whole dispatch
                # duration into the latency tail
                oldest = self._queues[key][0].enqueued
                spill_at = oldest + gate_s
                if now < spill_at:
                    left = spill_at - now
                    wait = left if wait is None else min(wait, left)
                    continue
            opened = self._opened.get(key, now)
            if (now >= opened + win_s
                    or len(self._queues[key]) >= MAX_BATCH_JOBS):
                due.append((key, self._queues.pop(key)))
                self._opened.pop(key, None)
                self._dispatching[key] = (
                    self._dispatching.get(key, 0) + 1)
            else:
                left = opened + win_s - now
                wait = left if wait is None else min(wait, left)
        return due, wait

    def _loop(self) -> None:
        idle_exit = max(1.0, 20.0 * window_ms() / 1000.0)
        while True:
            with self._cond:
                _san_note(self._san_tracker, True)
                due, wait = self._pop_due_locked(_time.monotonic())
                if not due:
                    if not self._queues:
                        # idle: wait for work, exit after the grace so
                        # short-lived managers never leak a thread
                        if not self._cond.wait(timeout=idle_exit) \
                                and not self._queues:
                            self._thread = None
                            return
                        continue
                    self._cond.wait(timeout=wait)
                    continue
            for key, pendings in due:   # OUTSIDE the lock (RT009)
                # one short-lived thread per batch: dispatching inline
                # would park the NEXT family's members behind this whole
                # device dispatch — a cross-family tail the off arm
                # doesn't have. Thread count is bounded by batches in
                # flight, each of which replaced >= 2 would-be job
                # dispatch threads, so this is strictly fewer threads
                # than the pre-scheduler path under the same load.
                threading.Thread(
                    target=self._dispatch_safe, args=(key, pendings),
                    name="sched-batch", daemon=True).start()

    def _dispatch_safe(self, key, pendings) -> None:
        try:
            self._dispatch(key, pendings)
        except Exception as e:   # a batch bug must not wedge
            _log.warning(        # every member forever
                "scheduler dispatch crashed (%s: %s) — members "
                "fall back to their own paths",
                type(e).__name__, e)
            for p in pendings:
                if not p.done.is_set():
                    p.finish("declined")
        finally:
            with self._cond:
                left = self._dispatching.get(key, 1) - 1
                if left > 0:
                    self._dispatching[key] = left
                else:
                    self._dispatching.pop(key, None)
                if self._queues:
                    # arrivals accumulated during the dispatch; the
                    # dispatcher may have idle-exited meanwhile
                    self._ensure_thread_locked()
                self._cond.notify_all()   # re-evaluate this family's
                # window: accumulated arrivals are (usually) already
                # past it and pop immediately

    def _requeue_front_locked(self, key, pendings) -> None:
        q = self._queues.get(key)
        if q is None:
            q = self._queues[key] = []
        q[0:0] = pendings
        # overflow members open an already-expired window: they dispatch
        # on the very next loop pass instead of waiting a fresh window.
        # The dispatcher may have idle-exited while this batch ran — the
        # requeue must restart it or these members would sit until the
        # next offer()
        self._opened[key] = _time.monotonic() - window_ms() / 1000.0
        self._ensure_thread_locked()

    def _dispatch(self, key, pendings) -> None:
        import numpy as np

        now = _time.monotonic()
        live = []
        for p in pendings:
            if p.job._kill.is_set():
                p.finish("killed")
            elif p.deadline is not None and now > p.deadline:
                # expired in queue: fail fast, never dispatch
                self._count("deadline_expired")
                METRICS.scheduler_deadline_expired.inc()
                TRACER.instant("sched.deadline", job_id=p.job.id,
                               waited_seconds=round(now - p.enqueued, 4))
                if _journal.enabled():
                    _journal.emit("sched", {
                        "decision": "deadline_expired",
                        "where": "queue", "job_id": p.job.id,
                        "waited_seconds": round(now - p.enqueued, 4)},
                        trace_id=getattr(p.job, "trace_id", None))
                p.finish("expired")
            else:
                live.append(p)
        if not live:
            return
        if len(live) == 1:
            # a window that collected one job has nothing to amortise:
            # decline so the solo path behaves exactly as pre-scheduler
            self._count("solo_passthrough")
            live[0].finish("declined")
            return
        # greedy pack under the column cap; overflow re-queues and
        # dispatches as the next batch immediately
        cap = max_cols()
        hop_set: set = set()
        win_set: set = set()
        take, rest = [], []
        from ..engine.device_sweep import normalize_windows
        from ..engine.hopbatch import stack_grids

        for p in live:
            ts, ws = p.grid
            nh = hop_set | {int(t) for t in ts}
            nw = win_set | set(normalize_windows(ws))
            if take and len(nh) * len(nw) > cap:
                rest.append(p)
                continue
            hop_set, win_set = nh, nw
            take.append(p)
        if rest:
            with self._cond:
                _san_note(self._san_tracker, True)
                self._requeue_front_locked(key, rest)
                self._cond.notify_all()
        if len(take) == 1:
            self._count("solo_passthrough")
            take[0].finish("declined")
            return

        grids = [p.grid for p in take]
        hops, wlist, cols = stack_grids(grids)
        total_cols = len(hops) * len(wlist)
        leader = take[0].job
        try:
            hb = leader._columnar_builder()
            # the same memory guards the solo columnar route applies —
            # an over-guard batch declines rather than misrouting
            if (hb.device_mask_bytes(total_cols) > 1 << 32
                    or hb.host_column_bytes(len(hops)) > 1 << 29):
                raise MemoryError("batch grid exceeds the columnar "
                                  "memory guards")
        except Exception as e:
            _log.info("coalesced dispatch declined (%s: %s) — %d members "
                      "take their own paths", type(e).__name__, e,
                      len(take))
            self._count("batch_declined")
            for p in take:
                p.finish("declined")
            return

        from .manager import _shell_from_fold

        shells: dict = {}

        def grab_shell(T, sw):
            shells[int(T)] = _shell_from_fold(hb.tables, sw, int(T))

        batch_id = f"batch_{next(_BATCH_IDS)}"
        fam_name = key[0]
        led = _ledger.Ledger(batch_id, fam_name)
        dispatch_started = _time.monotonic()
        t0 = _time.perf_counter()
        try:
            with TRACER.span("sched.dispatch", batch=batch_id,
                             family=fam_name, jobs=len(take),
                             hops=len(hops), windows=len(wlist),
                             cols=total_cols), \
                    _ledger.activate(led):
                # the sched.dispatch failpoint: an injected failure
                # rides the existing decline path — every member falls
                # back to its solo route, availability costs nothing
                _faults.fire("sched.dispatch")
                ranks, steps = hb.run(hops, wlist, chunks=1,
                                      hop_callback=grab_shell)
                ranks = np.asarray(ranks)
                steps = int(steps)
        except Exception as e:
            # a failed shared dispatch must cost availability nothing:
            # every member falls back to its own (pre-scheduler) path
            _log.warning("coalesced dispatch failed (%s: %s) — %d "
                         "members fall back to their own paths",
                         type(e).__name__, e, len(take))
            self._count("batch_declined")
            for p in take:
                p.finish("declined")
            return
        elapsed = _time.perf_counter() - t0
        METRICS.supersteps.inc(max(steps, 0))
        METRICS.scheduler_batches.labels(fam_name).inc()
        METRICS.scheduler_coalesced_jobs.observe(len(take))
        with self._cond:
            self._stats["batches"] += 1
            self._stats["coalesced_jobs"] += len(take)
            self._batch_sizes[len(take)] = (
                self._batch_sizes.get(len(take), 0) + 1)
        TRACER.instant(
            "sched.batch", batch=batch_id, family=fam_name,
            jobs=len(take), hops=len(hops), windows=len(wlist),
            cols=total_cols, elapsed_seconds=round(elapsed, 6),
            fold_seconds=round(float(hb.fold_seconds), 6))
        if _journal.enabled():
            _journal.emit("sched", {
                "decision": "batch", "batch": batch_id,
                "family": fam_name, "jobs": len(take),
                "cols": total_cols,
                "elapsed_seconds": round(elapsed, 6),
                "fold_seconds": round(float(hb.fold_seconds), 6)})
        snap = led.as_dict()
        fold_s = float(hb.fold_seconds)
        # a column REQUESTED BY SEVERAL members splits its cost among
        # them (identical concurrent requests are the headline case —
        # each must absorb 1/N of their shared column, not 100% of the
        # batch); cells nobody asked for are the coalescing overhead
        # and stay unattributed, so member shares sum to <= 1 exactly
        # as absorb_share's conservation contract promises
        requesters: dict[int, int] = {}
        for mycols in cols:
            for c in mycols:
                requesters[c] = requesters.get(c, 0) + 1
        for p, mycols in zip(take, cols):
            share = (sum(1.0 / requesters[c] for c in mycols)
                     / max(total_cols, 1))
            p.finish("ok", payload={
                "ranks": ranks, "steps": steps, "shells": shells,
                "cols": mycols, "elapsed": elapsed,
                "fold_seconds": fold_s, "share": share,
                "total_cols": total_cols,
                "dispatch_started": dispatch_started,
                "snap": snap,
                "batch": {"batch_id": batch_id, "family": fam_name,
                          "jobs": len(take), "columns": len(mycols),
                          "total_columns": total_cols,
                          "share": round(share, 4)},
            })

    def _count(self, what: str, n: int = 1) -> None:
        with self._cond:
            self._stats[what] = self._stats.get(what, 0) + n

    # -------------------------------------------------------- admission

    def price(self, program, query) -> float:
        """Estimated cost seconds of a request: its view count × the
        algorithm's EWMA seconds-per-view from completed-job history
        (``DEFAULT_PRICE_S`` before any history exists). Live
        subscriptions price from the ``live:`` book when the epoch
        engine has fed it — an incremental epoch costs O(delta), not
        the O(m) a one-shot view of the same algorithm implies, so the
        admission book must not overcharge standing subscriptions."""
        from .manager import LiveQuery

        alg = getattr(program, "cost_label", type(program).__name__)
        views = views_of(query)
        with self._cond:
            per = self._prices.get(alg, (DEFAULT_PRICE_S, 0))[0]
            if isinstance(query, LiveQuery):
                live = self._prices.get(f"live:{alg}")
                if live is not None:
                    per = live[0]
        return views * per

    def note_live_epoch(self, algorithm: str, seconds: float) -> None:
        """One live epoch served in ``seconds``: EWMA it into the
        ``live:<algorithm>`` price-book key so admission prices standing
        subscriptions from measured epoch cost rather than the one-shot
        view price (same 0.7/0.3 fold as ``complete()``)."""
        alg = f"live:{algorithm}"
        per = max(0.0, float(seconds))
        with self._cond:
            _san_note(self._san_tracker, True)
            prev = self._prices.get(alg)
            if prev is None:
                if len(self._prices) >= MAX_PRICE_KEYS:
                    return   # bounded book (RT011)
                self._prices[alg] = (per, 1)
            else:
                ewma, n = prev
                self._prices[alg] = (0.7 * ewma + 0.3 * per, n + 1)

    def admit(self, program, query, tenant: str,
              deadline_ms=None) -> float:
        """Price the request and either register its cost into the live
        backlog (returns the estimate — the caller must ``complete()``
        or ``cancel()`` it) or shed it with :class:`AdmissionDenied`.
        With ``RTPU_ADMISSION`` off the backlog is still tracked (so
        flipping admission on mid-run starts with honest state) but
        nothing is ever shed."""
        est = self.price(program, query)
        tenant = _workload.normalize_tenant(tenant)
        shed = None
        if admission_enabled():
            # budget/workload reads take their own locks: OUTSIDE ours
            burning_top = None
            try:
                if _budget.BUDGET.status_block()["grade"] == "burning":
                    top = _workload.WORKLOAD.top_by_cost(1)
                    if top:
                        burning_top = top[0]["tenant"]
            except Exception:
                burning_top = None
            budget_s = admission_budget_s()
            cap = admission_max_jobs()
            t_cap = max(1, int(cap * tenant_share()))
            # decide AND register in ONE critical section (the decision
            # is pure arithmetic on our own counters): a burst of K
            # concurrent submits must not all read depth = cap-1 and
            # register together past every advertised bound — the burst
            # is exactly when admission matters
            with self._cond:
                _san_note(self._san_tracker, True)
                depth = self._live_jobs
                backlog = self._live_cost_s
                t_live = self._tenant_live.get(tenant, 0)
                if depth >= cap:
                    shed = ("queue_full",
                            f"{depth} admitted jobs >= cap {cap}",
                            max(1.0, backlog / max(depth, 1) * 4))
                elif t_live >= t_cap:
                    shed = ("tenant_share",
                            f"tenant {tenant!r} holds {t_live} live "
                            f"jobs >= its bounded share {t_cap}",
                            max(1.0, backlog / max(depth, 1) * 2))
                elif burning_top is not None and tenant == burning_top:
                    # the advisor's queue-burn-shed-top-tenant finding,
                    # actuated: while some error budget burns, the
                    # top-cost tenant's NEW work is shed until it drops
                    shed = ("shed_top_tenant",
                            f"SLO error budget burning and tenant "
                            f"{tenant!r} holds the top attributed cost",
                            max(2.0, min(30.0, backlog)))
                elif backlog + est > budget_s:
                    shed = ("over_budget",
                            f"priced backlog {backlog:.2f}s + this "
                            f"request {est:.2f}s exceeds "
                            f"RTPU_ADMISSION_BUDGET_S={budget_s:g}",
                            math.ceil(max(1.0, backlog + est - budget_s)))
                elif (deadline_ms is not None
                      and backlog + est > float(deadline_ms) / 1000.0):
                    shed = ("deadline_infeasible",
                            f"deadline_ms={deadline_ms:g} cannot be "
                            f"met: projected wait {backlog:.2f}s + cost "
                            f"{est:.2f}s already exceeds it",
                            math.ceil(max(1.0, backlog)))
                if shed is None:
                    self._live_cost_s += est
                    self._live_jobs += 1
                    self._tenant_live[tenant] = t_live + 1
                else:
                    self._shed[shed[0]] = self._shed.get(shed[0], 0) + 1
            if shed is not None:
                reason, why, retry_after = shed
                evidence = {
                    "reason": reason, "tenant": tenant,
                    "queue_depth": depth,
                    "backlog_seconds": round(backlog, 3),
                    "priced_cost_seconds": round(est, 4),
                    "budget_seconds": budget_s,
                    "retry_after_s": float(retry_after),
                }
                if deadline_ms is not None:
                    evidence["deadline_ms"] = float(deadline_ms)
                if burning_top is not None:
                    evidence["burning_top_tenant"] = burning_top
                METRICS.scheduler_shed.labels(reason).inc()
                TRACER.instant("sched.shed", reason=reason,
                               tenant=tenant, queue_depth=depth,
                               backlog_seconds=round(backlog, 3),
                               priced_cost_seconds=round(est, 4))
                if _journal.enabled():
                    _journal.emit("sched", dict(
                        evidence, decision="shed"), tenant=tenant)
                raise AdmissionDenied(f"admission shed ({reason}): {why}",
                                      retry_after, evidence)
            return est
        with self._cond:
            _san_note(self._san_tracker, True)
            self._live_cost_s += est
            self._live_jobs += 1
            self._tenant_live[tenant] = (
                self._tenant_live.get(tenant, 0) + 1)
        return est

    def cancel(self, est: float, tenant: str) -> None:
        """Roll back a registered admission when job creation failed
        after ``admit()`` succeeded."""
        self._release(est, _workload.normalize_tenant(tenant))

    def _release(self, est, tenant: str) -> None:
        with self._cond:
            _san_note(self._san_tracker, True)
            if est is not None:
                self._live_cost_s = max(0.0, self._live_cost_s - est)
            self._live_jobs = max(0, self._live_jobs - 1)
            left = self._tenant_live.get(tenant, 0) - 1
            if left > 0:
                self._tenant_live[tenant] = left
            else:
                self._tenant_live.pop(tenant, None)

    def complete(self, job) -> None:
        """Completion hook (``Job._publish_ledger``): release the job's
        admitted cost and fold its measured cost into the price book."""
        est = getattr(job, "_admitted_cost_s", None)
        if est is None:
            return
        job._admitted_cost_s = None
        self._release(est, job.tenant)
        led = job.ledger
        if led.status != "done" or led.views <= 0:
            # only SUCCESSFUL jobs price the book: an expired-in-queue
            # burst (views=0, seconds~0) would EWMA the price toward 0
            # and silently disable shedding exactly under overload,
            # while a mid-dispatch failure would record its sunk cost
            # against zero views and 429 healthy traffic
            return
        # price from the job's ATTRIBUTED work (its column share of a
        # coalesced dispatch via absorb_share, its own phases solo) —
        # never from member wall clock, which includes collect-window
        # and gate waits: pricing queueing into the book would make
        # load inflate prices inflate shedding, a positive feedback
        # loop exactly where admission must stay calm
        with led._lock:
            ph = dict(led.phase_seconds)
        seconds = max(0.0, sum(ph.values()) - ph.get("sched_wait", 0.0)
                      - ph.get("other", 0.0))
        views = max(1, led.views)
        alg = led.algorithm or "unknown"
        per = seconds / views
        with self._cond:
            _san_note(self._san_tracker, True)
            prev = self._prices.get(alg)
            if prev is None:
                if len(self._prices) >= MAX_PRICE_KEYS:
                    return   # bounded book: dynamic programs can't grow it
                self._prices[alg] = (per, 1)
            else:
                ewma, n = prev
                self._prices[alg] = (0.7 * ewma + 0.3 * per, n + 1)

    # --------------------------------------------------------- surfaces

    def queue_depth(self) -> int:
        with self._cond:
            _san_note(self._san_tracker, False)
            return sum(len(q) for q in self._queues.values())

    def backlog_seconds(self) -> float:
        with self._cond:
            _san_note(self._san_tracker, False)
            return self._live_cost_s

    def status_block(self) -> dict:
        """The ``scheduler`` block of /statusz (and the CI failure
        artifact): queue depth by class, batches formed, the
        coalesced-jobs histogram, shed/deadline counters, the admission
        state and the price book."""
        with self._cond:
            _san_note(self._san_tracker, False)
            by_class: dict[str, int] = {}
            for k, q in self._queues.items():
                # aggregate by family NAME: two parameterisations of
                # one algorithm are distinct batch keys but one class
                by_class[k[0]] = by_class.get(k[0], 0) + len(q)
            stats = dict(self._stats)
            shed = dict(self._shed)
            sizes = {str(k): v
                     for k, v in sorted(self._batch_sizes.items())}
            live_jobs = self._live_jobs
            backlog = self._live_cost_s
            tenants = dict(self._tenant_live)
            prices = {a: round(p, 6)
                      for a, (p, _) in self._prices.items()}
        return {
            "enabled": window_ms() > 0.0,
            "window_ms": window_ms(),
            "admission": admission_enabled(),
            "queue_depth": sum(by_class.values()),
            "queue_by_class": by_class,
            "batches_formed": stats["batches"],
            "jobs_coalesced": stats["coalesced_jobs"],
            "coalesced_jobs_hist": sizes,
            "solo_passthrough": stats["solo_passthrough"],
            "batch_declined": stats["batch_declined"],
            "queue_overflow_passthrough":
                stats["queue_overflow_passthrough"],
            "deadline_expired": stats["deadline_expired"],
            "shed": shed,
            "admitted_live_jobs": live_jobs,
            "backlog_seconds": round(backlog, 3),
            "tenant_live_jobs": tenants,
            "prices_seconds_per_view": prices,
            "caps": {"queue": queue_cap(),
                     "admitted_jobs": admission_max_jobs(),
                     "budget_seconds": admission_budget_s(),
                     "tenant_share": tenant_share(),
                     "batch_cols": max_cols(),
                     "max_gate_ms": max_gate_ms()},
        }


    def clear_stats(self) -> None:
        """Reset the counter tables (tests, operator resets). Their key
        spaces are small by construction — ``_stats`` a fixed literal
        set, ``_shed`` the five admission reasons, ``_batch_sizes`` at
        most MAX_BATCH_JOBS distinct sizes — and this reset is the
        explicit shrink that keeps a long-lived server's scheduler
        state restartable without a process bounce. The learned
        admission price book is deliberately NOT cleared: resetting
        counters must never revert pricing to the cold default."""
        with self._cond:
            self._stats = {"batches": 0, "coalesced_jobs": 0,
                           "deadline_expired": 0, "solo_passthrough": 0,
                           "queue_overflow_passthrough": 0,
                           "batch_declined": 0}
            self._shed = {}
            self._batch_sizes = {}


# -------------------------------------------------- process-wide helpers


def note_deadline_expired(job) -> None:
    """Count a deadline that expired before the job thread ever
    dispatched (the non-batched twin of the scheduler-queue expiry)."""
    METRICS.scheduler_deadline_expired.inc()
    TRACER.instant("sched.deadline", job_id=job.id, where="job_start")
    if _journal.enabled():
        _journal.emit("sched", {
            "decision": "deadline_expired", "where": "job_start",
            "job_id": job.id},
            trace_id=getattr(job, "trace_id", None))
    sched = getattr(job, "_sched", None)
    if sched is not None:
        sched._count("deadline_expired")


def total_queue_depth() -> float:
    """Sum over live schedulers — the Prometheus gauge callback."""
    return float(sum(s.queue_depth() for s in list(_INSTANCES)))


def total_backlog_seconds() -> float:
    return float(sum(s.backlog_seconds() for s in list(_INSTANCES)))


def schedulerz() -> dict:
    """Every live scheduler's status block — the RTPU_SCHED_DUMP
    document (tier-1 failure artifact, next to the flight recorder)."""
    return {"schedulers": [s.status_block() for s in list(_INSTANCES)]}


_sched_dump = os.environ.get("RTPU_SCHED_DUMP")
if _sched_dump:
    import json as _json

    from ..obs import exitdump as _exitdump

    def _dump_sched(path=_sched_dump):
        with open(path, "w") as f:
            _json.dump(schedulerz(), f, indent=1)

    _exitdump.register("sched", _dump_sched)
