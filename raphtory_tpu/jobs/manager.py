"""Job orchestration: Live/View/Range analysis × window variants.

The reference spawns 1-of-9 ``AnalysisTask`` actors per request —
{Live, View, Range} × {plain, windowed, batch-windowed}
(``AnalysisManager.scala:72-167``, ``Tasks/``) — each driving the actor BSP
handshake per timestamp. Here a job is a host thread sweeping timestamps and
invoking the compiled engine; the 9-way matrix collapses into one loop with
a window parameter, and the per-hop handshake disappears (compiled runner +
snapshot cache are reused across hops).
"""

from __future__ import annotations

import itertools
import os
import threading
import time as _time
import traceback
from dataclasses import dataclass

from ..analysis.sanitizer import (note_shared as _san_note,
                                  track_shared as _san_track)
from ..core.service import TemporalGraph
from ..engine import bsp
from ..engine.program import VertexProgram
from ..obs import advisor as _advisor
from ..obs import freshness as _fresh
from ..obs import journal as _journal
from ..obs import ledger as _ledger
from ..obs import slo as _slo
from ..obs import workload as _workload
from ..obs.metrics import METRICS
from ..obs.trace import TRACER, block_steps as _block_steps
from ..resilience import degrade as _degrade
from ..resilience.policy import default_classify as _transient

import logging

_jobs_log = logging.getLogger(__name__)


@dataclass(frozen=True)
class ViewQuery:
    """One timestamp (ViewAnalysisTask)."""
    timestamp: int
    window: int | None = None
    windows: tuple | None = None


@dataclass(frozen=True)
class RangeQuery:
    """Timestamp sweep start..end step jump (RangeAnalysisTask.scala:18-35)."""
    start: int
    end: int
    jump: int
    window: int | None = None
    windows: tuple | None = None

    def __post_init__(self):
        if int(self.jump) <= 0:
            # jump=0 would spin every sweep loop forever (REST bodies pass
            # raw ints straight through) — refuse at construction
            raise ValueError(f"jump must be positive, got {self.jump}")


@dataclass(frozen=True)
class LiveQuery:
    """Repeating analysis at the moving watermark (LiveAnalysisTask).
    event_time=False: re-run every repeat seconds of processing time;
    event_time=True: advance the target timestamp by `repeat` event-time
    units and wait for the watermark (LiveAnalysisTask.scala:34-52)."""
    repeat: float = 1.0
    event_time: bool = False
    max_runs: int | None = None   # None = until killed
    window: int | None = None
    windows: tuple | None = None


Query = ViewQuery | RangeQuery | LiveQuery


class Job:
    def __init__(self, job_id: str, program: VertexProgram, query: Query,
                 graph: TemporalGraph, mesh=None, wait_timeout: float = 30.0,
                 explain: bool = False, tenant: str | None = None,
                 deadline_ms=None, priority: int = 0,
                 no_batch: bool = False):
        self.id = job_id
        self.program = program
        self.query = query
        self.graph = graph
        self.mesh = mesh
        self.wait_timeout = wait_timeout
        #: client deadline (jobs/scheduler.py): absolute monotonic
        #: seconds, or None. An expired-in-queue job fails fast with
        #: status "expired" and never dispatches.
        self.deadline_ms = None if deadline_ms is None else float(deadline_ms)
        self.deadline = (None if deadline_ms is None
                         else _time.monotonic() + float(deadline_ms) / 1e3)
        if self.deadline_ms is not None and not self.deadline_ms > 0:
            raise ValueError(
                f"deadline_ms must be positive, got {deadline_ms!r}")
        #: >= scheduler.PRIORITY_BYPASS skips the coalescing collect
        #: window entirely (latency over throughput)
        self.priority = int(priority or 0)
        #: per-request coalescing opt-out (REST `batch: false`)
        self.no_batch = bool(no_batch)
        #: _Pending handle while waiting in a scheduler collect window
        self._coalesce = None
        #: the manager's ServingScheduler (admission/price hooks); None
        #: for directly-constructed jobs
        self._sched = None
        self._admitted_cost_s = None
        #: per-query resource ledger — always collected (cheap dict
        #: accounting); ``explain`` additionally returns it with the
        #: results over REST (obs/ledger.py)
        self.explain = bool(explain)
        self.ledger = _ledger.Ledger(
            job_id, getattr(program, "cost_label", type(program).__name__))
        #: normalized tenant identity (obs/workload.py): the account this
        #: job's closed ledger rolls into. Normalization NEVER raises —
        #: a malformed tenant header must not fail the request it rode
        self.tenant = _workload.normalize_tenant(tenant)
        self.ledger.tenant = self.tenant
        # trace-context handoff: a Job is constructed on the SUBMITTING
        # thread (the REST handler's rest.request span is still open),
        # and the job thread adopts this context in _run — so one REST
        # request and its job share one trace id end to end. None when
        # tracing is off or nothing is open (adopt degrades to a no-op).
        self._trace_ctx = TRACER.capture()
        #: trace id of this job's `job` span once it runs (None untraced)
        #: — the SLO exemplar and the /AnalysisResults correlation key
        self.trace_id: str | None = None
        self._submitted = _time.perf_counter()
        # ResultSink | None — attached by AnalysisManager.submit (the only
        # path, so every sink went through the path jail + in-use check)
        self.sink = None
        self.results: list[dict] = []
        # live jobs emit forever; an uncapped result list is the classic
        # serving slow leak (rtpulint RT011). Oldest rows roll off past
        # the cap — the sink (file) keeps the full history, the REST
        # surface reports how many rolled off. 0 disables. The trim
        # SHRINKS the list, so readers must take results_snapshot()
        # under the same lock (append-only was prefix-safe to iterate;
        # a shrink mid-serialization is not).
        self._results_cap = max(
            0, int(os.environ.get("RTPU_RESULT_ROWS", 10_000)))
        self._results_mu = threading.Lock()
        self.results_dropped = 0
        self.status = "pending"
        self.error: str | None = None
        #: degraded serving (resilience/degrade.py): a range sweep whose
        #: deadline or retry budget expired MID-sweep ships the hops it
        #: covered, status "done", with these three fields telling the
        #: client exactly how much of the range the answer covers
        self.degraded = False
        self.covered_time: int | None = None
        self.degraded_reason: str | None = None
        self._kill = threading.Event()
        self._thread: threading.Thread | None = None
        self._done = threading.Event()

    # ---- lifecycle ----

    def start(self) -> "Job":
        self._thread = threading.Thread(
            target=self._run, name=f"job-{self.id}", daemon=True)
        self.status = "running"
        self._thread.start()
        return self

    def kill(self) -> None:
        self._kill.set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._done.wait(timeout)

    def results_snapshot(self) -> list[dict]:
        """Stable copy of the result rows for readers on other threads —
        the cap trim shrinks the live list, so serializing it directly
        would race the job thread."""
        with self._results_mu:
            return list(self.results)

    # ---- execution ----

    def _run(self) -> None:
        METRICS.jobs_started.labels(type(self.query).__name__).inc()
        # queue wait = submit → job thread actually running (today that is
        # thread-spawn latency; an admission-controlled scheduler will put
        # real queueing here, and the ledger field is where it shows up)
        self.ledger.queue_wait_seconds = max(
            0.0, _time.perf_counter() - self._submitted)
        try:
            with TRACER.adopt(self._trace_ctx), \
                    TRACER.span("job", job_id=self.id,
                                kind=type(self.query).__name__,
                                program=type(self.program).__name__) as jsp, \
                    _ledger.activate(self.ledger):
                self.trace_id = jsp.trace or None
                self.ledger.trace_id = self.trace_id or ""
                self._run_query()
                jsp.set(status=self.status)
            # wall is submit → done, so it CONTAINS the queue wait and
            # finish()'s residual (wall - queue_wait - phases) is exactly
            # the unattributed run time — the queue_wait + Σphases ==
            # wall invariant holds even once real admission queueing
            # exists
            self._publish_ledger(_time.perf_counter() - self._submitted)
        finally:
            # _done fires LAST: a waiter woken by wait() must observe the
            # published SLO/exemplar/queue-wait/ledger state — publishing
            # after the wakeup raced every /slz-after-wait reader
            self._done.set()

    def _publish_ledger(self, wall_seconds: float) -> None:
        """Close the job's ledger and fan it out: per-algorithm
        ``raphtory_query_cost_*`` metrics, the /costz recent-query ring,
        and a ``ledger.query`` flight-recorder instant. With
        ``RTPU_LEDGER=0`` the ledger closes quietly (explain still shows
        the jobs-layer timings) but publishes NOTHING — disabling
        collection must silence every ledger surface, not just the
        engine-side hooks."""
        led = self.ledger
        led.finish(wall_seconds, status=self.status)
        # SLO surface (obs/slo.py): end-to-end latency + per-phase
        # seconds into the exemplar histograms, keyed by this job's
        # trace id so a p99 bucket resolves to an actual trace. Fed from
        # the JOBS-layer timings, which RTPU_LEDGER=0 still collects —
        # the SLO histograms have their own knob (RTPU_SLO), because the
        # serving SLO must survive turning cost accounting off. The
        # queue-wait distribution ships alongside (measured since PR 6,
        # never exported as a histogram until now).
        alg = led.algorithm or "unknown"
        if self.status == "done":
            # only SUCCESSFUL jobs land in the latency SLI: a burst of
            # fast failures would otherwise IMPROVE p99 while the service
            # errors, and a minutes-late kill would inflate the tail for
            # healthy traffic. Error/kill RATES live in
            # jobs_completed_total{status}; their latency is not an SLO.
            _slo.SLO.observe(alg, "e2e", led.wall_seconds,
                             trace_id=self.trace_id)
            for ph, sec in dict(led.phase_seconds).items():
                _slo.SLO.observe(alg, ph, sec, trace_id=self.trace_id)
        # queue wait is an ADMISSION signal, valid whatever the outcome
        METRICS.job_queue_wait_seconds.observe(led.queue_wait_seconds)
        # per-tenant workload account (obs/workload.py): its own knob
        # (RTPU_WORKLOAD), independent of RTPU_LEDGER — the jobs-layer
        # phase timings above are collected either way, and attribution
        # must survive turning the engine-side cost harvest off
        _workload.WORKLOAD.record(led, status=self.status)
        # advisor evidence ring (obs/advisor.py): jobs-layer data that,
        # like the SLO and workload surfaces above, must survive
        # RTPU_LEDGER=0 — otherwise every query-windowed rule silently
        # goes inert in a supported config. Gated on the advisor's own
        # knob so the bench off-arm pays nothing.
        if _advisor.enabled():
            _advisor.note_query(led.as_dict())
        # durable journal (obs/journal.py): every COMPLETED query's
        # ledger lands on disk — like the SLO/workload surfaces this
        # survives RTPU_LEDGER=0 (the jobs-layer timings are collected
        # either way), so a postmortem can always price the final sweep
        if _journal.enabled():
            snap_j = led.as_dict()
            snap_j["job_id"] = self.id
            snap_j["status"] = self.status
            _journal.emit("ledger", snap_j, trace_id=self.trace_id,
                          tenant=led.tenant or None)
        # serving-scheduler completion hook (jobs/scheduler.py): release
        # this job's admitted cost from the live backlog and fold its
        # measured seconds-per-view into the admission price book —
        # always, whatever the outcome (an admitted job that failed
        # still left the backlog)
        if self._sched is not None:
            self._sched.complete(self)
        if not _ledger.collection_enabled():
            return
        METRICS.query_cost_queries.labels(alg, led.bound()).inc()
        METRICS.query_cost_seconds.labels(alg, "queue_wait").observe(
            led.queue_wait_seconds)
        snap = led.as_dict()
        for ph, sec in snap["phase_seconds"].items():
            METRICS.query_cost_seconds.labels(alg, ph).observe(sec)
        METRICS.query_cost_est_flops.labels(alg).inc(
            snap["device"]["est_flops"])
        METRICS.query_cost_est_hbm_bytes.labels(alg).inc(
            snap["device"]["est_bytes_accessed"])
        METRICS.query_cost_h2d_bytes.labels(alg).inc(snap["h2d"]["bytes"])
        if snap["dcn"]["bytes"]:
            METRICS.query_cost_dcn_bytes.labels(alg).inc(
                snap["dcn"]["bytes"])
        _ledger.note_completed(led)

    def _run_query(self) -> None:
        try:
            q = self.query
            if self.deadline is not None \
                    and _time.monotonic() > self.deadline:
                # fail fast BEFORE any dispatch: the client has already
                # given up on this answer (jobs/scheduler.py deadlines)
                self.status = "expired"
                self.error = (f"DeadlineExpired: deadline_ms="
                              f"{self.deadline_ms:g} passed before the "
                              "job dispatched")
                if self._coalesce is None:
                    # a queued job's expiry is counted ONCE, by the
                    # scheduler at batch formation — counting here too
                    # would report one expired request as two
                    from . import scheduler as _sched

                    _sched.note_deadline_expired(self)
                return
            if self._coalesce is not None and self._run_coalesced(q):
                return   # status set by the coalesced path
            self._coalesce = None   # declined/timed out: own path
            if isinstance(q, ViewQuery):
                self._run_at(q.timestamp, q)
            elif isinstance(q, RangeQuery):
                # When the whole range is already safe, sweep incrementally
                # (delta-applied snapshots, core/sweep.py) instead of
                # re-folding the log per hop; otherwise hop-by-hop behind the
                # watermark fence like the reference (RangeAnalysisTask).
                # Qualifying programs take the amortised engines: on a mesh
                # the static global-space partition (parallel/sweep.py), on
                # one device the device-resident sweep (engine/device_sweep)
                # — fold state stays on the chip, hops ship O(delta) bytes.
                if not (self._try_range_mesh_columns(q)
                        or self._try_range_mesh(q)
                        or self._try_range_hopbatch(q)
                        or self._try_range_device(q)):
                    sweep = None
                    if self.graph.safe_time() >= q.end:
                        from ..core.sweep import SweepBuilder

                        sweep = SweepBuilder(
                            self.graph.log,
                            include_occurrences=self.program.needs_occurrences)
                    t = q.start
                    while t <= q.end and not self._kill.is_set():
                        self._run_at(t, q, sweep=sweep)
                        t += q.jump
            elif isinstance(q, LiveQuery):
                self._run_live(q)
            self.status = "done" if not self._kill.is_set() else "killed"
        except Exception as e:  # job errors surface via status, like the
            self.status = "failed"  # reference's per-phase catches
            self.error = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
        finally:
            if self.sink is not None:
                self.sink.close()   # flush partial output on kill/failure too
            METRICS.jobs_completed.labels(self.status).inc()
            # _done is set by _run AFTER _publish_ledger — wait()
            # returning guarantees the telemetry has landed

    def _run_live(self, q: LiveQuery) -> None:
        """The live loop is a thin pacer over the epoch engine
        (jobs/live.LiveEpochState): each iteration computes the target
        timestamp exactly as before, then lets the epoch engine decide
        HOW to serve it — incremental delta fold over the standing
        columnar engine, full re-sweep fallback, or (wall mode, nothing
        moved) a skip. Emission, freshness and pricing all happen
        inside ``epoch()``; the wall-mode wait adapts to the staleness
        budget (``next_wait``)."""
        from .live import LiveEpochState

        live = LiveEpochState(self)
        runs = 0
        t_target = None
        while not self._kill.is_set():
            if q.event_time:
                if t_target is None:
                    t_target = min(self.graph.safe_time(),
                                   self.graph.latest_time)
                else:
                    # advance in event time and wait for the watermark to
                    # catch up (never clamped back: LiveAnalysisTask.scala:
                    # 34-52 event-time mode); sub-1 repeats still advance
                    t_target += max(1, int(q.repeat))
                # condition-variable fence wait (chunked so kill() still
                # interrupts promptly even with no watermark traffic)
                deadline = _time.monotonic() + self.wait_timeout
                while (not self._kill.is_set()
                       and _time.monotonic() < deadline
                       and not self.graph.watermarks.wait_for(
                           t_target,
                           timeout=min(0.5, max(
                               0.0, deadline - _time.monotonic())))):
                    pass
                t = t_target
            else:
                t = min(self.graph.safe_time(), self.graph.latest_time)
            live.epoch(q, int(t))
            runs += 1
            if q.max_runs is not None and runs >= q.max_runs:
                break
            if q.event_time:
                # all sources finished and the target has passed the end of
                # history: nothing new can ever arrive — finish rather than
                # busy-spin past the end of the stream (unless the caller
                # asked for an exact number of runs)
                if (q.max_runs is None
                        and self.graph.watermarks.safe_time() >= 2**62
                        and t_target >= self.graph.latest_time):
                    break
            else:
                self._kill.wait(live.next_wait(q))

    def _run_coalesced(self, q) -> bool:
        """Wait on this job's scheduler collect-window handle and, when
        the batch dispatched, demux + emit THIS job's columns on THIS
        thread (result/ledger ownership never crosses threads). Returns
        False when the scheduler declined (solo window, incompatible
        pack, failed dispatch) — the caller falls through to the normal
        per-job routes, so coalescing can only ever ADD latency equal to
        the collect window, never lose a request."""
        pend = self._coalesce
        limit = max(float(self.wait_timeout), 600.0)
        w0 = _time.monotonic()
        while not pend.done.wait(0.05):
            if self._kill.is_set():
                self.status = "killed"
                return True
            if _time.monotonic() - w0 > limit:
                _jobs_log.warning(
                    "coalesced wait timed out for %s after %.0fs — "
                    "falling back to the solo path", self.id, limit)
                return False
        if pend.outcome == "declined":
            return False
        if pend.outcome == "killed":
            self.status = "killed"
            return True
        if pend.outcome == "expired":
            self.status = "expired"
            self.error = (f"DeadlineExpired: deadline_ms="
                          f"{self.deadline_ms:g} expired in the "
                          "scheduler queue (never dispatched)")
            return True
        pay = pend.payload
        # collect-window queueing the scheduler ADDED, measured from
        # THIS THREAD's wait start (w0) — not pend.enqueued, which
        # predates the thread and overlaps queue_wait_seconds; the
        # dispatch itself is attributed by column share via
        # absorb_share, so queue_wait + sched_wait + phases never
        # double-count an interval
        self.ledger.add_phase("sched_wait", max(
            0.0, pay["dispatch_started"] - w0))
        self.ledger.absorb_share(pay["snap"], pay["share"],
                                 coalesced=pay["batch"])
        self._emit_coalesced(pend.grid, pay)
        self.status = "done" if not self._kill.is_set() else "killed"
        return True

    def _emit_coalesced(self, grid, pay) -> None:
        """Emit this job's result rows from a shared batch dispatch:
        ``grid`` is the SAME (hops, windows) tuple the scheduler packed
        this job's columns from (``pend.grid`` — never re-derived, so
        the demux can't drift from the packing), in serial emission
        order. ``viewTime`` is the amortised per-column share of the
        batch dispatch — the same rule ``_emit_columnar`` applies
        within one job's sweep, extended across requests."""
        hops, windows = grid
        ranks, steps = pay["ranks"], int(pay["steps"])
        shells, cols = pay["shells"], pay["cols"]
        per_row = pay["elapsed"] / max(pay["total_cols"], 1)
        for _ in hops:
            METRICS.snapshot_build_seconds.observe(
                pay["fold_seconds"] * pay["share"] / max(len(hops), 1))
        self.ledger.count_supersteps(steps)
        i = 0
        for T in sorted({int(t) for t in hops}):
            for w in windows:
                if self._kill.is_set():
                    return
                self._emit(T, w, ranks[cols[i]], shells[int(T)], steps,
                           _time.perf_counter() - per_row)
                i += 1

    def _device_engine_ok(self) -> bool:
        """Shared eligibility gate for the device-resident engines (warm
        View, single-device Range, mesh Range): the program must run
        without occurrences/property joins (``device_sweep.supported``)
        and its reduce must accept the vertex-side shell view."""
        from ..engine.device_sweep import supported

        if not supported(self.program):
            return False
        return (type(self.program).reduce is VertexProgram.reduce
                or self.program.reduce_shell_safe)

    def _try_range_mesh(self, q: RangeQuery) -> bool:
        """Amortised mesh range sweep: one static partition for the whole
        range, per-hop O(delta) updates, hop i+1's host fold overlapped with
        hop i's device supersteps (``sharded.run(block=False)``). Returns
        False when the query/program must use the per-hop path."""
        if self.mesh is None or self.graph.safe_time() < q.end:
            return False
        from ..parallel import sharded as _sh
        from ..parallel.sweep import ShardedSweep

        if not self._device_engine_ok():
            return False
        try:
            sweep = ShardedSweep(self.graph.log,
                                 self.mesh.shape[_sh.V_AXIS])
        except ValueError:
            return False  # e.g. shard count does not divide the global pad

        def run(windows):
            return sweep.run(self.program, mesh=self.mesh, window=q.window,
                             windows=windows, block=False)

        self._range_amortised(q, sweep.advance, run, sweep.reduce_view)
        return True

    def _columnar_builder(self):
        """Construct the hop-batched columnar engine for this job's
        program (raises for programs without one — the caller treats any
        failure as \'route declined\'). PageRank: finalize is the raw rank
        vector and the power iteration warm-starts safely. CC: labels are
        global padded indices in both engines. SSSP/BFS: the columnar
        distances are exactly finalize's output; weighted traversal folds
        per-hop weight columns (immutable weight keys raise)."""
        from ..algorithms import ConnectedComponents as _CC
        from ..algorithms import PageRank as _PR
        from ..algorithms.traversal import SSSP as _SSSP
        from ..engine.hopbatch import (HopBatchedBFS, HopBatchedCC,
                                       HopBatchedPageRank, HopBatchedSSSP)

        p = self.program
        if type(p) is _PR:
            return HopBatchedPageRank(self.graph.log, damping=p.damping,
                                      tol=p.tol, max_steps=p.max_steps)
        if type(p) is _CC:
            return HopBatchedCC(self.graph.log, max_steps=p.max_steps)
        if type(p) is _SSSP:
            if p.weight_prop:
                return HopBatchedSSSP(self.graph.log, p.seeds,
                                      p.weight_prop, directed=p.directed,
                                      max_steps=p.max_steps)
            return HopBatchedBFS(self.graph.log, p.seeds,
                                 directed=p.directed,
                                 max_steps=p.max_steps)
        raise TypeError(f"no columnar engine for {type(p).__name__}")

    def _columnar_range_prep(self, q: RangeQuery):
        """Shared eligibility + construction for the columnar range routes
        (single-device hopbatch and column-sharded mesh). Returns
        ``(hops, windows, hb)`` or None; enumerable construction failures
        (TypeError: no columnar engine for the program; ValueError:
        immutable weight key / >2^31 vertex packing; MemoryError) decline
        the route rather than failing the job."""
        hops = list(range(int(q.start), int(q.end) + 1, int(q.jump)))
        windows = list(q.windows) if q.windows is not None else [q.window]
        if not hops or len(hops) * len(windows) > 1024:
            return None   # the cheap guard — before paying for tables
        try:
            hb = self._columnar_builder()
        except (TypeError, ValueError, MemoryError) as e:
            _jobs_log.info("columnar range route declined: %s: %s",
                           type(e).__name__, e)
            return None
        # memory guards, sized by the ENGINE's own accounting (the fold
        # strategy — delta vs host columns — changes what the host
        # materialises). Oversized ranges stay on the O(1)-memory-per-hop
        # paths (which rebuild their own tables; a rejected range pays
        # the table build twice, acceptable next to the sweep it avoids
        # misrouting).
        if hb.device_mask_bytes(len(hops) * len(windows)) > 1 << 32:
            return None
        if hb.host_column_bytes(len(hops)) > 1 << 29:
            return None
        return hops, windows, hb

    def _try_range_hopbatch(self, q: RangeQuery) -> bool:
        """Whole-range columnar dispatch for qualifying Range queries:
        every (hop, window) view of the range is a COLUMN of one compiled
        program (``engine/hopbatch``), pipelined in equal hop chunks —
        against the reference's full per-hop actor handshake
        (``RangeAnalysisTask.scala:18-35``). Routes: PageRank (finalize is
        the raw rank vector; the power iteration warm-starts safely),
        ConnectedComponents (labels are global padded indices in both
        engines; no warm start — min-propagation is not a contraction on a
        changing edge set), and SSSP/BFS (unit or mutable-numeric-weighted;
        no warm start)."""
        import numpy as np

        if self.mesh is not None or self.graph.safe_time() < q.end:
            return False
        prep = self._columnar_range_prep(q)
        if prep is None:
            return False
        hops, windows, hb = prep
        if self._kill.is_set():
            return True

        # keyed by hop time, not call order: with parallel chunk folds
        # (and fold-cache replays) the callback may fire from worker
        # threads, interleaved across chunk groups
        shells = {}

        def grab_shell(T, sw):
            shells[int(T)] = _shell_from_fold(hb.tables, sw, int(T))

        chunks = next((k for k in (4, 3, 2)
                       if len(hops) >= 2 * k and len(hops) % k == 0), 1)
        t0 = _time.perf_counter()
        try:
            ranks, steps = hb.run(hops, windows, chunks=chunks,
                                  warm_start=chunks > 1
                                  and hb.supports_warm_start,
                                  hop_callback=grab_shell)
            b0 = _time.perf_counter()
            ranks, steps = _block_steps(
                lambda: (np.asarray(ranks), steps))
            self.ledger.add_phase("device_wait",
                                  _time.perf_counter() - b0)
        except Exception as e:
            # a device failure mid-dispatch falls back to the
            # O(1)-memory-per-hop device-resident route (which rebuilds
            # its own state) instead of failing the job
            _jobs_log.warning("columnar range route failed (%s: %s) — "
                              "falling back to the per-hop path",
                              type(e).__name__, e)
            return False
        self._emit_columnar(hops, windows, ranks, shells,
                            int(steps), _time.perf_counter() - t0,
                            hb.fold_seconds)
        return True

    def _emit_columnar(self, hops, windows, ranks, shells, steps, elapsed,
                       fold_seconds) -> None:
        """Emit one result row per (hop, window) column of a whole-range
        dispatch: viewTime is the AMORTISED share of the dispatch (plus
        that row's own reduce), snapshot-build is the per-hop share of the
        measured incremental fold. ``shells`` is keyed by hop time (the
        fold callback may fire out of hop order under parallel folds)."""
        W = len(windows)
        per_row = elapsed / max(len(hops) * W, 1)
        for _ in hops:
            METRICS.snapshot_build_seconds.observe(
                fold_seconds / max(len(hops), 1))
        METRICS.supersteps.inc(max(steps, 0))
        self.ledger.count_supersteps(steps)
        for j, T in enumerate(hops):
            if self._kill.is_set():
                return
            for i, w in enumerate(windows):
                self._emit(T, w, ranks[j * W + i], shells[int(T)], steps,
                           _time.perf_counter() - per_row)

    def _try_range_mesh_columns(self, q: RangeQuery) -> bool:
        """View-axis mesh parallelism for qualifying Range queries: the
        (hop, window) columns spread COLLECTIVE-FREE over every device of
        the mesh (``parallel/columns.py``) — the graph tables replicate,
        so this route takes ranges whose graph fits one chip; bigger
        graphs fall through to the vertex-sharded ``_try_range_mesh``."""
        import numpy as np

        from ..engine.hopbatch import (HopBatchedCC, HopBatchedPageRank,
                                       HopBatchedSSSP)
        from ..parallel.columns import run_columns_sharded

        if self.mesh is None or self.graph.safe_time() < q.end:
            return False
        prep = self._columnar_range_prep(q)
        if prep is None:
            return False
        hops, windows, hb = prep
        if self._kill.is_set():
            return True

        if isinstance(hb, HopBatchedPageRank):
            kw = dict(kind="pagerank", damping=hb.damping, tol=hb.tol,
                      max_steps=hb.max_steps)
        elif isinstance(hb, HopBatchedCC):
            kw = dict(kind="cc", max_steps=hb.max_steps)
        else:
            kw = dict(kind="bfs", seeds=hb.seeds, directed=hb.directed,
                      max_steps=hb.max_steps)

        shells = {}

        def grab_shell(T, sw):
            shells[int(T)] = _shell_from_fold(hb.tables, sw, int(T))

        t0 = _time.perf_counter()
        _, cols = hb._fold_columns(hops, grab_shell)
        self.ledger.add_phase("fold", hb.fold_seconds)
        if isinstance(hb, HopBatchedSSSP):
            *cols, kw["weight_cols"] = cols
        try:
            ranks, steps = run_columns_sharded(
                hb.tables, *cols, hops, windows,
                self.mesh.devices.ravel(), **kw)
            b0 = _time.perf_counter()
            ranks, steps = _block_steps(
                lambda: (np.asarray(ranks), steps))
            self.ledger.add_phase("device_wait",
                                  _time.perf_counter() - b0)
        except Exception as e:
            # replicating the tables can exhaust one chip's HBM on graphs
            # the host-side guard admits — fall through to the
            # vertex-sharded route instead of failing the job
            _jobs_log.warning("column-sharded mesh route failed (%s: %s) — "
                              "falling back to vertex sharding",
                              type(e).__name__, e)
            return False
        self._emit_columnar(hops, windows, ranks, shells,
                            int(steps), _time.perf_counter() - t0,
                            hb.fold_seconds)
        return True

    def _try_range_device(self, q: RangeQuery) -> bool:
        """Single-device amortised range sweep: device-resident fold state,
        O(delta) per-hop uploads, pipelined emit (engine/device_sweep)."""
        if self.mesh is not None or self.graph.safe_time() < q.end:
            return False
        from ..engine.device_sweep import DeviceSweep

        if not self._device_engine_ok():
            return False
        try:
            sweep = DeviceSweep(self.graph.log)
        except ValueError:
            return False  # >2^31 distinct vertices: packed keys exhausted
        shell = _DeviceShell(sweep)

        def run(windows):
            return sweep.run(self.program, window=q.window, windows=windows)

        self._range_amortised(q, sweep.advance, run, shell.freeze)
        return True

    def _range_amortised(self, q: RangeQuery, advance, run, freeze_rv) -> None:
        """The shared amortised-sweep hop loop: advance the fold, dispatch
        async, emit the PREVIOUS hop while this one computes (hop i+1's host
        fold overlaps hop i's device supersteps).

        Degraded serving (docs/RESILIENCE.md): a deadline that expires or
        a transient failure that exhausts its retry budget MID-sweep stops
        the loop but ships every hop already covered — the job finishes
        "done" with ``degraded: true`` and ``covered_time`` instead of
        discarding paid-for work. Pre-dispatch expiry (nothing covered)
        still fails fast in ``_run_query``, and non-transient errors still
        fail the job: a wrong answer is not a degraded answer."""
        pending = None
        covered = None
        reason = None
        t = q.start
        while t <= q.end and not self._kill.is_set():
            if (self.deadline is not None
                    and _time.monotonic() > self.deadline
                    and (pending is not None or covered is not None)):
                reason = "deadline"
                break
            t0 = _time.perf_counter()
            s0 = _time.perf_counter()
            try:
                advance(int(t))
                METRICS.snapshot_build_seconds.observe(
                    _time.perf_counter() - s0)
                self.ledger.add_phase("fold", _time.perf_counter() - s0)
                windows = list(q.windows) if q.windows is not None else None
                result, steps = run(windows)
                rv = freeze_rv()
            except Exception as e:
                if (_transient(e)
                        and (pending is not None or covered is not None)):
                    reason = "retry_budget"
                    break
                raise
            t_disp = _time.perf_counter()
            if pending is not None:
                self._emit_mesh(*pending)
                covered = pending[0]
            pending = (t, q, rv, result, steps, t0, t_disp)
            t += q.jump
        if pending is not None:
            try:
                self._emit_mesh(*pending)
                covered = pending[0]
            except Exception as e:
                # the tail hop's buffers may be poisoned by the same
                # transient failure that stopped the loop — a degraded
                # answer keeps the PRIOR covered hops rather than dying
                # on the flush; a healthy run still propagates
                if reason is None or not _transient(e):
                    raise
        if reason is not None:
            self._mark_degraded(reason, covered)

    def _mark_degraded(self, reason: str, covered) -> None:
        """Record a partial answer: job-side fields the REST payload
        surfaces, plus the process-wide ledger /healthz and /faultz grade
        from. Never fails the job it is marking."""
        self.degraded = True
        self.covered_time = None if covered is None else int(covered)
        self.degraded_reason = reason
        try:
            _degrade.DEGRADED.note(self.id, reason,
                                   covered_time=self.covered_time)
        except Exception:   # telemetry must not fail a served answer
            pass

    def _emit_mesh(self, t, q, rv, result, steps, t0, t_disp) -> None:
        import jax
        import numpy as np

        # viewTime must mean "this hop's fold+dispatch + its device wait +
        # reduce" — not the NEXT hop's host work that ran in the overlap gap.
        # Shift t0 forward by the time spent between this hop's dispatch and
        # now (the pipelined hop's fold) so _emit's end-to-end clock reads
        # dispatch-window + blocking tail only.
        t0 = t0 + (_time.perf_counter() - t_disp)
        b0 = _time.perf_counter()
        _, steps = _block_steps(lambda: (None, steps))
        self.ledger.add_phase("device_wait", _time.perf_counter() - b0)
        METRICS.supersteps.inc(max(steps, 0))
        self.ledger.count_supersteps(steps)
        if q.windows is not None:
            for i, w in enumerate(q.windows):
                r_i = jax.tree_util.tree_map(
                    lambda a: np.asarray(a[i]), result)
                self._emit(t, w, r_i, rv, steps, t0)
        else:
            result = jax.tree_util.tree_map(np.asarray, result)
            self._emit(t, q.window, result, rv, steps, t0)

    def _try_view_resident(self, t: int, q) -> bool:
        """Warm View/Live dispatch through the graph's shared resident
        DeviceSweep: delta-advance + one compiled dispatch instead of a
        full host fold + O(m) upload per request (the cold ``view_at``
        path; ref builds a fresh lens per job, ReaderWorker.scala:293-352).
        Returns False when the query/program must use the cold path."""
        import jax
        import numpy as np

        p = self.program
        if self.mesh is not None or self.graph.safe_time() < int(t):
            return False   # the cold path owns the fence wait
        if not self._device_engine_ok():
            return False
        try:
            acq = self.graph.resident_acquire(int(t))
        except Exception as e:
            # device trouble building the one-time tables (e.g. a tunnel
            # flap during the upload): the cold path must still serve
            _jobs_log.warning("resident sweep build failed (%s: %s) — "
                              "falling back to the cold path",
                              type(e).__name__, e)
            return False
        if acq is None:
            return False
        sweep, lock = acq
        t0 = _time.perf_counter()
        try:
            s0 = _time.perf_counter()
            sweep.advance(int(t))
            METRICS.snapshot_build_seconds.observe(_time.perf_counter() - s0)
            self.ledger.add_phase("fold", _time.perf_counter() - s0)
            windows = list(q.windows) if q.windows is not None else None
            result, steps = sweep.run(p, window=q.window, windows=windows)
            rv = _DeviceShell(sweep).freeze()
            b0 = _time.perf_counter()
            result, steps = _block_steps(lambda: (
                jax.tree_util.tree_map(np.asarray, result), steps))
            self.ledger.add_phase("device_wait",
                                  _time.perf_counter() - b0)
        except Exception as e:
            # device trouble mid-dispatch: a partially applied delta (or a
            # failed donated-buffer call) can leave the device state
            # inconsistent with the host fold — drop the sweep while the
            # lock is still held, then decline to the cold path
            self.graph.resident_discard()
            _jobs_log.warning("resident view route failed (%s: %s) — "
                              "falling back to the cold path",
                              type(e).__name__, e)
            return False
        finally:
            lock.release()
        METRICS.supersteps.inc(max(steps, 0))
        self.ledger.count_supersteps(steps)
        if windows is not None:
            for i, w in enumerate(windows):
                r_i = jax.tree_util.tree_map(lambda a: a[i], result)
                self._emit(t, w, r_i, rv, steps, t0)
        else:
            self._emit(t, q.window, result, rv, steps, t0)
        return True

    def _run_at(self, t: int, q, exact: bool = True, sweep=None) -> None:
        if sweep is None and self._try_view_resident(t, q):
            return
        t0 = _time.perf_counter()
        if sweep is not None:
            s0 = _time.perf_counter()
            view = sweep.view_at(int(t))
            METRICS.snapshot_build_seconds.observe(_time.perf_counter() - s0)
            self.graph.cache_put(
                int(t), view, self.program.needs_occurrences,
                version=sweep.log.version)
        else:
            s0 = _time.perf_counter()
            view = self.graph.view_at(
                int(t), exact=exact, wait_timeout=self.wait_timeout,
                include_occurrences=self.program.needs_occurrences)
        self.ledger.add_phase("fold", _time.perf_counter() - s0)
        windows = q.windows
        c0 = _time.perf_counter()
        if windows is not None:
            result, steps = self._execute(view, windows=list(windows))
            steps = int(steps)   # device barrier for the superstep count
            self.ledger.add_phase("compute", _time.perf_counter() - c0)
            METRICS.supersteps.inc(max(steps, 0))  # once per device run
            self.ledger.count_supersteps(steps)
            for i, w in enumerate(windows):
                import jax

                r_i = jax.tree_util.tree_map(lambda a: a[i], result)
                self._emit(t, w, r_i, view, steps, t0)
        else:
            result, steps = self._execute(view, window=q.window)
            steps = int(steps)
            self.ledger.add_phase("compute", _time.perf_counter() - c0)
            METRICS.supersteps.inc(max(steps, 0))
            self.ledger.count_supersteps(steps)
            self._emit(t, q.window, result, view, steps, t0)

    def _execute(self, view, window=None, windows=None):
        if self.mesh is not None:
            from ..parallel import sharded

            return sharded.run(self.program, view, self.mesh,
                               window=window, windows=windows)
        return bsp.run(self.program, view, window=window, windows=windows)

    def _emit(self, t, window, result, view, steps, t0) -> None:
        e0 = _time.perf_counter()
        reduced = self.program.reduce(result, view, window=window)
        # counted only after the host reduce: viewTime is END-TO-END (device
        # compute + reduce), and a failed reduce is not a computed view
        METRICS.views_computed.inc()
        METRICS.view_seconds.observe(_time.perf_counter() - t0)
        self.ledger.add_phase("emit", _time.perf_counter() - e0)
        self.ledger.count_views()
        row = {
            "time": int(t),
            "windowsize": int(window) if window is not None else None,
            "viewTime": round((_time.perf_counter() - t0) * 1000.0, 3),
            "steps": int(steps),
            "result": reduced,
        }
        with self._results_mu:
            self.results.append(row)
            if self._results_cap and len(self.results) > self._results_cap:
                drop = len(self.results) - self._results_cap
                del self.results[:drop]
                self.results_dropped += drop
        if self.sink is not None:
            self.sink.write(row)


def _shell_from_fold(tables, sw, T):
    """Reducer-facing vertex shell from a SweepBuilder's fold state at T
    (vertex-side fields only — gated by ``reduce_shell_safe``)."""
    import numpy as np

    from ..core.snapshot import INT64_MIN
    from ..parallel.sweep import _Shell

    n, n_pad = tables.n, tables.n_pad
    vids = tables.vids
    if vids is None:   # DeviceSweep frees the host copy after upload
        vids = getattr(tables, "_shell_vids", None)
        if vids is None:   # rebuild once per sweep, not once per hop
            vids = np.full(n_pad, -1, np.int64)
            vids[:n] = tables.uv
            tables._shell_vids = vids
    vm = np.zeros(n_pad, bool)
    vm[:n] = sw.v_alive
    vl = np.full(n_pad, INT64_MIN, np.int64)
    vl[:n] = sw.v_lat
    vf = np.full(n_pad, INT64_MIN, np.int64)
    vf[:n] = sw.v_first
    return _Shell(time=int(T), n_pad=n_pad, vids=vids, v_mask=vm,
                  v_latest_time=vl, v_first_time=vf)


class _DeviceShell:
    """Reducer-facing view shells over a DeviceSweep's HOST fold state
    (the device buffers' numpy twin lives in the SweepBuilder)."""

    def __init__(self, sweep):
        self.sweep = sweep

    def freeze(self):
        ds = self.sweep
        return _shell_from_fold(ds.tables, ds.sw, ds.t_now)


class AnalysisManager:
    """Job registry + submission surface (``AnalysisManager.scala:49-70``
    job tracking for RequestResults/KillTask)."""

    def __init__(self, graph: TemporalGraph, mesh=None, sink_dir: str = "",
                 sink_format: str = "jsonl"):
        from .scheduler import ServingScheduler

        self.graph = graph
        self.mesh = mesh
        self.sink_dir = sink_dir       # "" disables file sinks (ref: unset
        self.sink_format = sink_format  # env path in Utils.scala:107-126)
        #: serving scheduler (jobs/scheduler.py): cross-request
        #: coalescing collect windows + ledger-priced admission control
        #: + deadlines. Always constructed — RTPU_BATCH_WINDOW_MS=0 and
        #: RTPU_ADMISSION=0 make every path identical to pre-scheduler.
        self.scheduler = ServingScheduler(graph)
        self._jobs: dict[str, Job] = {}
        self._counter = itertools.count()
        self._lock = threading.Lock()
        # finished jobs are retained for /AnalysisResults but evicted
        # oldest-first past the cap — an always-up job server must not
        # grow its job table with every request served. 0 disables.
        self._table_cap = max(
            0, int(os.environ.get("RTPU_JOB_TABLE_CAP", 4096)))
        # lockset-sanitizer registration (None unless RTPU_SANITIZE): job
        # table accesses report their held lockset; an unguarded access
        # path surfaces as a shared-state-race finding in tier-1
        self._san_tracker = _san_track("job_table")

    def _note_table(self, write: bool = False) -> None:
        _san_note(self._san_tracker, write)

    def _evict_done_locked(self) -> None:
        """Drop oldest FINISHED jobs past the table cap (caller holds
        ``_lock``). Running jobs are never evicted — the cap bounds
        retention, not concurrency (admission control is ROADMAP #1)."""
        if not self._table_cap or len(self._jobs) <= self._table_cap:
            return
        excess = len(self._jobs) - self._table_cap
        for jid in [jid for jid, j in self._jobs.items()
                    if j._done.is_set()][:excess]:
            del self._jobs[jid]

    def submit(self, program: VertexProgram, query: Query,
               job_id: str | None = None, mesh=None,
               wait_timeout: float = 30.0, sink_name: str | None = None,
               sink_format: str | None = None,
               explain: bool = False, tenant: str | None = None,
               deadline_ms=None, priority: int = 0,
               batch=None) -> Job:
        from .sink import ResultSink, resolve_sink_path

        # a malformed deadline is the CALLER's error and must raise as
        # one — validated BEFORE admission, or an admission-enabled
        # server would misreport it as a deadline_infeasible shed (a
        # capacity signal) and pollute the shed metrics
        if deadline_ms is not None and not float(deadline_ms) > 0:
            raise ValueError(
                f"deadline_ms must be positive, got {deadline_ms!r}")
        # admission BEFORE the job exists: an over-budget / over-share /
        # deadline-infeasible request is shed here with AdmissionDenied
        # (REST maps it to 429 + Retry-After) and never touches the job
        # table. The returned estimate is registered into the live
        # backlog; complete() (via _publish_ledger) or the failure path
        # below releases it.
        est = self.scheduler.admit(program, query, tenant,
                                   deadline_ms=deadline_ms)
        with self._lock:
            if job_id is None:
                job_id = f"{type(program).__name__}_{next(self._counter)}"
            if job_id in self._jobs:
                self.scheduler.cancel(est, tenant)
                raise KeyError(f"job {job_id!r} already exists")
            try:
                job = Job(job_id, program, query, self.graph,
                          mesh=mesh if mesh is not None else self.mesh,
                          wait_timeout=wait_timeout, explain=explain,
                          tenant=tenant, deadline_ms=deadline_ms,
                          priority=priority,
                          no_batch=batch is False)
            except BaseException:
                self.scheduler.cancel(est, tenant)
                raise
            job._sched = self.scheduler
            job._admitted_cost_s = est
            self._jobs[job_id] = job
            self._note_table(write=True)
            self._evict_done_locked()
        sink = None
        try:
            # disk I/O (mkdirs + open) stays OUTSIDE the registry lock;
            # the job is registered but not started, so the sink attaches
            # before any emit. Format rides the resolved suffix.
            path = resolve_sink_path(self.sink_dir, job_id,
                                     requested=sink_name,
                                     fmt=sink_format or self.sink_format)
            if path is not None:
                sink = ResultSink(path)
                with self._lock:
                    # no two LIVE jobs share one file (interleaved rows);
                    # sequential append to a finished job's file is fine.
                    # Sinks only attach under this lock, so the check and
                    # the attach are atomic.
                    for other in self._jobs.values():
                        if (other is not job and other.sink is not None
                                and other.sink.path == sink.path
                                and not other._done.is_set()):
                            raise ValueError(
                                f"sink path in use by job {other.id!r}")
                    job.sink = sink
        except BaseException:
            if sink is not None:
                sink.close()
            with self._lock:
                del self._jobs[job_id]
            self.scheduler.cancel(est, tenant)
            raise
        # coalescing: an eligible job joins its family's collect window
        # BEFORE its thread starts (the thread's first act is to wait on
        # the window handle); ineligible jobs — and every job when
        # RTPU_BATCH_WINDOW_MS=0 — take exactly the pre-scheduler path
        try:
            self.scheduler.offer(job)
            return job.start()
        except BaseException:
            # thread exhaustion is exactly when admission matters: a
            # failed start must not leave a never-running "running" job
            # in the table nor its cost stuck in the admission backlog.
            # Kill first: offer() may have enqueued a _Pending, and a
            # dead job's pending must be dropped at batch formation
            # (the dispatch loop checks _kill), not dispatched for a
            # result nobody will read
            job.kill()
            if sink is not None:
                sink.close()
            with self._lock:
                self._jobs.pop(job_id, None)
            self.scheduler.cancel(est, tenant)
            raise

    def get(self, job_id: str) -> Job:
        # under the registry lock like every other table access: a bare
        # dict read racing submit's insert/evict is exactly the unguarded
        # shape the lockset sanitizer flags (rtpulint v2)
        with self._lock:
            job = self._jobs.get(job_id)
            self._note_table()
        if job is None:
            raise KeyError(f"unknown job {job_id!r}")
        return job

    def results(self, job_id: str) -> list[dict]:
        return self.get(job_id).results_snapshot()

    def kill(self, job_id: str) -> None:
        self.get(job_id).kill()

    def jobs(self) -> dict[str, str]:
        with self._lock:
            self._note_table()
            return {jid: j.status for jid, j in self._jobs.items()}
