"""History compaction — the Archivist's memory governance, log-structured.

The reference's ``Archivist`` cycle (``Archivist.scala:56-159``): when heap
crosses 70%, compress history older than a cutoff (dedup runs of equal
state — ``Entity.compressHistory``, ``Entity.scala:64-99``) and archive
(drop) the oldest 10% of the time span (``Entity.archive``,
``Entity.scala:102-138``). On an append-only log both become pure
log→log rewrites:

* ``compress_events``: within each entity's pre-cutoff history, keep only the
  FIRST event of every run of equal aliveness. ``alive_at`` is preserved
  exactly at every T; per-entity ``latest_time`` (window membership) may move
  earlier for views inside a compressed run — the same approximation the
  reference makes.
* ``archive_events``: drop all events before the cutoff, folding pre-cutoff
  state into baseline events at each surviving entity's latest pre-cutoff
  activity time (with its latest property values). Every view at T >= cutoff
  is preserved exactly (aliveness, latest_time, windows, property values);
  views before the cutoff are gone — that is the point of archiving.
  ``first_time`` (creation time) collapses to the baseline time.
"""

from __future__ import annotations

import time as _time

import numpy as np

from ..obs.metrics import METRICS
from ..obs.trace import TRACER

from ..core import events as ev
from ..core.events import EventLog
from ..core.snapshot import build_view
from ..native import lib as _native


def compress_events(log: EventLog, cutoff: int) -> EventLog:
    """Run-length dedup of aliveness flips strictly before `cutoff`.

    Redundancy is judged against the MERGED aliveness streams exactly as the
    snapshot fold sees them: edge adds are vertex-revival marks (so a vertex
    delete after an incident edge add is never "redundant"), and vertex
    deletes are edge tombstones. Only an entity's own events are droppable;
    a droppable event must repeat its predecessor's aliveness in the merged
    stream. Events carrying properties are kept (their values feed later
    lookups)."""
    from ..core.snapshot import _endpoint_tombstones, _unique_pairs

    t = log.column("time")
    k = log.column("kind")
    s = log.column("src")
    d = log.column("dst")
    keep = np.ones(log.n, bool)
    has_props = np.zeros(log.n, bool)
    if log.props.n:
        has_props[np.unique(log.props.column("event"))] = True

    def dedup(keys, times, alive, own_row):
        """own_row >= 0 marks droppable events (index into the log)."""
        if len(times) == 0:
            return
        order = _native.sort_events(keys, times, alive)
        if order is None:
            order = np.lexsort((~alive, times) + tuple(reversed(keys)))
        oalive = alive[order]
        orow = own_row[order]
        same = np.ones(len(order) - 1, bool)
        for kk in keys:
            ko = kk[order]
            same &= ko[1:] == ko[:-1]
        ot = times[order]
        redundant = (same & (oalive[1:] == oalive[:-1]) & (ot[1:] < cutoff)
                     & (orow[1:] >= 0))
        rows = orow[1:][redundant]
        rows = rows[~has_props[rows]]
        keep[rows] = False

    is_va = k == ev.VERTEX_ADD
    is_vd = k == ev.VERTEX_DELETE
    is_ea = k == ev.EDGE_ADD
    is_ed = k == ev.EDGE_DELETE

    # ---- vertex merged stream ----
    v_ids = np.concatenate([s[is_va], s[is_vd], s[is_ea], d[is_ea]])
    v_t = np.concatenate([t[is_va], t[is_vd], t[is_ea], t[is_ea]])
    v_alive = np.concatenate([
        np.ones(int(is_va.sum()), bool),
        np.zeros(int(is_vd.sum()), bool),
        np.ones(2 * int(is_ea.sum()), bool),
    ])
    v_own = np.concatenate([
        np.flatnonzero(is_va), np.flatnonzero(is_vd),
        np.full(2 * int(is_ea.sum()), -1, np.int64),
    ])
    dedup((v_ids,), v_t, v_alive, v_own)

    # ---- edge merged stream (own events + endpoint tombstones) ----
    e_s = np.concatenate([s[is_ea], s[is_ed]])
    e_d = np.concatenate([d[is_ea], d[is_ed]])
    e_t = np.concatenate([t[is_ea], t[is_ed]])
    e_alive = np.concatenate([
        np.ones(int(is_ea.sum()), bool), np.zeros(int(is_ed.sum()), bool)])
    e_own = np.concatenate([np.flatnonzero(is_ea), np.flatnonzero(is_ed)])
    if is_vd.any() and (is_ea.any() or is_ed.any()):
        up_s, up_d = _unique_pairs(e_s, e_d)
        ts_s, ts_d, ts_t = _endpoint_tombstones(up_s, up_d, s[is_vd], t[is_vd])
        e_s = np.concatenate([e_s, ts_s])
        e_d = np.concatenate([e_d, ts_d])
        e_t = np.concatenate([e_t, ts_t])
        e_alive = np.concatenate([e_alive, np.zeros(len(ts_s), bool)])
        e_own = np.concatenate([e_own, np.full(len(ts_s), -1, np.int64)])
    dedup((e_s, e_d), e_t, e_alive, e_own)

    return _rebuild(log, keep)


def archive_events(log: EventLog, cutoff: int) -> EventLog:
    """Drop history before `cutoff`; fold surviving state into baselines."""
    base = build_view(log, cutoff - 1)
    keep = log.column("time") >= cutoff
    out = _rebuild(log, keep)

    # baselines: alive vertices / edges at cutoff-1, stamped at their latest
    # pre-cutoff activity so window semantics at T >= cutoff stay exact
    vm = base.v_mask
    v_rows: dict[int, int] = {}
    if vm.any():
        start, _ = out.append_batch(
            base.v_latest_time[vm],
            np.full(int(vm.sum()), ev.VERTEX_ADD, np.uint8),
            base.vids[vm],
            np.full(int(vm.sum()), -1, np.int64),
        )
        for i, vid in enumerate(base.vids[vm]):
            v_rows[int(vid)] = start + i
    em = base.e_mask
    e_rows: dict[tuple[int, int], int] = {}
    if em.any():
        gsrc = base.vids[base.e_src[em]]
        gdst = base.vids[base.e_dst[em]]
        start, _ = out.append_batch(
            base.e_latest_time[em],
            np.full(int(em.sum()), ev.EDGE_ADD, np.uint8),
            gsrc, gdst,
        )
        for i in range(len(gsrc)):
            e_rows[(int(gsrc[i]), int(gdst[i]))] = start + i

    _attach_baseline_props(log, out, cutoff, v_rows, e_rows)
    return out


def _rebuild(log: EventLog, keep: np.ndarray) -> EventLog:
    """Copy surviving events + their property rows into a fresh log."""
    out = EventLog()
    out.append_batch(
        log.column("time")[keep], log.column("kind")[keep],
        log.column("src")[keep], log.column("dst")[keep])
    new_of_old = np.full(log.n, -1, np.int64)
    new_of_old[np.flatnonzero(keep)] = np.arange(int(keep.sum()))
    props = log.props
    op = out.props
    for name in props.keys:
        op.key_id(name)
    op._immutable = set(props._immutable)
    pe = props.column("event")
    for r in np.flatnonzero(new_of_old[pe] >= 0):
        _copy_prop_row(props, op, int(r), int(new_of_old[pe[r]]))
    return out


def _copy_prop_row(src_props, dst_props, row: int, target_event: int) -> None:
    tag = int(src_props.column("tag")[row])
    if tag == src_props.STR_TAG:
        sref = len(dst_props._strings)
        dst_props._strings.append(
            src_props.string(int(src_props.column("sref")[row])))
    else:
        sref = -1
    dst_props._rows.append_row(
        event=target_event, key=int(src_props.column("key")[row]),
        tag=tag, num=float(src_props.column("num")[row]), sref=sref)


def _attach_baseline_props(log: EventLog, out: EventLog, cutoff: int,
                           v_rows: dict, e_rows: dict) -> None:
    """Carry each surviving entity's latest (earliest, if immutable) property
    value per key from the pre-cutoff history onto its baseline event."""
    props = log.props
    if props.n == 0 or (not v_rows and not e_rows):
        return
    pe = props.column("event")
    pk = props.column("key")
    ev_time = log.column("time")[pe]
    ev_kind = log.column("kind")[pe]
    ev_src = log.column("src")[pe]
    ev_dst = log.column("dst")[pe]
    pre = ev_time < cutoff

    # winner per (entity, key): latest row (stable by row order), or earliest
    # for immutable keys
    winners: dict[tuple, int] = {}
    for r in np.flatnonzero(pre):
        kind = ev_kind[r]
        if kind == ev.VERTEX_ADD:
            ent = ("v", int(ev_src[r]))
            if ent[1] not in v_rows:
                continue
        elif kind == ev.EDGE_ADD:
            ent = ("e", int(ev_src[r]), int(ev_dst[r]))
            if (ent[1], ent[2]) not in e_rows:
                continue
        else:
            continue
        key = ent + (int(pk[r]),)
        prev = winners.get(key)
        if prev is None:
            winners[key] = int(r)
        elif props.is_immutable(int(pk[r])):
            if (ev_time[r], r) < (ev_time[prev], prev):
                winners[key] = int(r)
        else:
            if (ev_time[r], r) >= (ev_time[prev], prev):
                winners[key] = int(r)

    for key, r in winners.items():
        if key[0] == "v":
            target = v_rows[key[1]]
        else:
            target = e_rows[(key[1], key[2])]
        _copy_prop_row(props, out.props, r, target)


class Archivist:
    """Memory governor running the reference's TWO-PHASE cycle: when the log
    exceeds its budget, COMPRESS history older than 90% of the span (dedup
    redundant aliveness runs — ``Archivist.scala:66-122`` compressGraph) and
    ARCHIVE the oldest 10% outright (``Archivist.scala:138-159``
    archiveGraph). Each phase is gated by its flag, mirroring the
    ``compressing``/``archiving`` env switches (``Utils.scala:22-26``)."""

    def __init__(self, graph, max_events: int = 50_000_000,
                 archive_fraction: float = 0.1,
                 compress_fraction: float = 0.9,
                 compressing: bool = True, archiving: bool = True):
        self.graph = graph
        self.max_events = max_events
        self.archive_fraction = archive_fraction
        self.compress_fraction = compress_fraction
        self.compressing = compressing
        self.archiving = archiving

    def maybe_compact(self) -> bool:
        log = self.graph.log
        if log.n <= self.max_events:
            return False
        if not (self.compressing or self.archiving):
            return False
        # Rewrite a frozen prefix while ingestion continues, then atomically
        # splice the concurrent tail back in compact_to — every holder of
        # the EventLog object (pipelines, views) sees the compacted history;
        # nothing is stranded or lost.
        t0 = _time.perf_counter()
        with TRACER.span("compact.cycle", events_before=int(log.n),
                         compressing=self.compressing,
                         archiving=self.archiving) as tsp:
            frozen = log.freeze()
            span = log.max_time - log.min_time
            new_log = frozen
            if self.compressing:
                c_cut = log.min_time + int(span * self.compress_fraction)
                with TRACER.span("compact.compress", cutoff=int(c_cut)):
                    new_log = compress_events(new_log, c_cut)
            if self.archiving:
                a_cut = log.min_time + int(span * self.archive_fraction) + 1
                with TRACER.span("compact.archive", cutoff=int(a_cut)):
                    new_log = archive_events(new_log, a_cut)
            tsp.set(events_after=int(new_log.n))
            if new_log.n >= frozen.n:
                # nothing shrank (e.g. compress-only on already-compressed
                # history) — skip the splice, or every governor tick would
                # rewrite the whole log and invalidate caches for nothing
                tsp.set(spliced=False)
                return False
            log.compact_to(new_log, since_row=frozen.n)
            self.graph.invalidate_cache()
            tsp.set(spliced=True)
        # counters record compactions that actually landed
        if self.compressing:
            METRICS.compactions.labels("compress").inc()
        if self.archiving:
            METRICS.compactions.labels("archive").inc()
        METRICS.compaction_seconds.observe(_time.perf_counter() - t0)
        return True
