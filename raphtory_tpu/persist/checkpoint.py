"""Durable event-log snapshots — checkpoint/resume.

The reference designed (but disabled) Cassandra persistence: the ``SAVING``
flag gates writing compressed history out, and ``Vertex.apply``/``Edge.apply``
exist for rehydration (``Utils.scala:22``, ``Vertex.scala:9-25`` — SURVEY
§5.4: "capability bar: durable history snapshot + reload"). Here the whole
bitemporal store IS flat arrays, so a checkpoint is one compressed .npz:
event columns + property rows + interned strings + key table. Bit-exact
round trip.
"""

from __future__ import annotations

import json
import os

import numpy as np

from ..core.events import EventLog

FORMAT_VERSION = 1


def save_log(log: EventLog, path: str) -> None:
    """Atomic write (tmp + rename) of a consistent snapshot of the log
    (freeze() pins matching event/prop lengths, so checkpointing during live
    ingestion is safe)."""
    log = log.freeze()
    props = log.props
    meta = {
        "format": FORMAT_VERSION,
        "n_events": log.n,
        "keys": props.keys,
        "immutable": sorted(props._immutable),
    }
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez_compressed(
            f,
            meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8),
            time=log.column("time"),
            kind=log.column("kind"),
            src=log.column("src"),
            dst=log.column("dst"),
            p_event=props.column("event"),
            p_key=props.column("key"),
            p_tag=props.column("tag"),
            p_num=props.column("num"),
            p_sref=props.column("sref"),
            strings=np.frombuffer(
                json.dumps(props._strings).encode(), dtype=np.uint8),
        )
    os.replace(tmp, path)


def load_log(path: str) -> EventLog:
    with np.load(path) as z:
        meta = json.loads(bytes(z["meta"]).decode())
        if meta["format"] != FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint format {meta['format']}")
        log = EventLog()
        log.append_batch(z["time"], z["kind"], z["src"], z["dst"])
        props = log.props
        for name in meta["keys"]:
            props.key_id(name)
        props._immutable = set(meta["immutable"])
        props._strings = json.loads(bytes(z["strings"]).decode())
        props._rows.append_batch(
            event=z["p_event"], key=z["p_key"], tag=z["p_tag"],
            num=z["p_num"], sref=z["p_sref"],
        )
    return log
