"""Amortised range sweeps on a device mesh — static partition, O(delta) hops.

Round-3 finding: the mesh path re-ran ``partition_view`` (a per-shard Python
loop + halo construction + lexsorts) from scratch for EVERY hop of a range
sweep, while the single-chip path got incremental snapshots. The fix is the
same move that built ``engine/device_sweep``: work in the GLOBAL dense
space (every vertex/pair the pinned log ever mentions — positions never
change), so the partition layout, halo exchange structure and compiled
program are all STATIC across the sweep; each hop updates only the
fold-state values (latest/alive) at the delta's per-shard slots.

The reference re-runs its full per-timestamp handshake per range hop
(``RangeAnalysisTask.scala:18-35``); ``partition_view`` amortised nothing;
``ShardedSweep`` amortises everything but the O(delta) host fold.

Supports the same program class as ``DeviceSweep``: no occurrence arrays,
no host-materialised properties (``engine.device_sweep.supported``).
"""

from __future__ import annotations

import numpy as np

from ..core.events import EventLog
from ..core.snapshot import INT64_MIN
from ..core.sweep import _ENC_MASK, _ENC_SHIFT, SweepBuilder
from ..engine.device_sweep import GlobalTables, supported
from . import sharded
from .sharded import ShardedView, _build_halo, _pow2


class ShardedSweep:
    """Ascending-time range sweep over a mesh with a static partition.

    ``run(program, T, ...)`` advances the host fold to T, patches the delta
    into the per-shard blocks, and dispatches the (cached) compiled SPMD
    program. Results are in the GLOBAL dense vertex space (row i is
    ``self.tables.uv[i]``), like ``DeviceSweep``.
    """

    def __init__(self, log: EventLog, n_shards: int):
        self.sw = SweepBuilder(log, track_rows=False, preseed_pairs=True)
        self.t = GlobalTables(self.sw)
        t = self.t
        if t.n_pad % n_shards:
            raise ValueError(
                f"vertex shards ({n_shards}) must divide the padded global "
                f"vertex count ({t.n_pad})")
        S = self.S = n_shards
        n_loc = self.n_loc = t.n_pad // n_shards
        sharded.note_partition_build()  # the ONE static build of this sweep

        # ---- static partition of the global pair table (both directions) --
        def build(owner_of, local_of, global_of):
            owner = owner_of[: t.m] // n_loc
            order = np.lexsort((local_of[: t.m], owner))
            counts = np.bincount(owner, minlength=S)
            m_loc = _pow2(int(counts.max()) if t.m else 0)
            idx_g = np.full((S, m_loc), t.n_pad - 1, np.int32)
            idx_l = np.full((S, m_loc), n_loc - 1, np.int32)
            shard_of = np.empty(t.m, np.int32)   # engine pos -> (shard, slot)
            slot_of = np.empty(t.m, np.int32)
            off = 0
            for sh in range(S):
                c = int(counts[sh])
                rows = order[off: off + c]       # engine positions, sorted
                off += c
                idx_g[sh, :c] = global_of[rows]
                idx_l[sh, :c] = owner_of[rows] - sh * n_loc
                shard_of[rows] = sh
                slot_of[rows] = np.arange(c, dtype=np.int32)
            return m_loc, idx_g, idx_l, shard_of, slot_of

        esrc = t.e_src.astype(np.int64)
        edst = t.e_dst.astype(np.int64)
        m_d, d_src_g, d_dst_l, self._d_shard, self._d_slot = build(
            edst, edst % n_loc, esrc)
        m_s, s_dst_g, s_src_l, self._s_shard, self._s_slot = build(
            esrc, esrc % n_loc, edst)
        h_d, d_src_h, d_send, halo_d = _build_halo(d_src_g, n_loc, S)
        h_s, s_dst_h, s_send, halo_s = _build_halo(s_dst_g, n_loc, S)

        # per-shard degree/halo skew of the ONE static partition this
        # sweep amortises over every hop — same surface as partition_view
        skew = sharded.shard_skew(
            edges_dst=np.bincount(self._d_shard, minlength=S),
            edges_src=np.bincount(self._s_shard, minlength=S),
            halo_dst=halo_d, halo_src=halo_s)
        sharded.note_partition_skew(skew)

        # mutable fold-state blocks (alive masks + latest times), all-dead
        def blk(m_loc, fill, dt):
            return np.full((S, m_loc), fill, dt)

        self.sv = ShardedView(
            n_shards=S, n_loc=n_loc, m_loc_d=m_d, m_loc_s=m_s,
            vids=t.vids.reshape(S, n_loc),
            v_mask=np.zeros((S, n_loc), bool),
            v_latest=np.full((S, n_loc), INT64_MIN, np.int64),
            v_first=np.full((S, n_loc), INT64_MIN, np.int64),
            d_src_g=d_src_g, d_dst_l=d_dst_l,
            d_mask=blk(m_d, False, bool),
            d_time=blk(m_d, INT64_MIN, np.int64),
            d_first=blk(m_d, INT64_MIN, np.int64),
            s_dst_g=s_dst_g, s_src_l=s_src_l,
            s_mask=blk(m_s, False, bool),
            s_time=blk(m_s, INT64_MIN, np.int64),
            s_first=blk(m_s, INT64_MIN, np.int64),
            d_props={}, s_props={}, view=None,
            h_d=h_d, d_src_h=d_src_h, d_send=d_send,
            h_s=h_s, s_dst_h=s_dst_h, s_send=s_send,
            skew=skew,
        )
        self._shell = _Shell(time=0, n_pad=t.n_pad, vids=t.vids,
                             v_mask=self.sv.v_mask.reshape(-1),
                             v_latest_time=self.sv.v_latest.reshape(-1),
                             v_first_time=self.sv.v_first.reshape(-1))
        self.sv.view = self._shell
        self.t_now: int | None = None
        # Round-7 finding: ``sv.skew`` was computed ONCE above and never
        # again, so after a large ingest suffix the route chooser and the
        # advisor's shard-skew rule kept reading day-1 balance. Track edge
        # rows touched since the last skew publication and recompute
        # (sampled, O(S * min(m_loc, 64Ki))) once a quarter of the edge
        # table has churned.
        self._rows_since_skew = 0
        self._skew_refresh_rows = max(256, t.m // 4)

    # ---- sweep driving ----

    def advance(self, time: int) -> None:
        time = int(time)
        if self.t_now is not None and time < self.t_now:
            raise ValueError(
                f"ShardedSweep times must ascend (got {time} < {self.t_now})")
        if self.t_now is not None and time == self.t_now:
            return
        self.sw._advance(time)
        self.t_now = time
        self._shell.time = time
        d = self.sw.last_delta
        sv, n_loc = self.sv, self.n_loc
        vi = d["v_idx"]
        if len(vi):
            vs, vl = vi // n_loc, vi % n_loc
            sv.v_mask[vs, vl] = d["v_alive"]
            sv.v_latest[vs, vl] = d["v_lat"]
            sv.v_first[vs, vl] = d["v_first"]
        if len(d["e_enc"]):
            pos = self.t.eng_pos(d["e_enc"])
            for shard, slot in ((self._d_shard, self._d_slot),
                                (self._s_shard, self._s_slot)):
                sh, sl = shard[pos], slot[pos]
                blocks = (sv.d_mask, sv.d_time, sv.d_first) \
                    if shard is self._d_shard \
                    else (sv.s_mask, sv.s_time, sv.s_first)
                blocks[0][sh, sl] = d["e_alive"]
                blocks[1][sh, sl] = d["e_lat"]
                blocks[2][sh, sl] = d["e_first"]
            self._rows_since_skew += len(pos)
            if self._rows_since_skew >= self._skew_refresh_rows:
                self._rows_since_skew = 0
                sharded.refresh_partition_skew(sv)

    # ---- dispatch ----

    def run(self, program, time: int | None = None, *, mesh,
            window: int | None = None, windows=None, comm: str = "auto",
            block: bool = True):
        """Advance to `time` and run `program` over `mesh` using the static
        partition. Result rows are global dense vertex indices."""
        if not supported(program):
            raise ValueError(
                "program needs occurrences or host-materialised properties — "
                "use jobs/bsp with per-view partitioning instead")
        if mesh.shape[sharded.V_AXIS] != self.S:
            raise ValueError(
                f"mesh vertex axis ({mesh.shape[sharded.V_AXIS]}) != "
                f"partition shards ({self.S})")
        if time is not None:
            self.advance(time)
        if self.t_now is None:
            raise ValueError("call advance(T) (or pass time=) before run()")
        return sharded.run(program, self._shell, mesh, window=window,
                           windows=windows, sharded_view=self.sv, comm=comm,
                           block=block)

    def reduce_view(self):
        """A frozen host copy of the reducer-facing view fields at t_now —
        safe to keep across a later ``advance`` (the live shell mutates)."""
        return _Shell(time=int(self._shell.time), n_pad=self.t.n_pad,
                      vids=self.t.vids,
                      v_mask=self._shell.v_mask.copy(),
                      v_latest_time=self._shell.v_latest_time.copy(),
                      v_first_time=self._shell.v_first_time.copy())


class _Shell:
    """The reducer-facing slice of a GraphView over the global dense space:
    enough for ``sharded.run`` (time, n_pad) and host reducers
    (vids/v_mask/window_masks)."""

    def __init__(self, time, n_pad, vids, v_mask, v_latest_time,
                 v_first_time):
        self.time = time
        self.n_pad = n_pad
        self.vids = vids
        self.v_mask = v_mask
        self.v_latest_time = v_latest_time
        self.v_first_time = v_first_time

    def window_masks(self, windows):
        w = np.asarray(windows, np.int64).reshape(-1, 1)
        lo = self.time - w
        v = self.v_mask[None, :] & (self.v_latest_time[None, :] >= lo)
        return v, None  # edge masks live in the sharded blocks

    def vertex_prop(self, name, default=np.nan):  # pragma: no cover
        raise ValueError("ShardedSweep does not materialise properties — "
                         "programs with props use the per-view path")
