"""Sharded BSP engine: SPMD supersteps over a TPU device mesh.

The distributed design the reference implements with hash-sharded partition
managers + point-to-point actor messages + ack counting
(``Utils.scala:32-47`` sharding, ``EntityStorage`` sync protocol,
``AnalysisTask.scala:197-283`` coordinator) re-expressed the TPU way:

* The padded vertex space is range-partitioned over the mesh's ``vertices``
  axis (contiguous slices — not hash: keeps segment ids sorted per shard).
* Edges are materialised twice, partitioned by DST shard (for out-direction
  combine-at-destination) and by SRC shard (for in-direction) — the analogue
  of the reference's src-copy + ``SplitEdge`` dst-mirror, but immutable, so
  the entire ack/sync dance disappears.
* A superstep all_gathers the (small) per-vertex state along the vertex axis
  over ICI, gathers source states locally, segment-combines into the local
  slice. Votes/quiescence are a ``psum`` — the reference's coordinator
  counting EndStep acks collapses into one collective (SURVEY §2.9).
* Batched windows ride a second mesh axis (``windows``) — window sweeps are
  embarrassingly parallel, so multi-chip scaling multiplies window throughput
  (the reference's analogue of sequence parallelism, SURVEY §5.7).

Scaling note (How-to-Scale-Your-Model recipe): all_gather of state costs
|V|·state_bytes per superstep over ICI. For bigger-than-ICI graphs the next
step is halo compaction (ppermute only the remote sources each shard actually
references); the partition layout here is already built for it.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..core.snapshot import GraphView, INT64_MIN
from ..engine.bsp import _elem, _merge_aggs
from ..engine.program import Context, Edges, VertexProgram
from ..ops.segment import segment_combine

V_AXIS = "vertices"
W_AXIS = "windows"


def make_mesh(n_vertex_shards: int | None = None, n_window_shards: int = 1,
              devices=None) -> Mesh:
    """Build a (windows, vertices) mesh. Defaults to all devices on the
    vertex axis — the common layout for one big graph."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    total = devices.size
    if n_vertex_shards is None:
        n_vertex_shards = total // n_window_shards
    assert n_vertex_shards * n_window_shards == total, (
        f"{n_vertex_shards}x{n_window_shards} != {total} devices")
    return Mesh(devices.reshape(n_window_shards, n_vertex_shards),
                (W_AXIS, V_AXIS))


@dataclass
class ShardedView:
    """Host-side partitioned snapshot: leading axis = vertex shard."""

    n_shards: int
    n_loc: int                 # vertices per shard
    m_loc_d: int               # padded edges per shard (dst partition)
    m_loc_s: int               # padded edges per shard (src partition)
    vids: np.ndarray           # i64[S, n_loc]
    v_mask: np.ndarray         # bool[S, n_loc]
    v_latest: np.ndarray       # i64[S, n_loc]
    v_first: np.ndarray        # i64[S, n_loc]
    # dst partition: combine-at-dst; src index is GLOBAL (gathered state)
    d_src_g: np.ndarray        # i32[S, m_loc_d]
    d_dst_l: np.ndarray        # i32[S, m_loc_d]  local, sorted, pad n_loc-1
    d_mask: np.ndarray         # bool[S, m_loc_d]
    d_time: np.ndarray         # i64[S, m_loc_d]
    d_first: np.ndarray
    # src partition: combine-at-src; dst index is GLOBAL
    s_dst_g: np.ndarray        # i32[S, m_loc_s]
    s_src_l: np.ndarray        # i32[S, m_loc_s]  local, sorted, pad n_loc-1
    s_mask: np.ndarray
    s_time: np.ndarray
    s_first: np.ndarray
    d_props: dict              # name -> f32[S, m_loc_d]
    s_props: dict
    view: GraphView


def _pow2(n: int) -> int:
    return 8 if n <= 8 else 1 << int(np.ceil(np.log2(n)))


def partition_view(view: GraphView, n_shards: int,
                   edge_props: tuple = ()) -> ShardedView:
    """Range-partition the padded vertex space into contiguous shards and
    scatter edges into per-shard blocks (dst- and src-partitioned)."""
    assert view.n_pad % n_shards == 0, (
        f"vertex shard count {n_shards} must divide the padded vertex count "
        f"{view.n_pad} (pad buckets are powers of two; use a power-of-two "
        f"vertex-axis size)")
    n_loc = view.n_pad // n_shards
    S = n_shards

    act = view.e_mask
    esrc = view.e_src[act].astype(np.int64)
    edst = view.e_dst[act].astype(np.int64)
    etime = view.e_latest_time[act]
    efirst = view.e_first_time[act]
    props = {k: view.edge_prop(k)[act] for k in edge_props}

    def _partition(owner_of, local_of, global_of):
        owner = owner_of // n_loc
        order = np.lexsort((local_of, owner))
        counts = np.bincount(owner, minlength=S)
        m_loc = _pow2(int(counts.max()) if len(counts) else 0)
        idx_g = np.full((S, m_loc), view.n_pad - 1, np.int32)
        idx_l = np.full((S, m_loc), n_loc - 1, np.int32)
        mask = np.zeros((S, m_loc), bool)
        tarr = np.full((S, m_loc), INT64_MIN, np.int64)
        farr = np.full((S, m_loc), INT64_MIN, np.int64)
        parr = {k: np.zeros((S, m_loc), np.float32) for k in props}
        off = 0
        for sh in range(S):
            c = int(counts[sh]) if sh < len(counts) else 0
            rows = order[off : off + c]
            off += c
            idx_g[sh, :c] = global_of[rows]
            idx_l[sh, :c] = (owner_of[rows] - sh * n_loc)
            mask[sh, :c] = True
            tarr[sh, :c] = etime[rows]
            farr[sh, :c] = efirst[rows]
            for kk in props:
                parr[kk][sh, :c] = props[kk][rows]
        return m_loc, idx_g, idx_l, mask, tarr, farr, parr

    m_loc_d, d_src_g, d_dst_l, d_mask, d_time, d_first, d_props = _partition(
        edst, edst % n_loc, esrc)
    m_loc_s, s_dst_g, s_src_l, s_mask, s_time, s_first, s_props = _partition(
        esrc, esrc % n_loc, edst)

    rs = lambda a: a.reshape(S, n_loc)
    return ShardedView(
        n_shards=S, n_loc=n_loc, m_loc_d=m_loc_d, m_loc_s=m_loc_s,
        vids=rs(view.vids), v_mask=rs(view.v_mask),
        v_latest=rs(view.v_latest_time), v_first=rs(view.v_first_time),
        d_src_g=d_src_g, d_dst_l=d_dst_l, d_mask=d_mask,
        d_time=d_time, d_first=d_first,
        s_dst_g=s_dst_g, s_src_l=s_src_l, s_mask=s_mask,
        s_time=s_time, s_first=s_first,
        d_props=d_props, s_props=s_props, view=view,
    )


@functools.lru_cache(maxsize=128)
def _sharded_runner(program: VertexProgram, mesh: Mesh, n_loc: int,
                    m_loc_d: int, m_loc_s: int, k_loc: int, n_pad: int,
                    prop_keys: tuple):
    """Compile one SPMD program for (algorithm, shapes, mesh)."""
    has_w = W_AXIS in mesh.axis_names and mesh.shape[W_AXIS] > 1
    reduce_axes = (W_AXIS, V_AXIS)

    def gather_state(state_loc):
        # state leaves are [k_loc, n_loc, ...]: the vertex axis is axis 1
        # (axis 0 is the local window batch) — tiled gather concatenates the
        # contiguous range partitions back into global vertex order
        return jax.tree_util.tree_map(
            lambda a: jax.lax.all_gather(a, V_AXIS, axis=1, tiled=True),
            state_loc)

    def device_fn(v_mask, vids, v_latest, v_first,
                  d_src_g, d_dst_l, d_mask, d_time, d_first,
                  s_dst_g, s_src_l, s_mask, s_time, s_first,
                  d_props, s_props, vprops, time, windows):
        # shapes (per device): v_mask [Kl, n_loc]; d_* [m_loc_d] / masks
        # [Kl, m_loc_d]; windows [Kl]
        v_off = jax.lax.axis_index(V_AXIS).astype(jnp.int32) * n_loc

        # Flat window-major layout: the window batch is ONE graph of
        # k_loc*n_loc local vertices, per-window segment ids offset by
        # kk*n_loc. One scatter for all windows — and no vmapped scatter
        # inside the superstep while_loop, the shape that miscompiles on
        # the TPU backend when the loop condition reads carried state
        # (see engine/bsp.py make_runner).
        woffs_loc = (jnp.arange(k_loc, dtype=jnp.int32) * n_loc)[:, None]
        woffs_pad = (jnp.arange(k_loc, dtype=jnp.int32) * n_pad)[:, None]
        fl_d_dst = (d_dst_l[None, :] + woffs_loc).reshape(-1)  # sorted/blk
        fl_d_src = (d_src_g[None, :] + woffs_pad).reshape(-1)  # into st_full
        fl_s_src = (s_src_l[None, :] + woffs_loc).reshape(-1)  # sorted/blk
        fl_s_dst = (s_dst_g[None, :] + woffs_pad).reshape(-1)
        dm_flat = d_mask.reshape(-1)
        sm_flat = s_mask.reshape(-1)

        def tile_d(a):
            return jnp.broadcast_to(a[None, :], (k_loc,) + a.shape).reshape(
                (k_loc * m_loc_d,) + a.shape[1:])

        def tile_s(a):
            return jnp.broadcast_to(a[None, :], (k_loc,) + a.shape).reshape(
                (k_loc * m_loc_s,) + a.shape[1:])

        def combine_flat(tree_flat, ids, msk):
            def leaf(x):
                out = segment_combine(x, ids, k_loc * n_loc, program.combiner,
                                      msk, indices_are_sorted=True)
                return out.reshape((k_loc, n_loc) + x.shape[1:])
            return jax.tree_util.tree_map(leaf, tree_flat)

        in_deg = segment_combine(
            jnp.ones((k_loc * m_loc_d,), jnp.int32), fl_d_dst,
            k_loc * n_loc, "sum", dm_flat, True).reshape(k_loc, n_loc)
        out_deg = segment_combine(
            jnp.ones((k_loc * m_loc_s,), jnp.int32), fl_s_src,
            k_loc * n_loc, "sum", sm_flat, True).reshape(k_loc, n_loc)

        def mk_ctx(kk, step):
            n_act = jnp.sum(v_mask[kk].astype(jnp.int32))
            n_act = jax.lax.psum(n_act, V_AXIS)
            return Context(
                n=n_loc, time=time, window=windows[kk], v_mask=v_mask[kk],
                vids=vids, v_latest_time=v_latest, v_first_time=v_first,
                out_deg=out_deg[kk], in_deg=in_deg[kk], n_active=n_act,
                step=step, vprops=vprops, v_offset=v_off, axis_name=V_AXIS,
            )

        def init_k(kk):
            return program.init(mk_ctx(kk, jnp.int32(0)))

        state0 = jax.vmap(init_k)(jnp.arange(k_loc))

        def gather_flat(st_full, ids):
            return jax.tree_util.tree_map(
                lambda a: a.reshape((k_loc * n_pad,) + a.shape[2:])[ids],
                st_full)

        def step_all(st, step):
            st_full = gather_state(st)  # [k_loc, n_pad, ...]
            agg = None
            if program.direction in ("out", "both"):
                # Edges contract: src/dst are GLOBAL padded indices
                edges = Edges(src=tile_d(d_src_g), dst=tile_d(d_dst_l) + v_off,
                              mask=dm_flat, time=tile_d(d_time),
                              first_time=tile_d(d_first),
                              props=jax.tree_util.tree_map(tile_d, d_props),
                              step=step)
                payload = program.message(gather_flat(st_full, fl_d_src), edges)
                agg = combine_flat(payload, fl_d_dst, dm_flat)
            if program.direction in ("in", "both"):
                edges = Edges(src=tile_s(s_src_l) + v_off, dst=tile_s(s_dst_g),
                              mask=sm_flat, time=tile_s(s_time),
                              first_time=tile_s(s_first),
                              props=jax.tree_util.tree_map(tile_s, s_props),
                              step=step)
                payload = program.message(gather_flat(st_full, fl_s_dst), edges)
                agg_in = combine_flat(payload, fl_s_src, sm_flat)
                agg = agg_in if agg is None else _merge_aggs(
                    program.combiner, agg, agg_in)

            def upd_k(kk, stk, aggk):
                new_st, votes = program.update(stk, aggk, mk_ctx(kk, step))
                # local vote only — caller makes it global (psum over shards)
                unhalted = jnp.sum((~(votes | ~v_mask[kk])).astype(jnp.int32))
                return new_st, unhalted

            return jax.vmap(upd_k, in_axes=(0, 0, 0))(
                jnp.arange(k_loc), st, agg)


        if program.max_steps > 0:
            def cond(carry):
                step, _, halted = carry
                # halted is per-window and identical on every vertex shard
                # (derived from a psum over V); any unhalted window anywhere
                # keeps every device stepping — SPMD-uniform condition.
                unhalted = jnp.sum((~halted).astype(jnp.int32))
                unhalted = jax.lax.psum(unhalted, reduce_axes)
                return (step < program.max_steps) & (unhalted > 0)

            def body(carry):
                step, st, halted = carry
                new_st, unhalted_local = step_all(st, step)
                # per-window GLOBAL quiescence: a window halts only when no
                # shard changed state — freezing must never be shard-local,
                # or a converged shard would stop receiving neighbours'
                # updates. (The reference's coordinator quiescence check,
                # AnalysisTask.scala:237-283, as one psum.)
                unhalted_global = jax.lax.psum(unhalted_local, V_AXIS)
                new_halt = unhalted_global == 0
                st = jax.tree_util.tree_map(
                    lambda old, new: jnp.where(
                        halted.reshape((k_loc,) + (1,) * (new.ndim - 1)),
                        old, new),
                    st, new_st)
                return step + 1, st, halted | new_halt

            steps, state, _ = jax.lax.while_loop(
                cond, body, (jnp.int32(0), state0, jnp.zeros((k_loc,), bool)))
        else:
            steps, state = jnp.int32(0), state0

        def fin_k(kk, st):
            return program.finalize(st, mk_ctx(kk, steps))

        result = jax.vmap(fin_k, in_axes=(0, 0))(jnp.arange(k_loc), state)
        return result, steps

    # specs: window-sharded leading axis (if any), vertex-sharded second
    kv = P(W_AXIS, V_AXIS)       # [K, S, ...]: windows on W, shards on V
    v = P(V_AXIS)                # [S, ...]: shard axis 0, replicated over W
    in_specs = (
        kv,            # v_mask [K, S, n_loc]
        v, v, v,       # vids, v_latest, v_first [S, n_loc]
        v, v, kv, v, v,        # d_src_g, d_dst_l, d_mask[K,S,m], d_time, d_first
        v, v, kv, v, v,        # s_dst_g, s_src_l, s_mask, s_time, s_first
        v, v, v,       # edge/vertex prop dicts (leaves [S, m_loc] / [S, n_loc])
        P(),           # time scalar
        P(W_AXIS),     # windows [K]
    )
    out_specs = (P(W_AXIS, V_AXIS), P())

    def squeeze_fn(v_mask, vids, v_latest, v_first,
                   d_src_g, d_dst_l, d_mask, d_time, d_first,
                   s_dst_g, s_src_l, s_mask, s_time, s_first,
                   d_props, s_props, vprops, time, windows):
        # strip the sharded block axes: [Kl, 1, ...] -> [Kl, ...]; [1, ...] -> [...]
        sq_kv = lambda a: a.reshape((a.shape[0],) + a.shape[2:])
        sq_v = lambda a: a.reshape(a.shape[1:])
        result, steps = device_fn(
            sq_kv(v_mask), sq_v(vids), sq_v(v_latest), sq_v(v_first),
            sq_v(d_src_g), sq_v(d_dst_l), sq_kv(d_mask), sq_v(d_time), sq_v(d_first),
            sq_v(s_dst_g), sq_v(s_src_l), sq_kv(s_mask), sq_v(s_time), sq_v(s_first),
            jax.tree_util.tree_map(sq_v, d_props),
            jax.tree_util.tree_map(sq_v, s_props),
            jax.tree_util.tree_map(sq_v, vprops),
            time, windows)
        # back to block shape for out_specs [K, S, n_loc, ...]
        result = jax.tree_util.tree_map(
            lambda a: a.reshape((a.shape[0], 1) + a.shape[1:]), result)
        return result, steps

    fn = jax.shard_map(squeeze_fn, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
    return jax.jit(fn)


def run(program: VertexProgram, view: GraphView, mesh: Mesh, *,
        window: int | None = None, windows=None,
        sharded_view: ShardedView | None = None):
    """Run a vertex program SPMD over the mesh. Same surface as
    ``engine.bsp.run`` plus the mesh. Returns (result, steps) with result
    leading axes [K windows, n_pad] in GLOBAL vertex order."""
    batched = windows is not None
    if getattr(program, "needs_occurrences", False):
        raise NotImplementedError(
            "occurrence-based programs (temporal multigraph traversal, e.g. "
            "TaintTracking) are not supported on a mesh yet — the sharded "
            "view partitions deduplicated edges only; run via engine.bsp")
    if windows is not None and len(windows) == 0:
        raise ValueError("windows must be a non-empty list of window sizes")
    if windows is None:
        windows = [window if window is not None else -1]
    wlist = [int(w) if w is not None and w >= 0 else -1 for w in windows]

    W = mesh.shape.get(W_AXIS, 1)
    S = mesh.shape[V_AXIS]
    # pad window count to a multiple of the window-axis size with no-op
    # duplicates of the last window
    k = len(wlist)
    k_pad = ((k + W - 1) // W) * W
    wlist_p = wlist + [wlist[-1]] * (k_pad - k)
    k_loc = k_pad // W

    sv = sharded_view
    if (sv is None or sv.n_shards != S or sv.view is not view
            or not set(program.edge_props) <= set(sv.d_props)):
        sv = partition_view(view, S, tuple(program.edge_props))

    # window masks, computed from per-shard latest-time arrays
    v_masks = np.empty((k_pad, S, sv.n_loc), bool)
    d_masks = np.empty((k_pad, S, sv.m_loc_d), bool)
    s_masks = np.empty((k_pad, S, sv.m_loc_s), bool)
    for i, w in enumerate(wlist_p):
        if w < 0:
            v_masks[i] = sv.v_mask
            d_masks[i] = sv.d_mask
            s_masks[i] = sv.s_mask
        else:
            lo = view.time - w
            v_masks[i] = sv.v_mask & (sv.v_latest >= lo)
            d_masks[i] = sv.d_mask & (sv.d_time >= lo)
            s_masks[i] = sv.s_mask & (sv.s_time >= lo)

    runner = _sharded_runner(
        program, mesh, sv.n_loc, sv.m_loc_d, sv.m_loc_s, k_loc, view.n_pad,
        tuple(program.edge_props))

    result, steps = runner(
        jnp.asarray(v_masks), jnp.asarray(sv.vids), jnp.asarray(sv.v_latest),
        jnp.asarray(sv.v_first),
        jnp.asarray(sv.d_src_g), jnp.asarray(sv.d_dst_l), jnp.asarray(d_masks),
        jnp.asarray(sv.d_time), jnp.asarray(sv.d_first),
        jnp.asarray(sv.s_dst_g), jnp.asarray(sv.s_src_l), jnp.asarray(s_masks),
        jnp.asarray(sv.s_time), jnp.asarray(sv.s_first),
        {kk: jnp.asarray(vv) for kk, vv in sv.d_props.items()},
        {kk: jnp.asarray(vv) for kk, vv in sv.s_props.items()},
        {kk: jnp.asarray(
            np.asarray(view.vertex_prop(kk), np.float32).reshape(S, sv.n_loc))
         for kk in program.vertex_props},
        jnp.asarray(view.time, jnp.int64),
        jnp.asarray(wlist_p, jnp.int64),
    )
    # merge shard axis back into global vertex order: [K, S, n_loc] -> [K, n]
    result = jax.tree_util.tree_map(
        lambda a: np.asarray(a).reshape((k_pad, view.n_pad) + a.shape[3:]),
        result)
    result = jax.tree_util.tree_map(lambda a: a[:k], result)
    if not batched:
        result = jax.tree_util.tree_map(lambda a: a[0], result)
    return result, int(steps)
